"""The hierarchical aggregation agent: rank → slice leader → job view.

Every process runs one :class:`TelemetryAgent` (started by ``hvd.init``
when the launcher's HTTP-KV store is reachable). Each beacon round
(``HOROVOD_TELEMETRY_INTERVAL``), an agent:

1. publishes its own digest at ``telemetry/g<gen>/rank/<r>`` — one PUT;
2. if it is its slice's leader, reads the slice members' digests
   (slice-size GETs), merges them into one slice summary at
   ``telemetry/g<gen>/slice/<s>``;
3. if it is the job leader (the leader of the lowest live slice), reads
   every slice summary (num_slices GETs), classifies rank health
   (:mod:`horovod_tpu.telemetry.health`), and publishes the job view at
   ``telemetry/job``.

So the fan-in above slice level is ``num_slices``, not world size — the
scaling contract ``TestTelemetryScaling`` guards. A non-leader costs 2
RPCs per round (beacon PUT + one freshness probe GET).

**Leadership is leased by freshness, not configured.** The lowest rank
of a slice leads by default; every other member probes the slice
summary's age each round and, when it goes stale past ``dead_after``,
checks whether any lower-ranked member still beacons — if none does, it
takes over. An acting (non-default) leader stands down the moment a
lower-ranked member's beacon reappears. Job leadership uses the same
rule one level up, over slice summaries. Re-election therefore converges
within ~2 beacon intervals of a leader death, with no extra election
traffic in the steady state.

**Generations.** Keys are scoped by the elastic membership generation
(``HOROVOD_ELASTIC_INIT_VERSION``): rank numbering changes across a
membership change, so mixing generations would mark renumbered ranks
dead forever. The unscoped ``telemetry/job`` view always reflects the
newest generation; when a generation changes, the new job leader diffs
the previous view's host set and records hosts that vanished as ``dead``
transitions in the view's bounded event log — the "who did we lose in
that membership change" evidence the chaos soak asserts on.

The whole tick is wrapped fail-soft: a telemetry plane that can crash
the job it watches is worse than none (the chaos soak kills leaders
mid-run to prove this).
"""

import json
import os
import threading
import time

from horovod_tpu.chaos import injector as _chaos
from horovod_tpu.common.config import _env_float, _env_int
from horovod_tpu.telemetry import digest as _digest
from horovod_tpu.telemetry import health as _health

SCOPE = "telemetry"
JOB_KEY = "job"
MAX_EVENTS = 32

# Counter phases (also the metrics label values of
# ``telemetry_rpcs_total{phase}``). The first six are the AGGREGATION
# round's traffic — what the scaling contract bounds; ``read_get`` is
# demand-driven endpoint/API reads (/cluster/*, cluster_snapshot on
# non-leaders) and scales with scrape rate, so it is counted apart.
PHASES = ("beacon_put", "probe_get", "slice_get", "slice_put",
          "job_get", "job_put", "read_get")


def slice_of(rank, world, num_slices):
    """Process → slice under the rank-major near-equal partition (exact
    when ``world % num_slices == 0``, which is how multi-slice meshes are
    built — topology._build_dcn_mesh; still total otherwise so a shrunk
    elastic world keeps a working hierarchy)."""
    k = max(1, min(num_slices, world))
    return rank * k // world


def slice_members(sid, world, num_slices):
    k = max(1, min(num_slices, world))
    return [r for r in range(world) if r * k // world == sid]


def goodput_view(rows):
    """Job-level goodput aggregate from per-rank health rows: the job
    ``ratio`` is wall-weighted (a rank that lived longer weighs more),
    and each beaconing rank keeps its ratio plus the two badput numbers
    the victim-naming report reads. None until any rank reports."""
    walls = prod = 0.0
    ranks = {}
    for r, row in rows.items():
        if not row or row.get("goodput_ratio") is None:
            continue
        w = float(row.get("goodput_wall_s") or 0.0)
        ratio = float(row["goodput_ratio"])
        walls += w
        prod += ratio * w
        ranks[str(r)] = {
            "ratio": round(ratio, 6),
            "straggler_wait_s": round(
                float(row.get("straggler_wait_s") or 0.0), 6),
            "rendezvous_recovery_s": round(
                float(row.get("rendezvous_recovery_s") or 0.0), 6),
        }
    if not ranks:
        return None
    return {
        "ratio": round(prod / walls, 6) if walls > 0 else None,
        "wall_s": round(walls, 6),
        "ranks": ranks,
    }


class TelemetryAgent:
    """One process's member of the aggregation plane. ``kv`` is any
    object with the :class:`horovod_tpu.runner.http_kv.KVStoreClient`
    get/put surface (tests pass the in-process server directly);
    ``time_fn`` is injectable so the failover tests drive a fake clock.
    ``tick()`` performs one full round synchronously; ``start()`` runs
    ticks on a daemon thread every ``interval`` seconds."""

    def __init__(self, kv, rank, world, num_slices=1, interval=None,
                 dead_after=None, stall_after=None, step_lag=None,
                 seq_lag=None, gen=None, include_metrics=None,
                 time_fn=time.time):
        self.kv = kv
        self.rank = int(rank)
        self.world = int(world)
        self.num_slices = max(1, min(int(num_slices), self.world))
        self.interval = interval if interval is not None \
            else _env_float("HOROVOD_TELEMETRY_INTERVAL", 2.0)
        if dead_after is None:
            env_v = _env_float("HOROVOD_TELEMETRY_DEAD_AFTER", 0.0)
            dead_after = env_v if env_v > 0 else None
        if stall_after is None:
            env_v = _env_float("HOROVOD_TELEMETRY_STALL_AFTER", 0.0)
            stall_after = env_v if env_v > 0 else None
        self.thresholds = _health.thresholds(
            interval=self.interval,
            dead_after=dead_after,
            stall_after=stall_after,
            step_lag=step_lag if step_lag is not None
            else _env_int("HOROVOD_TELEMETRY_STEP_LAG", 5),
            seq_lag=seq_lag if seq_lag is not None
            else _env_int("HOROVOD_TELEMETRY_SEQ_LAG", 64))
        self.gen = str(gen) if gen is not None else \
            os.environ.get("HOROVOD_ELASTIC_INIT_VERSION", "0")
        self.include_metrics = include_metrics
        self.time_fn = time_fn
        self.slice = slice_of(self.rank, self.world, self.num_slices)
        self.members = slice_members(self.slice, self.world,
                                     self.num_slices)
        self.counters = dict.fromkeys(PHASES, 0)
        self.rounds = 0
        self._acting_slice_leader = False
        self._acting_job_leader = False
        self._last_digest = None
        self._last_slice_summary = None
        self._last_job_view = None
        self._events = []           # job-view transition log (leader-held)
        self._prev_states = {}
        self._inherited = False     # previous job view consulted for
        #                             this leadership tenure
        self._last_compose_t = None
        self._gen_diff_waited = 0   # compose rounds spent waiting for a
        #                             complete new-gen picture to diff
        self._thread = None
        self._stop = threading.Event()

    # --- KV plumbing ----------------------------------------------------

    def _key(self, rest):
        return f"g{self.gen}/{rest}"

    def _count(self, phase, n=1):
        self.counters[phase] += n
        try:
            from horovod_tpu.metrics import instruments as _metrics
            _metrics.record_telemetry_rpc(phase, n)
        except Exception:  # noqa: BLE001
            pass

    def _scope(self, sid=None):
        """Slice-local keys (rank beacons, slice summaries) live under a
        slice-scoped spelling so the KV resolver (KVStoreClient /
        KVStoreServer scope router) lands them on the per-slice shard
        listener when the launcher sharded the plane — beacon fan-in off
        the root store. Job-global keys (``sid=None``) stay on the
        root."""
        if sid is None:
            return SCOPE
        from horovod_tpu.common.control_plane import slice_scope
        return slice_scope(SCOPE, sid)

    def _get_json(self, key, phase, scoped=True, sid=None):
        try:
            self._count(phase)
            raw = self.kv.get(self._scope(sid),
                              self._key(key) if scoped else key)
        except Exception:  # noqa: BLE001 — a KV blip is one missed round
            return None
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except (ValueError, TypeError):
            return None

    def _put_json(self, key, obj, phase, scoped=True, sid=None):
        try:
            self._count(phase)
            self.kv.put(self._scope(sid),
                        self._key(key) if scoped else key,
                        json.dumps(obj).encode())
            return True
        except Exception:  # noqa: BLE001
            return False

    def _fresh(self, row, now):
        return row is not None and row.get("t") is not None \
            and now - row["t"] <= self.thresholds["dead_after"]

    # --- leadership -----------------------------------------------------

    def _lead_slice(self, now):
        lower = [m for m in self.members if m < self.rank]
        if not lower:
            return True
        if self._acting_slice_leader:
            # Stand down the moment any lower-ranked member is back —
            # and drop job leadership with it (job leadership is only
            # ever held BY a slice leader; a stale _acting_job_leader
            # would make job_view() serve this rank's frozen view
            # forever) plus the inherited event state (the next
            # acquisition must re-read the then-current view).
            for m in lower:
                if self._fresh(self._get_json(f"rank/{m}", "probe_get",
                                              sid=self.slice), now):
                    self._acting_slice_leader = False
                    self._acting_job_leader = False
                    self._inherited = False
                    return False
            return True
        s = self._get_json(f"slice/{self.slice}", "probe_get",
                           sid=self.slice)
        if s is not None and self._fresh(s, now):
            return False
        # Summary stale or absent: the next live member takes over.
        for m in lower:
            if self._fresh(self._get_json(f"rank/{m}", "probe_get",
                                          sid=self.slice), now):
                return False
        self._acting_slice_leader = True
        return True

    def _lead_job(self, now):
        """Called only on slice leaders: the leader of the lowest slice
        with a live summary composes the job view."""
        lower = list(range(self.slice))
        if not lower:
            return True
        if self._acting_job_leader:
            for s in lower:
                if self._fresh(self._get_json(f"slice/{s}", "probe_get",
                                              sid=s),
                               now):
                    self._acting_job_leader = False
                    self._inherited = False
                    return False
            return True
        j = self._get_json(JOB_KEY, "probe_get", scoped=False)
        if j is not None and j.get("gen") == self.gen \
                and self._fresh(j, now):
            return False
        for s in lower:
            if self._fresh(self._get_json(f"slice/{s}", "probe_get",
                                          sid=s), now):
                return False
        self._acting_job_leader = True
        return True

    # --- the round ------------------------------------------------------

    def tick(self):
        """One aggregation round. Never raises — a telemetry fault is a
        missed round, not a crashed trainer (the chaos contract)."""
        try:
            self._tick_inner()
        except Exception:  # noqa: BLE001
            try:
                from horovod_tpu.common import logging as hvd_logging
                hvd_logging.debug("telemetry tick failed", exc_info=True)
            except Exception:  # noqa: BLE001
                pass

    def _tick_inner(self):
        self.rounds += 1
        now = self.time_fn()
        if _chaos.armed:
            # Chaos site: drop/delay/crash one aggregation round — the
            # "never a crashed aggregator" contract rides the tick()
            # wrapper above this.
            _chaos.fire("telemetry.tick")
        d = _digest.collect(rank=self.rank,
                            include_metrics=self.include_metrics)
        d["t"] = round(now, 6)
        self._last_digest = d
        self._put_json(f"rank/{self.rank}", d, "beacon_put",
                       sid=self.slice)
        if self._lead_slice(now):
            summary = self._compose_slice(now)
            if summary is not None:
                self._last_slice_summary = summary
                self._put_json(f"slice/{self.slice}", summary, "slice_put",
                               sid=self.slice)
                if self._lead_job(now):
                    view = self._compose_job(now, summary)
                    if view is not None:
                        self._last_job_view = view
                        self._put_json(JOB_KEY, view, "job_put",
                                       scoped=False)

    def _compose_slice(self, now):
        rows, metrics_snaps, fresh = {}, [], 0
        for m in self.members:
            if m == self.rank:
                dig = self._last_digest      # own copy: no self-GET
            else:
                dig = self._get_json(f"rank/{m}", "slice_get",
                                     sid=self.slice)
            if dig is None:
                rows[str(m)] = None
                continue
            rows[str(m)] = _digest.health_row(dig)
            if self._fresh(dig, now):
                fresh += 1
                if dig.get("metrics"):
                    metrics_snaps.append(dig["metrics"])
        from horovod_tpu.metrics import merge as _merge
        return {
            "v": 1, "slice": self.slice, "leader": self.rank,
            "gen": self.gen, "t": round(now, 6), "world": self.world,
            "members": self.members, "digests": fresh,
            "ranks": rows,
            "metrics": _merge.merge_snapshots(metrics_snaps),
        }

    def _fetch_slice_summaries(self, own_summary=None, phase="job_get"):
        """All slice summaries, using the local copy for our own slice.
        The job-level fan-in: ``num_slices - 1`` GETs. Demand-driven
        callers (endpoints) pass ``phase="read_get"`` so the aggregation
        round's scaling counters stay uncontaminated by scrape traffic."""
        out = {}
        for s in range(self.num_slices):
            if own_summary is not None and s == self.slice:
                out[s] = own_summary
            else:
                out[s] = self._get_json(f"slice/{s}", phase, sid=s)
        return out

    def _inherit_previous_view(self):
        """Once per leadership acquisition: pull the previous job view to
        carry its event log forward and, across a generation change, mark
        the hosts that vanished from the membership as dead transitions —
        the age-based detector can't see them (their beacons died with
        the old generation's key space)."""
        prev = self._get_json(JOB_KEY, "probe_get", scoped=False)
        self._inherited = True
        if prev is None:
            return
        self._events = list(prev.get("events") or [])[-MAX_EVENTS:]
        self._prev_states = {
            r: s.get("state") for r, s in (prev.get("health") or {}).items()
        } if prev.get("gen") == self.gen else {}
        if prev.get("gen") != self.gen:
            # The new membership's hosts are resolved in _compose_job
            # (we may not have seen every beacon yet); stash the old
            # rank → host map for the diff there.
            self._prev_gen_hosts = {
                r: s.get("host")
                for r, s in (prev.get("health") or {}).items()
                if s.get("host")}
            self._prev_gen = prev.get("gen")

    def _record_transitions(self, states, now, slice_summaries):
        for r, s in states.items():
            prev = self._prev_states.get(r)
            if prev is not None and prev != s["state"]:
                self._events.append({
                    "t": round(now, 6), "gen": self.gen, "rank": int(r),
                    "from": prev, "to": s["state"],
                    "why": s.get("why"), "age_s": s.get("age_s"),
                    "host": s.get("host")})
            self._prev_states[r] = s["state"]
        # Generation diff: hosts that existed in the previous generation's
        # view but are absent from this membership were removed/killed.
        # Deferred until every new-generation rank has beaconed (bounded
        # by a few rounds) — diffing against a half-assembled membership
        # would mark not-yet-started survivors as removed.
        prev_hosts = getattr(self, "_prev_gen_hosts", None)
        if prev_hosts:
            live_hosts, seen_ranks = set(), 0
            for summ in slice_summaries.values():
                for row in (summ or {}).get("ranks", {}).values():
                    if row and row.get("host"):
                        live_hosts.add(row["host"])
                        seen_ranks += 1
            if seen_ranks >= self.world or self._gen_diff_waited >= 5:
                for old_rank, host in sorted(prev_hosts.items()):
                    if host not in live_hosts:
                        self._events.append({
                            "t": round(now, 6), "gen": self.gen,
                            "rank": int(old_rank), "host": host,
                            "from": "healthy", "to": "dead",
                            "why": "membership_removed",
                            "prev_gen": getattr(self, "_prev_gen", None)})
                self._prev_gen_hosts = None
            else:
                self._gen_diff_waited += 1
        self._trim_events()

    def _trim_events(self):
        """Bound the event log, but never evict ``membership_removed``
        entries in favor of churn: a dead↔healthy flap storm (loaded
        hosts near the dead_after boundary) must not flush the one event
        that says which host the job actually lost."""
        overflow = len(self._events) - MAX_EVENTS
        if overflow <= 0:
            return
        pruned = []
        for e in self._events:
            if overflow > 0 and e.get("why") != "membership_removed":
                overflow -= 1
                continue
            pruned.append(e)
        self._events = pruned[-MAX_EVENTS:]

    def _compose_job(self, now, own_summary):
        # Re-inherit after a composing gap: a default leader paused past
        # the dead window (GC stall, machine wedge) may have been
        # substituted by an acting leader — resuming with the pre-pause
        # event log would overwrite the interim leader's transitions.
        if self._last_compose_t is not None and \
                now - self._last_compose_t > self.thresholds["dead_after"]:
            self._inherited = False
        if not self._inherited:
            self._inherit_previous_view()
        self._last_compose_t = now
        summaries = self._fetch_slice_summaries(own_summary)
        rows, slices_meta = {}, {}
        for sid, summ in summaries.items():
            if summ is None:
                slices_meta[str(sid)] = {
                    "t": None, "leader": None, "digests": 0,
                    "members": slice_members(sid, self.world,
                                             self.num_slices)}
                for m in slice_members(sid, self.world, self.num_slices):
                    rows[m] = None
                continue
            slices_meta[str(sid)] = {
                "t": summ.get("t"), "leader": summ.get("leader"),
                "digests": summ.get("digests", 0),
                "age_s": round(now - summ["t"], 3)
                if summ.get("t") else None,
                "members": summ.get("members", [])}
            for r_str, row in summ.get("ranks", {}).items():
                rows[int(r_str)] = row
        # Every rank of the world appears, beaconed or not.
        for r in range(self.world):
            rows.setdefault(r, None)
        states, progress = _health.classify(rows, now, self.thresholds)
        self._record_transitions({str(r): s for r, s in states.items()},
                                 now, summaries)
        # Feed this rank's own stall verdict back into its goodput
        # ledger: a "stalled" classification flips the phase to
        # wedge_idle, "healthy" flips it back (a completed step always
        # overrides both — see goodput/ledger.note_wedge).
        try:
            from horovod_tpu.goodput import ledger as _goodput
            _goodput.wedge_from_rows(
                [{"rank": r, "state": s["state"]}
                 for r, s in states.items()], self.rank)
        except Exception:  # noqa: BLE001
            pass
        return {
            "v": 1, "t": round(now, 6), "gen": self.gen,
            "leader": self.rank, "leader_slice": self.slice,
            "world": self.world, "num_slices": self.num_slices,
            "interval_s": self.interval,
            "thresholds": self.thresholds,
            "slices": slices_meta,
            "health": {str(r): states[r] for r in sorted(states)},
            "counts": _health.counts(states),
            "progress": progress,
            "goodput": goodput_view(rows),
            "events": list(self._events),
        }

    # --- reads ----------------------------------------------------------

    def job_view(self):
        """The freshest job view this process can produce: the local copy
        when we lead AND it is recent, else one KV GET (counted as
        ``read_get`` — demand traffic, not aggregation traffic). None
        when nothing published yet."""
        local = self._last_job_view
        if local is not None and (
                self._acting_job_leader or
                (self.slice == 0 and self.rank == self.members[0])):
            t = local.get("t")
            if t is not None and \
                    self.time_fn() - t <= self.thresholds["dead_after"]:
                return local
        return self._get_json(JOB_KEY, "read_get", scoped=False)

    def slice_summaries(self):
        """Every slice's latest summary (the ``/cluster/metrics`` /
        ``/cluster/steps`` composition input; counted as ``read_get``).
        The local copy is used only while FRESH — a leader whose beacon
        thread wedged must serve its successor's KV summary, not its own
        frozen one (the same guard as job_view())."""
        own = None
        local = self._last_slice_summary
        if local is not None and (
                self._acting_slice_leader
                or self.rank == self.members[0]):
            t = local.get("t")
            if t is not None and \
                    self.time_fn() - t <= self.thresholds["dead_after"]:
                own = local
        return self._fetch_slice_summaries(own, phase="read_get")

    # --- lifecycle ------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            # Jittered phase so a synchronized fleet doesn't thundering-
            # herd the KV store at each interval boundary.
            import random
            self._stop.wait(random.random() * self.interval)
            while not self._stop.is_set():
                self.tick()
                self._stop.wait(self.interval)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="hvd-telemetry")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


# --- process-global agent (wired by basics.init / shutdown) ---------------

_agent = None
_agent_lock = threading.Lock()


def get_agent():
    return _agent


def set_agent(agent):
    """Install (tests) or replace the process-global agent."""
    global _agent
    with _agent_lock:
        prev, _agent = _agent, agent
    if prev is not None and prev is not agent:
        prev.stop()
    return agent


def start_from_config(config, topology=None):
    """Start the process-global agent from a Config + resolved topology
    (called by ``hvd.init``). No-ops (returns None) when telemetry is
    off, the launcher KV is unreachable, or the world is one process —
    ``cluster_snapshot()`` then serves the local-only view."""
    import jax
    if not getattr(config, "telemetry", True):
        return None
    addr = os.environ.get("HOROVOD_KV_ADDR")
    port = os.environ.get("HOROVOD_KV_PORT")
    try:
        world = jax.process_count()
    except Exception:  # noqa: BLE001
        world = 1
    if not addr or not port or world <= 1:
        return None
    from horovod_tpu.runner.http_kv import KVStoreClient
    # Short timeout: a wedged KV must cost a beacon round, not block the
    # thread for the default 30 s request timeout.
    kv = KVStoreClient(addr, int(port), timeout=5)
    num_slices = getattr(topology, "num_slices", 1) if topology is not None \
        else 1
    # A forced HOROVOD_MESH_SLICES keeps the telemetry hierarchy even
    # when the DEVICE mesh factorization collapsed (topology requires
    # size % k == 0; an elastic shrink 8→7 breaks that) — telemetry
    # slices are process groupings and the rank-major near-equal
    # partition (slice_of) is total for any world size.
    forced = _env_int("HOROVOD_MESH_SLICES", 0)
    if forced > 1:
        num_slices = forced
    try:
        rank = jax.process_index()
    except Exception:  # noqa: BLE001
        rank = _env_int("HOROVOD_CROSS_RANK", 0)
    agent = TelemetryAgent(
        kv, rank=rank, world=world, num_slices=num_slices,
        interval=config.telemetry_interval,
        dead_after=config.telemetry_dead_after or None,
        stall_after=config.telemetry_stall_after or None,
        step_lag=config.telemetry_step_lag,
        seq_lag=config.telemetry_seq_lag,
        include_metrics=config.telemetry_metrics)
    return set_agent(agent).start()


def stop():
    set_agent(None)


def _local_view():
    """Single-process / no-KV fallback: the job view composed from this
    process's own digest — ``cluster_snapshot()`` is never empty."""
    now = time.time()
    d = _digest.collect()
    row = _digest.health_row(d)
    states, progress = _health.classify({d["rank"]: row}, now,
                                        _health.thresholds())
    return {
        "v": 1, "t": round(now, 6), "gen": "local", "leader": d["rank"],
        "world": 1, "num_slices": 1, "local_only": True,
        "slices": {"0": {"t": round(now, 6), "leader": d["rank"],
                         "digests": 1, "members": [d["rank"]]}},
        "health": {str(r): s for r, s in states.items()},
        "counts": _health.counts(states),
        "progress": progress,
        "goodput": goodput_view({d["rank"]: row}),
        "events": [],
    }


def cluster_snapshot():
    """The job-level cluster view: per-rank health states, per-slice
    digest counts, job step progress, and the bounded state-transition
    event log (``hvd.cluster_snapshot()``; schema in
    docs/observability.md). Falls back to a local-only view when no
    aggregation plane is running — never returns None."""
    agent = _agent
    if agent is not None:
        view = agent.job_view()
        if view is not None:
            return view
    return _local_view()


def cluster_steps():
    """Per-rank step progress (the ``/cluster/steps`` payload): rank →
    {step, step_t, wall_mean_s, host_dispatch_mean_s} + job medians."""
    agent = _agent
    out = {"ranks": {}, "progress": {}}
    if agent is None:
        d = _digest.collect()
        row = _digest.health_row(d)
        out["ranks"][str(d["rank"])] = {
            k: row.get(k) for k in ("step", "step_t", "wall_mean_s",
                                    "host_dispatch_mean_s", "steps")}
        if row.get("step") is not None:
            out["progress"] = {"median_step": row["step"]}
        return out
    now = agent.time_fn()
    rows = {}
    for summ in agent.slice_summaries().values():
        for r_str, row in (summ or {}).get("ranks", {}).items():
            if row is None:
                continue
            rows[int(r_str)] = row
            out["ranks"][r_str] = {
                k: row.get(k) for k in ("step", "step_t", "wall_mean_s",
                                        "host_dispatch_mean_s", "steps")}
    out["progress"] = _health.job_progress(rows, now, agent.thresholds)
    return out


def cluster_metrics_text():
    """Job-aggregated Prometheus exposition (the ``/cluster/metrics``
    payload): every slice's merged snapshot stamped with its ``slice``
    label, then merged — counters sum within a slice and stay
    distinguishable across slices."""
    from horovod_tpu.metrics import merge as _merge
    from horovod_tpu.metrics.instruments import REGISTRY
    agent = _agent
    if agent is None:
        snap = _merge.add_labels(_merge.compact(REGISTRY.snapshot()),
                                 slice="0")
        return _merge.render_text(snap, prefix=REGISTRY.prefix)
    labelled = []
    for sid, summ in agent.slice_summaries().items():
        m = (summ or {}).get("metrics")
        if m:
            labelled.append(_merge.add_labels(m, slice=sid))
    merged = _merge.merge_snapshots(labelled)
    return _merge.render_text(merged, prefix=REGISTRY.prefix)
