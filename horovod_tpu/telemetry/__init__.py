"""Hierarchical cluster telemetry plane.

PRs 1/5/6 built rich *per-rank* observability (metrics registry, flight
recorder, step profiler); this package is the plane that composes it into
one *job-level* answer to "is this job healthy, and which slice/rank is
the problem?" — without the O(world) scrape-every-rank pattern that
"Collective Communication for 100k+ GPUs" (PAPERS.md: arxiv 2510.20171)
identifies as what breaks at scale, using the per-slice hierarchy the
MLPerf TPU-pod study (arxiv 1909.09756) applies to pods:

- every rank periodically publishes a compact **digest** (liveness
  beacon, current step + attribution means, flight-recorder anomaly
  counts, watchdog findings, mergeable metrics snapshot) to the runner
  HTTP-KV store (:mod:`horovod_tpu.telemetry.digest`);
- the **slice leader** merges its slice's digests into one slice summary
  (:mod:`horovod_tpu.telemetry.aggregator`), so the fan-in above slice
  level scales with *slice count*, not world size;
- the **job leader** (lowest live slice's leader) composes the slice
  summaries into the job view — per-rank health states
  (healthy / straggling / desynced / stalled / dead,
  :mod:`horovod_tpu.telemetry.health`), job step medians, and a bounded
  state-transition event log.

Read it via ``hvd.cluster_snapshot()``, the ``GET /cluster/health`` /
``/cluster/metrics`` / ``/cluster/steps`` endpoints on the metrics
server, or the live terminal view ``python -m horovod_tpu.telemetry.top``.
Leadership is leased by freshness, not configured: a leader that stops
beaconing is replaced by the next live rank within a couple of beacon
intervals (see ``aggregator.TelemetryAgent``). Knobs:
``HOROVOD_TELEMETRY`` (default on), ``HOROVOD_TELEMETRY_INTERVAL``, and
the health thresholds in :class:`horovod_tpu.common.config.Config`;
docs/observability.md has the full catalogue.
"""

from horovod_tpu.telemetry.aggregator import (  # noqa: F401
    TelemetryAgent, cluster_snapshot, get_agent, start_from_config, stop,
)
from horovod_tpu.telemetry import digest, health  # noqa: F401
