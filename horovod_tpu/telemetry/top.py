"""``python -m horovod_tpu.telemetry.top`` — live cluster terminal view.

The operator's first stop when a job looks wedged: one screen answering
"is this job healthy, and which slice/rank is the problem?" from the
job view the telemetry plane already maintains — no per-rank scraping.

Two sources, in precedence order:

- ``--url http://host:port`` — a metrics endpoint (any rank's); reads
  ``GET /cluster/health`` + ``/cluster/steps``.
- ``--kv host:port`` — the launcher HTTP-KV store directly (works even
  when no metrics endpoint was armed); reads the ``telemetry/job`` key.

``--once`` prints a single frame and exits 0 when every rank is healthy,
1 otherwise (scriptable health gate); the default loop redraws every
``--interval`` seconds until Ctrl-C.
"""

import argparse
import json
import sys
import time

_STATE_GLYPH = {
    "healthy": ".", "straggling": "~", "desynced": "#",
    "stalled": "!", "dead": "X",
}


def _fetch_url(base):
    from urllib import request as urlrequest
    with urlrequest.urlopen(base.rstrip("/") + "/cluster/health",
                            timeout=5) as r:
        return json.loads(r.read())


def _fetch_kv(addr_port):
    from horovod_tpu.runner.http_kv import KVStoreClient
    addr, port = addr_port.rsplit(":", 1)
    raw = KVStoreClient(addr, int(port), timeout=5).get("telemetry", "job")
    return json.loads(raw) if raw is not None else None


def _fetch_serving(base):
    from urllib import request as urlrequest
    with urlrequest.urlopen(base.rstrip("/") + "/serving/health",
                            timeout=5) as r:
        return json.loads(r.read())


def _age(now, t):
    return f"{now - t:5.1f}s" if t else "    ?"


def gate(view, now=None):
    """The ``--once`` exit gate: True iff the view exists, is FRESH, and
    every rank is healthy. Freshness matters as much as the states — a
    dead job stops publishing, leaving its last (often all-healthy) view
    in the KV; a gate that ignored age would green-light a crashed
    cluster. The bound is the view's own dead_after + one interval of
    publish slack."""
    if view is None:
        return False
    now = now if now is not None else time.time()
    t = view.get("t")
    dead_after = (view.get("thresholds") or {}).get("dead_after") \
        or 3.0 * view.get("interval_s", 2.0)
    if t is None or now - t > dead_after + view.get("interval_s", 2.0):
        return False
    health = view.get("health") or {}
    return bool(health) and all(s.get("state") == "healthy"
                                for s in health.values())


def serving_ready(snap):
    """The serving half of the readiness gate (``--once --serving``):
    True iff a serving engine answered AND it can absorb traffic — the
    admission queue is below its declared limit and the slot caches are
    live (a post-disruption engine whose caches are still stale must not
    take load-balancer traffic yet). Pure so tests drive it with
    synthetic frames."""
    if not snap or snap.get("error"):
        return False
    if snap.get("saturated"):
        return False
    return bool(snap.get("cache_valid", True))


def render_serving(snap):
    """One-line serving frame appended under the cluster view."""
    if not snap or snap.get("error"):
        return "serving: no engine answered"
    # Declared-SLO burn column (absent when no HOROVOD_SLO_* objective
    # is set): burn >= 1 means the error budget is being consumed at or
    # beyond its sustainable rate — flagged so the one-shot gate output
    # is greppable.
    slo = snap.get("slo") or {}
    burn = "".join(f"  burn[{obj}]={b:.2f}" + ("!" if b >= 1.0 else "")
                   for obj, b in sorted(slo.items()))
    return (f"serving: {snap.get('active', 0)}/{snap.get('slots', '?')} "
            f"slots  queue={snap.get('queue_depth', 0)}"
            + (f"/{snap['queue_limit']}" if snap.get("queue_limit") else "")
            + f"  served={snap.get('served', 0)}"
            f"  fill={snap.get('fill_ratio', 0.0):.2f}"
            + burn
            + ("  SATURATED" if snap.get("saturated") else "")
            + ("" if snap.get("cache_valid", True) else "  CACHE-STALE"))


def render(view, now=None):
    """One frame of the live view as a string (pure: tested directly)."""
    if view is None:
        return "no job view published yet (is the telemetry plane armed?)"
    now = now if now is not None else time.time()
    counts = view.get("counts", {})
    lines = []
    lines.append(
        f"job view g{view.get('gen')}  world={view.get('world')}  "
        f"slices={view.get('num_slices')}  leader=r{view.get('leader')}  "
        f"age={_age(now, view.get('t'))}")
    progress = view.get("progress") or {}
    if "median_step" in progress:
        lines.append(
            f"steps: median={progress['median_step']} "
            f"min={progress.get('min_step')} "
            f"max={progress.get('max_step')}")
    gp = view.get("goodput") or {}
    if gp.get("ratio") is not None:
        worst = None
        for r, d in (gp.get("ranks") or {}).items():
            ratio = (d or {}).get("ratio")
            if ratio is not None and (worst is None or ratio < worst[1]):
                worst = (r, ratio)
        lines.append(
            f"goodput: {gp['ratio']:.1%}"
            + (f"  worst=r{worst[0]} ({worst[1]:.1%})" if worst else ""))
    lines.append("health: " + "  ".join(
        f"{s}={counts.get(s, 0)}" for s in
        ("healthy", "straggling", "desynced", "stalled", "dead")))
    # Rank strip: one glyph per rank, grouped by slice.
    health = view.get("health") or {}
    slices = view.get("slices") or {}
    for sid in sorted(slices, key=int):
        meta = slices[sid] or {}
        members = meta.get("members") or []
        strip = "".join(_STATE_GLYPH.get(
            (health.get(str(r)) or {}).get("state", "dead"), "?")
            for r in members)
        lines.append(
            f"  slice {sid} [leader r{meta.get('leader')}, "
            f"{meta.get('digests', 0)}/{len(members)} digests, "
            f"age {_age(now, meta.get('t'))}]  {strip}")
    bad = {r: s for r, s in health.items()
           if s.get("state") != "healthy"}
    for r in sorted(bad, key=int)[:16]:
        s = bad[r]
        lines.append(
            f"  r{r}: {s['state']} ({s.get('why', '?')}"
            + (f", step {s.get('step')}" if s.get("step") is not None
               else "")
            + (f", age {s['age_s']}s" if s.get("age_s") is not None
               else "") + ")")
    events = (view.get("events") or [])[-6:]
    if events:
        lines.append("recent transitions:")
        for e in events:
            why = f"{e['why']}, " if e.get("why") else ""
            lines.append(
                f"  r{e.get('rank')} {e.get('from')}→{e.get('to')} "
                f"({why}g{e.get('gen')})")
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.telemetry.top",
        description="Live cluster health view from the telemetry plane.")
    p.add_argument("--url", help="a metrics endpoint base URL "
                                 "(http://host:port)")
    p.add_argument("--kv", help="the launcher KV store (host:port; "
                                "HOROVOD_KV_ADDR/PORT)")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--once", action="store_true",
                   help="print one frame; exit 0 iff all ranks healthy")
    p.add_argument("--serving", action="store_true",
                   help="additionally account serving health (the "
                        "/serving/health frame of --url): the --once "
                        "gate then also requires an unsaturated engine "
                        "with live caches — the load-balancer readiness "
                        "probe (docs/inference.md). Requires --url.")
    args = p.parse_args(argv)
    if args.serving and not args.url:
        p.error("--serving reads /serving/health and needs --url")
    if not args.url and not args.kv:
        import os
        addr, port = os.environ.get("HOROVOD_KV_ADDR"), \
            os.environ.get("HOROVOD_KV_PORT")
        if addr and port:
            args.kv = f"{addr}:{port}"
        else:
            p.error("need --url or --kv (or HOROVOD_KV_ADDR/PORT)")

    def fetch():
        try:
            return _fetch_url(args.url) if args.url \
                else _fetch_kv(args.kv)
        except Exception as e:  # noqa: BLE001 — keep the view alive
            print(f"fetch failed: {e}", file=sys.stderr)
            return None

    def fetch_serving():
        if not args.serving:
            return None
        try:
            return _fetch_serving(args.url)
        except Exception as e:  # noqa: BLE001 — a dead engine = not ready
            print(f"serving fetch failed: {e}", file=sys.stderr)
            return None

    if args.once:
        view = fetch()
        print(render(view))
        ok = gate(view)
        if args.serving:
            snap = fetch_serving()
            print(render_serving(snap))
            ok = ok and serving_ready(snap)
        if not ok and view is not None \
                and all(s.get("state") == "healthy"
                        for s in (view.get("health") or {}).values()):
            print("gate: job view is STALE — the plane (or the whole "
                  "job) stopped publishing", file=sys.stderr)
        return 0 if ok else 1
    try:
        while True:
            frame = render(fetch())
            if args.serving:
                frame += "\n" + render_serving(fetch_serving())
            # Clear + home, like watch(1); plain newline when not a tty.
            if sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")
            print(frame, flush=True)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
