"""The job health model: per-rank states from merged slice summaries.

States, most-severe first (one per rank, the first matching rule wins):

- ``dead``       — beacon missing or older than ``dead_after``. A rank
  that never beaconed at all is dead with ``why="never_reported"``.
- ``stalled``    — beacon fresh (the process is alive) but its step clock
  stopped for ``stall_after`` while the job median advanced past it: the
  classic wedged-in-a-collective signature.
- ``desynced``   — alive and stepping, but its global-process-set
  collective sequence number lags the fleet median by more than
  ``seq_lag``: it is issuing different/fewer collectives than its peers
  (the flight recorder's cross-rank desync key, surfaced live).
- ``straggling`` — step count lags the job median by more than
  ``step_lag``, or the step-profiler watchdog recently named it.
- ``healthy``    — everything else, including ranks with no step data at
  all (not every process runs a marked training loop).

Thresholds come from :class:`horovod_tpu.common.config.Config`
(``HOROVOD_TELEMETRY_*``); the defaults are deliberately conservative —
a health plane that cries wolf gets ignored. All classification is pure
(rows + now + thresholds in, states out) so the fast tier-1 tests drive
it with synthetic rows and a fake clock.
"""

STATES = ("healthy", "straggling", "desynced", "stalled", "dead")

# The flight recorder's global process set key in max_seq maps.
_GLOBAL_PS = "global"


def _median(xs):
    import statistics
    return statistics.median(xs)


def thresholds(interval=2.0, dead_after=None, stall_after=None,
               step_lag=None, seq_lag=None):
    """Resolve the health thresholds from an interval + explicit
    overrides (the aggregator feeds Config/env values through here).
    The derived ``dead_after`` is floored at 1.5 s: beacon threads on a
    loaded host routinely slip hundreds of ms, and a sub-second liveness
    window makes every rank flap dead↔healthy (observed on the 2-core
    CI box at interval=0.1) — an explicit override can still go lower."""
    return {
        "dead_after": dead_after if dead_after is not None
        else max(3.0 * interval, 1.5),
        "stall_after": stall_after if stall_after is not None
        else max(15.0 * interval, 30.0),
        "step_lag": step_lag if step_lag is not None else 5,
        "seq_lag": seq_lag if seq_lag is not None else 64,
    }


def job_progress(rows, now, thr):
    """Fleet step/seq medians over LIVE rows (dead ranks must not drag
    the median toward their frozen counters)."""
    steps, seqs = [], []
    for row in rows.values():
        if row is None or row.get("t") is None:
            continue
        if now - row["t"] > thr["dead_after"]:
            continue
        if row.get("step") is not None:
            steps.append(row["step"])
        seq = (row.get("max_seq") or {}).get(_GLOBAL_PS)
        if seq is not None:
            seqs.append(seq)
    out = {}
    if steps:
        out["median_step"] = _median(steps)
        out["min_step"] = min(steps)
        out["max_step"] = max(steps)
    if seqs:
        out["median_seq"] = _median(seqs)
    return out


def _recent_straggler_namings(rows):
    """rank -> times the watchdog named it a straggler in any live rank's
    recent findings (cross-rank corroboration rides along for free: every
    observer publishes its own findings list)."""
    named = {}
    for row in rows.values():
        if row is None:
            continue
        for f in row.get("findings") or ():
            if f.get("kind") == "straggler" and f.get("rank") is not None:
                named[f["rank"]] = named.get(f["rank"], 0) + 1
    return named


def classify(rows, now, thr):
    """``rows``: {rank(int) -> health_row dict or None (never beaconed)}.
    Returns ({rank -> {"state", "why", ...}}, job_progress_dict)."""
    progress = job_progress(rows, now, thr)
    named = _recent_straggler_namings(rows)
    median_step = progress.get("median_step")
    median_seq = progress.get("median_seq")
    out = {}
    for rank, row in rows.items():
        out[rank] = _classify_one(rank, row, now, thr, median_step,
                                  median_seq, named)
    return out, progress


def _classify_one(rank, row, now, thr, median_step, median_seq, named):
    if row is None or row.get("t") is None:
        return {"state": "dead", "why": "never_reported"}
    age = now - row["t"]
    if age > thr["dead_after"]:
        return {"state": "dead", "why": "beacon_stale",
                "age_s": round(age, 3), "host": row.get("host"),
                "step": row.get("step")}
    info = {"age_s": round(age, 3), "step": row.get("step"),
            "host": row.get("host")}
    step, step_t = row.get("step"), row.get("step_t")
    if step is not None and step_t is not None and median_step is not None \
            and median_step > step and now - step_t > thr["stall_after"]:
        return {"state": "stalled", "why": "step_clock_stopped",
                "stalled_s": round(now - step_t, 3), **info}
    seq = (row.get("max_seq") or {}).get(_GLOBAL_PS)
    if seq is not None and median_seq is not None \
            and median_seq - seq > thr["seq_lag"]:
        return {"state": "desynced", "why": "collective_seq_lag",
                "seq": seq, "median_seq": median_seq, **info}
    if step is not None and median_step is not None \
            and median_step - step > thr["step_lag"]:
        return {"state": "straggling", "why": "step_lag",
                "median_step": median_step, **info}
    if named.get(rank):
        return {"state": "straggling", "why": "watchdog_named",
                "namings": named[rank], **info}
    return {"state": "healthy", **info}


def counts(states):
    """{state: n} over a classify() result, every state present."""
    out = dict.fromkeys(STATES, 0)
    for s in states.values():
        out[s["state"]] = out.get(s["state"], 0) + 1
    return out
