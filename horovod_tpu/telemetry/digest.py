"""Per-rank telemetry digest: what one process tells its slice leader.

A digest is the compact, JSON-serializable beacon each process publishes
every ``HOROVOD_TELEMETRY_INTERVAL`` seconds — the *only* thing a rank
contributes to the cluster view, so everything the job health model needs
must be in it:

- liveness: ``t`` (publish wall time) — beacon age IS the liveness signal;
- progress: current step + when it closed, recent wall/attribution means
  (step-profiler ledger digest — the step-lag/stall/straggler inputs);
- anomalies: flight-recorder anomaly counts + per-process-set max
  collective seq (the desync key);
- findings: the watchdog's recent straggler/regression namings;
- metrics: a mergeable compacted registry snapshot
  (``HOROVOD_TELEMETRY_METRICS=0`` drops it for minimal beacons).

Collection runs on the beacon thread, off every dispatch hot path; each
contributor is independently fail-soft (a wedged subsystem must not
silence the liveness beacon that reports it wedged).
"""

import os
import time

from horovod_tpu.common.config import _env_bool, _env_int

SCHEMA_VERSION = 1


def _rank():
    return _env_int("HOROVOD_CROSS_RANK", 0)


def _host():
    h = os.environ.get("HOROVOD_HOST_KEY")
    if h:
        return h
    import socket
    try:
        return socket.gethostname()
    except OSError:
        return ""


def collect(rank=None, include_metrics=None):
    """Build this process's digest. Never raises: each contributing
    subsystem is wrapped separately so the beacon survives any of them
    misbehaving — a beacon with a missing section still proves liveness."""
    d = {
        "v": SCHEMA_VERSION,
        "rank": _rank() if rank is None else rank,
        "host": _host(),
        "pid": os.getpid(),
        "t": round(time.time(), 6),
    }
    try:
        from horovod_tpu.profile import ledger
        d["profile"] = ledger.digest()
    except Exception:  # noqa: BLE001 — beacon survives a wedged ledger
        pass
    try:
        from horovod_tpu.flight import recorder
        d["flight"] = recorder.digest()
    except Exception:  # noqa: BLE001
        pass
    try:
        from horovod_tpu.profile import watchdog
        d["findings"] = watchdog.findings(last=4)
    except Exception:  # noqa: BLE001
        pass
    try:
        from horovod_tpu.goodput import ledger as goodput_ledger
        snap = goodput_ledger.snapshot()
        if snap.get("enabled"):
            d["goodput"] = snap
    except Exception:  # noqa: BLE001
        pass
    if include_metrics is None:
        include_metrics = _env_bool("HOROVOD_TELEMETRY_METRICS", True)
    if include_metrics:
        try:
            from horovod_tpu.metrics import merge
            from horovod_tpu.metrics.instruments import REGISTRY, enabled
            if enabled():
                d["metrics"] = merge.compact(REGISTRY.snapshot())
        except Exception:  # noqa: BLE001
            pass
    return d


def health_row(digest_dict):
    """The slice-summary per-rank row: the digest minus its metrics bulk
    (metrics are merged INTO the slice summary, not repeated per rank),
    keeping exactly the health-model inputs + identity."""
    prof = digest_dict.get("profile") or {}
    flight = digest_dict.get("flight") or {}
    row = {
        "t": digest_dict.get("t"),
        "host": digest_dict.get("host"),
        "pid": digest_dict.get("pid"),
        "step": prof.get("step"),
        "step_t": prof.get("step_t"),
        "steps": prof.get("steps", 0),
        "wall_mean_s": prof.get("wall_mean_s"),
        "host_dispatch_mean_s": (prof.get("attribution_mean_s") or {})
        .get("host_dispatch"),
        "anomalies": flight.get("anomalies", 0),
        "anomaly_kinds": flight.get("by_kind") or {},
        "max_seq": flight.get("max_seq") or {},
        "findings": digest_dict.get("findings") or [],
    }
    gp = digest_dict.get("goodput") or {}
    if gp.get("enabled"):
        cats = gp.get("categories") or {}
        row["goodput_ratio"] = gp.get("goodput_ratio")
        row["goodput_wall_s"] = gp.get("wall_s")
        # The two per-rank badput numbers the victim-naming report (and
        # the chaos-soak brackets) need; the full decomposition stays in
        # the digest, not every row.
        row["straggler_wait_s"] = cats.get("straggler_wait", 0.0)
        row["rendezvous_recovery_s"] = cats.get("rendezvous_recovery", 0.0)
    return row
