"""Bayesian optimization with expected improvement.

Reference: horovod/common/optim/bayesian_optimization.cc/.h (308 LoC) —
EI acquisition over sampled test points, driven by the GP regressor.
"""

import numpy as np
from scipy.stats import norm

from horovod_tpu.autotune.gaussian_process import GaussianProcessRegressor


class BayesianOptimization:
    def __init__(self, bounds, alpha=1e-8, xi=0.01, seed=0):
        """``bounds``: array (d, 2) of [low, high] per dimension
        (reference: BayesianOptimization ctor with test points)."""
        self.bounds = np.asarray(bounds, float)
        self.xi = xi
        self.gp = GaussianProcessRegressor(alpha=alpha)
        self.x_samples = []
        self.y_samples = []
        self._rng = np.random.default_rng(seed)

    def add_sample(self, x, y):
        """reference: AddSample — record an observed objective value."""
        self.x_samples.append(np.atleast_1d(np.asarray(x, float)))
        self.y_samples.append(float(y))

    def expected_improvement(self, x):
        """reference: ExpectedImprovement."""
        mu, sigma = self.gp.predict(x)
        best = np.max(self.y_samples)
        imp = mu - best - self.xi
        z = np.where(sigma > 0, imp / sigma, 0.0)
        ei = imp * norm.cdf(z) + sigma * norm.pdf(z)
        return np.where(sigma > 0, ei, 0.0)

    def next_sample(self, n_candidates=256):
        """Fit the GP and return the EI-argmax candidate
        (reference: NextSample with random restarts)."""
        d = len(self.bounds)
        if not self.x_samples:
            return self.bounds[:, 0] + self._rng.random(d) * (
                self.bounds[:, 1] - self.bounds[:, 0])
        self.gp.fit(np.stack(self.x_samples), np.asarray(self.y_samples))
        cands = self.bounds[:, 0] + self._rng.random((n_candidates, d)) * (
            self.bounds[:, 1] - self.bounds[:, 0])
        ei = self.expected_improvement(cands)
        return cands[int(np.argmax(ei))]
