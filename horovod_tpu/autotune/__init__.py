from horovod_tpu.autotune.parameter_manager import (  # noqa: F401
    ParameterManager, sweep_categoricals,
)
