from horovod_tpu.autotune.parameter_manager import ParameterManager  # noqa: F401
