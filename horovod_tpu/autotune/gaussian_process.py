"""Gaussian-process regression for the autotuner.

Reference: horovod/common/optim/gaussian_process.cc/.h (300 LoC, Eigen +
LBFGS hyperparameter fitting). Same model — RBF kernel GP with noise, fitted
by maximizing the log marginal likelihood — expressed in numpy/scipy, which is
the idiomatic host-side tool here (the autotuner runs on the Python control
plane; there is no reason for C++).
"""

import numpy as np
from scipy.linalg import cho_factor, cho_solve
from scipy.optimize import minimize


class GaussianProcessRegressor:
    """RBF-kernel GP with observation noise
    (reference: gaussian_process.h GaussianProcessRegressor)."""

    def __init__(self, alpha=1e-8):
        self.alpha = alpha
        self.length = 1.0
        self.sigma_f = 1.0
        self.x_train = None
        self.y_train = None

    def kernel(self, x1, x2, length=None, sigma_f=None):
        length = self.length if length is None else length
        sigma_f = self.sigma_f if sigma_f is None else sigma_f
        sq = np.sum(x1 ** 2, 1)[:, None] + np.sum(x2 ** 2, 1)[None] \
            - 2 * x1 @ x2.T
        return sigma_f ** 2 * np.exp(-0.5 * np.maximum(sq, 0) / length ** 2)

    def fit(self, x, y):
        self.x_train = np.atleast_2d(np.asarray(x, float))
        self.y_train = np.asarray(y, float).reshape(-1, 1)

        def nll(theta):
            length, sigma_f = np.exp(theta)
            k = self.kernel(self.x_train, self.x_train, length, sigma_f)
            k = k + self.alpha * np.eye(len(self.x_train))
            try:
                c, low = cho_factor(k + 1e-10 * np.eye(len(k)))
            except np.linalg.LinAlgError:
                return 1e25
            a = cho_solve((c, low), self.y_train)
            return (
                0.5 * float((self.y_train.T @ a)[0, 0])
                + float(np.sum(np.log(np.abs(np.diag(c)))))
                + 0.5 * len(k) * np.log(2 * np.pi))

        best = None
        # multi-start L-BFGS-B over log hyperparams
        # (reference uses third_party/lbfgs the same way)
        for x0 in ([0.0, 0.0], [1.0, 0.0], [-1.0, 1.0]):
            r = minimize(nll, x0, method="L-BFGS-B",
                         bounds=[(-5, 5), (-5, 5)])
            if best is None or r.fun < best.fun:
                best = r
        self.length, self.sigma_f = np.exp(best.x)
        return self

    def predict(self, x):
        """Posterior mean and std at test points."""
        x = np.atleast_2d(np.asarray(x, float))
        if self.x_train is None:
            return np.zeros(len(x)), np.ones(len(x))
        k = self.kernel(self.x_train, self.x_train) \
            + self.alpha * np.eye(len(self.x_train))
        ks = self.kernel(self.x_train, x)
        kss = self.kernel(x, x)
        c, low = cho_factor(k + 1e-10 * np.eye(len(k)))
        a = cho_solve((c, low), self.y_train)
        mu = (ks.T @ a).ravel()
        v = cho_solve((c, low), ks)
        var = np.maximum(np.diag(kss - ks.T @ v), 1e-12)
        return mu, np.sqrt(var)
