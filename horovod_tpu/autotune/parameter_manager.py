"""Online autotuning of runtime knobs.

Reference: horovod/common/parameter_manager.cc/.h (544+257 LoC) — tunes the
fusion threshold and cycle time with Bayesian optimization (log2-scaled
NumericParameter, scored by bytes-reduced-per-second), plus categorical knobs,
over warmup/sample windows; winning parameters are logged and frozen after
``bayes_opt_max_samples``.

TPU adaptation: the knobs that still exist are the eager fusion runtime's
``fusion_threshold`` (bucket bytes) and its debounced ``cycle_time_ms``
(flush quiescence window) — tuned JOINTLY, like the reference's
threshold+cycle pair; jitted steps have nothing to tune. Scoring is
identical: bytes per second of reduced data over a sample window. The
manager is wired into :class:`horovod_tpu.ops.fusion.FusionRuntime`, which
reports each flush.
"""

import time

import numpy as np

from horovod_tpu.common import logging as hvd_logging
from horovod_tpu.autotune.bayesian_optimization import BayesianOptimization


class ParameterManager:
    """reference: parameter_manager.h:42-252 ParameterManager."""

    # log2 bounds: fusion threshold 1 MB .. 256 MB (reference:
    # NumericParameter fusion threshold log-scaled), cycle/debounce window
    # 0.25 ms .. 32 ms (reference: cycle time 1..25 ms).
    _LOG2_THR = (20.0, 28.0)
    _LOG2_CYC = (-2.0, 5.0)

    def __init__(self, warmup_samples=3, steps_per_sample=10,
                 bayes_opt_max_samples=20, gaussian_process_noise=0.8,
                 log_file=None, initial_threshold=64 * 1024 * 1024,
                 initial_cycle_ms=1.0):
        self._warmup_remaining = warmup_samples
        self._steps_per_sample = steps_per_sample
        self._max_samples = bayes_opt_max_samples
        self._bo = BayesianOptimization(
            bounds=[list(self._LOG2_THR), list(self._LOG2_CYC)],
            alpha=gaussian_process_noise)
        self._log_file = log_file
        # clamp into tuning bounds (threshold 0 = "fusion disabled" would
        # otherwise poison the GP with -inf)
        self._current = np.array([
            np.clip(np.log2(max(initial_threshold, 1)), *self._LOG2_THR),
            np.clip(np.log2(max(initial_cycle_ms, 1e-3)), *self._LOG2_CYC),
        ])
        self._samples = 0
        self._tuning = True
        self._window_bytes = 0
        self._window_steps = 0
        self._window_start = time.perf_counter()
        self._best = (None, -np.inf)
        if self._log_file:
            with open(self._log_file, "w") as f:
                f.write("sample,fusion_threshold,cycle_time_ms,"
                        "score_bytes_per_sec\n")

    @property
    def fusion_threshold(self):
        return int(2 ** self._current[0])

    @property
    def cycle_time_ms(self):
        return float(2 ** self._current[1])

    @property
    def tuning(self):
        return self._tuning

    def record(self, nbytes):
        """Report one flush of ``nbytes`` reduced bytes
        (reference: ParameterManager::Update per-tensor byte accounting)."""
        if not self._tuning:
            return None
        self._window_bytes += nbytes
        self._window_steps += 1
        if self._window_steps < self._steps_per_sample:
            return None
        return self._end_sample()

    def _end_sample(self):
        elapsed = max(time.perf_counter() - self._window_start, 1e-9)
        score = self._window_bytes / elapsed
        self._window_bytes = 0
        self._window_steps = 0
        self._window_start = time.perf_counter()

        if self._warmup_remaining > 0:
            # discard warmup windows (reference: warmup_samples)
            self._warmup_remaining -= 1
            return self.fusion_threshold, self.cycle_time_ms

        self._samples += 1
        self._bo.add_sample(self._current, score)
        if score > self._best[1]:
            self._best = (self._current.copy(), score)
        if self._log_file:
            with open(self._log_file, "a") as f:
                f.write(f"{self._samples},{self.fusion_threshold},"
                        f"{self.cycle_time_ms:.3f},{score:.1f}\n")

        if self._samples >= self._max_samples:
            # freeze at the best observed configuration
            self._current = self._best[0]
            self._tuning = False
            hvd_logging.info(
                "autotune converged: fusion_threshold=%d cycle=%.2fms "
                "(%.1f MB/s)", self.fusion_threshold, self.cycle_time_ms,
                self._best[1] / 1e6)
        else:
            self._current = np.asarray(self._bo.next_sample(), float)
        return self.fusion_threshold, self.cycle_time_ms
