"""Online autotuning of runtime knobs.

Reference: horovod/common/parameter_manager.cc/.h (544+257 LoC) — tunes the
fusion threshold and cycle time with Bayesian optimization (log2-scaled
NumericParameter, scored by bytes-reduced-per-second), PLUS categorical
knobs (CategoricalParameter: hierarchical allreduce/allgather, cache
toggles) swept per category, over warmup/sample windows; winning parameters
are logged and frozen after ``bayes_opt_max_samples``.

TPU adaptation: the numeric knobs are the eager fusion runtime's
``fusion_threshold`` (bucket bytes) and its debounced ``cycle_time_ms``
(flush quiescence window) — tuned JOINTLY, like the reference's
threshold+cycle pair; jitted steps have nothing to tune. The categorical
knobs are the allreduce STRATEGY (flat | hierarchical | torus — the 2-level
schemes of parallel/strategies.py over the cross×local mesh) and, when the
user already opted into a 16-bit wire, the WIRE DTYPE (float16 |
bfloat16). Categories are swept round-robin for ``CAT_PASSES`` windows
each after warmup (the reference's categorical phase), the best mean
score wins, then the numeric BO runs. Scoring is identical throughout:
bytes per second of reduced data over a sample window. The manager is
wired into :class:`horovod_tpu.ops.fusion.FusionRuntime`, which reports
each flush and applies returned knob updates.
"""

import itertools
import time

import numpy as np

from horovod_tpu.common import logging as hvd_logging
from horovod_tpu.autotune.bayesian_optimization import BayesianOptimization


def sweep_categoricals(current_strategy, config_wire_dtype, has_slices,
                       a2a_strategy=None, a2a_cross_dtype=""):
    """THE categorical knob set of the strategy/wire sweep — one
    definition for the flush-window tuner (FusionRuntime) and the
    autopilot controller, so the two can never sweep different spaces.
    ``current_strategy`` goes first (the tie-break winner);
    ``torus_qcross`` joins only when a slice hierarchy exists (on a
    1-slice layout it is pure overhead — hvdlint HVP113). The wire
    categorical exists only when the user already opted into a 16-bit or
    quantized wire, and sweeps UP in precision only (precision policy is
    never a speed knob).

    ``a2a_strategy`` (the hierarchical-alltoall tier's current strategy,
    None = tier disarmed / no alltoalls to steer) adds the expert-
    dispatch levers: the a2a strategy sweeps flat | hier | hier_qcross —
    again only over a real slice hierarchy — and, when the user already
    opted into a QUANTIZED expert cross wire (``a2a_cross_dtype``), the
    cross-leg dtype sweeps up to the exact leg (``""``). The sweep never
    quantizes activations on its own — that is the autopilot's guarded
    one-epoch trial (revert unless DCN collapses), not a category."""
    import jax.numpy as jnp

    from horovod_tpu.ops import wire as _wire

    choices = ("flat", "hierarchical", "torus") + (
        ("torus_qcross",) if has_slices else ())
    cats = {"strategy": [current_strategy] + [
        s for s in choices if s != current_strategy]}
    resolved = _wire.resolve_wire_dtype(config_wire_dtype)
    if _wire.is_quantized(resolved):
        first = jnp.dtype(_wire.wire_numpy_type(resolved)).name
        cats["wire_dtype"] = [first, "bfloat16", "float16"]
    elif resolved:
        cats["wire_dtype"] = [
            resolved, "bfloat16" if resolved == "float16" else "float16"]
    if a2a_strategy and has_slices:
        cats["a2a_strategy"] = [a2a_strategy] + [
            s for s in ("flat", "hier", "hier_qcross")
            if s != a2a_strategy]
        resolved_a2a = _wire.resolve_wire_dtype(a2a_cross_dtype)
        if _wire.is_quantized(resolved_a2a):
            cats["a2a_cross_dtype"] = [resolved_a2a, ""]
    return cats


class ParameterManager:
    """reference: parameter_manager.h:42-252 ParameterManager."""

    # log2 bounds: fusion threshold 1 MB .. 256 MB (reference:
    # NumericParameter fusion threshold log-scaled), cycle/debounce window
    # 0.25 ms .. 32 ms (reference: cycle time 1..25 ms).
    _LOG2_THR = (20.0, 28.0)
    _LOG2_CYC = (-2.0, 5.0)
    # sample windows per categorical combo (reference sweeps each category
    # value across its warmup/sample machinery)
    CAT_PASSES = 2

    def __init__(self, warmup_samples=3, steps_per_sample=10,
                 bayes_opt_max_samples=20, gaussian_process_noise=0.8,
                 log_file=None, initial_threshold=64 * 1024 * 1024,
                 initial_cycle_ms=1.0, categorical_knobs=None,
                 max_move_log2=None):
        self._warmup_remaining = warmup_samples
        self._steps_per_sample = steps_per_sample
        self._max_samples = bayes_opt_max_samples
        # Bounded move per sample (the autopilot's per-epoch guardrail):
        # the BO proposal is clamped to within +-max_move_log2 of the
        # knobs ACTUALLY in effect, and _current always records the
        # applied point — the GP is fed what really ran, never an
        # unapplied proposal. None = unbounded (the offline default).
        # `is not None`, not truthiness: an explicit 0 means FROZEN
        # numerics (clamp every move to zero), not unbounded.
        self._max_move = None if max_move_log2 is None \
            else float(max_move_log2)
        self._bo = BayesianOptimization(
            bounds=[list(self._LOG2_THR), list(self._LOG2_CYC)],
            alpha=gaussian_process_noise)
        self._log_file = log_file
        # clamp into tuning bounds (threshold 0 = "fusion disabled" would
        # otherwise poison the GP with -inf)
        self._current = np.array([
            np.clip(np.log2(max(initial_threshold, 1)), *self._LOG2_THR),
            np.clip(np.log2(max(initial_cycle_ms, 1e-3)), *self._LOG2_CYC),
        ])
        # categorical phase state: knob name -> ordered choices (first =
        # the configured/initial value, which is also the tie-break winner)
        self._cat_knobs = {k: list(v)
                           for k, v in (categorical_knobs or {}).items()
                           if len(v) > 1}
        names = sorted(self._cat_knobs)
        combos = list(itertools.product(*(self._cat_knobs[n]
                                          for n in names))) if names else []
        self._cat_names = names
        self._cat_queue = [c for c in combos
                           for _ in range(self.CAT_PASSES)][1:]
        self._cat_current = combos[0] if combos else ()
        self._cat_scores = {c: [] for c in combos}
        self._cat_done = not combos
        # First window on a new combo includes the combo's program compile
        # (strategy/wire_dtype are in the fused-program cache key) — its
        # score would bury every non-incumbent combo. Discard it.
        self._cat_warmed = None
        self._window_invalid = False
        self._invalid_streak = 0
        self._samples = 0
        self._tuning = True
        self._window_bytes = 0
        self._window_steps = 0
        self._window_start = time.perf_counter()
        self._best = (None, -np.inf)
        if self._log_file:
            with open(self._log_file, "w") as f:
                f.write("sample,fusion_threshold,cycle_time_ms,"
                        "categoricals,score_bytes_per_sec\n")

    @property
    def fusion_threshold(self):
        return int(2 ** self._current[0])

    @property
    def cycle_time_ms(self):
        return float(2 ** self._current[1])

    @property
    def categoricals(self):
        """Current categorical knob values as ``{name: choice}``."""
        return dict(zip(self._cat_names, self._cat_current))

    @property
    def tuning(self):
        return self._tuning

    def invalidate_window(self):
        """The runtime could not apply the configured knobs to the current
        window (e.g. a join mask or non-linear op forced the flat
        strategy): its score would misattribute flat timings to the
        configured combo — discard it when the window closes."""
        self._window_invalid = True

    def record(self, nbytes):
        """Report one flush of ``nbytes`` reduced bytes
        (reference: ParameterManager::Update per-tensor byte accounting).
        Returns ``(fusion_threshold, cycle_time_ms, categoricals)`` when a
        sample window closed (the caller applies all three), else None."""
        if not self._tuning:
            return None
        self._window_bytes += nbytes
        self._window_steps += 1
        if self._window_steps < self._steps_per_sample:
            return None
        elapsed = max(time.perf_counter() - self._window_start, 1e-9)
        score = self._window_bytes / elapsed
        self._window_bytes = 0
        self._window_steps = 0
        self._window_start = time.perf_counter()
        return self._end_sample(score)

    def suggest(self):
        """The knobs currently proposed/in effect, WITHOUT advancing the
        tuner: ``(fusion_threshold, cycle_time_ms, categoricals)``. The
        autopilot applies these for one decision epoch and feeds the
        measured result back through :meth:`observe`."""
        return self._knobs()

    def observe(self, score):
        """Online increment decoupled from the tensor-byte ``update``/
        ``record`` path: feed one externally-computed sample score (the
        autopilot's signal-plane bytes/sec for a whole decision epoch)
        and advance the same warmup → categorical sweep → BO → freeze
        machinery. Non-finite scores (a partially-observed first epoch:
        zero elapsed time, missing counters → NaN/inf) are clamped to
        0.0 so they can never poison the GP or win the sweep. Returns
        the next knobs like :meth:`record`, or None once frozen."""
        if not self._tuning:
            return None
        try:
            score = float(score)
        except (TypeError, ValueError):
            score = 0.0
        if not np.isfinite(score):
            score = 0.0
        return self._end_sample(score)

    # --- twin-prior serialization seam --------------------------------

    def export_observations(self):
        """JSON-serializable record of everything this manager observed —
        the sweep space it ran over, per-combo categorical scores, the
        numeric BO samples, and the best point seen. This is the twin
        prior artifact (``horovod_tpu.sim.autopilot`` writes it, a live
        controller loads it through ``HOROVOD_AUTOPILOT_PRIOR``)."""
        best_point, best_score = self._best
        if best_point is None:
            best_point = self._current
        return {
            "version": 1,
            "bounds": [list(self._LOG2_THR), list(self._LOG2_CYC)],
            "categoricals": {n: list(self._cat_knobs[n])
                             for n in self._cat_names},
            "cat_scores": [
                {"combo": dict(zip(self._cat_names, combo)),
                 "scores": [float(s) for s in scores]}
                for combo, scores in self._cat_scores.items()],
            "samples": [
                {"point": [float(v) for v in x], "score": float(y)}
                for x, y in zip(self._bo.x_samples, self._bo.y_samples)],
            "best": {
                "point": [float(v) for v in best_point],
                "score": (float(best_score)
                          if np.isfinite(best_score) else 0.0),
                "categoricals": self.categoricals,
            },
        }

    def import_observations(self, data, adopt_best=True):
        """Warm-start this manager from an :meth:`export_observations`
        artifact: the categorical sweep is SKIPPED (the prior's winning
        combo is adopted directly) and the numeric search starts at the
        prior's best point instead of the configured initials. Returns
        the number of prior observations consumed.

        The prior's scores are deliberately NOT fed to the live GP: twin
        scores are modeled bytes/sec, live scores are measured — mixing
        the two scales would distort expected improvement and could let
        a modeled score win ``_best`` at freeze time. What transfers is
        the sweep OUTCOME (combo + starting point); the prior's raw
        ``cat_scores`` are kept for forensics/tie context only.

        Raises ``ValueError`` when the artifact does not match this
        manager's sweep space (different bounds, categorical knob names,
        or choice sets) — a prior from a different layout or build must
        be rejected loudly, not silently misapplied."""
        if not isinstance(data, dict) or data.get("version") != 1:
            raise ValueError(
                "autopilot prior: expected an export_observations dict "
                f"with version=1, got {type(data).__name__}")
        bounds = [list(self._LOG2_THR), list(self._LOG2_CYC)]
        got_bounds = [[float(v) for v in b] for b in data.get("bounds", [])]
        if got_bounds != bounds:
            raise ValueError(
                f"autopilot prior: numeric bounds {got_bounds} do not "
                f"match this build's {bounds}")
        prior_cats = {n: list(v)
                      for n, v in (data.get("categoricals") or {}).items()}
        if sorted(prior_cats) != self._cat_names or any(
                set(prior_cats[n]) != set(self._cat_knobs[n])
                for n in self._cat_names):
            raise ValueError(
                "autopilot prior: categorical space "
                f"{ {n: sorted(v) for n, v in prior_cats.items()} } does "
                "not match this manager's "
                f"{ {n: sorted(v) for n, v in self._cat_knobs.items()} }")
        best = data.get("best") or {}
        best_cats = best.get("categoricals") or {}
        if self._cat_names:
            combo = tuple(best_cats.get(n) for n in self._cat_names)
            if any(c not in self._cat_knobs[n]
                   for n, c in zip(self._cat_names, combo)):
                raise ValueError(
                    f"autopilot prior: best categoricals {best_cats} not "
                    "in this manager's sweep space")
            self._cat_current = combo
            self._cat_done = True
            self._cat_queue = []
            self._cat_warmed = combo  # already compiled/ran in the twin
            for entry in data.get("cat_scores") or []:
                key = tuple(entry["combo"].get(n) for n in self._cat_names)
                if key in self._cat_scores:
                    self._cat_scores[key] = [float(s)
                                             for s in entry["scores"]]
        consumed = len(data.get("samples") or []) + sum(
            len(e.get("scores") or [])
            for e in data.get("cat_scores") or [])
        if adopt_best and best.get("point") is not None:
            point = np.asarray([float(v) for v in best["point"]], float)
            if point.shape != self._current.shape:
                raise ValueError(
                    f"autopilot prior: best point {best['point']} has "
                    f"wrong dimensionality (want {len(self._current)})")
            point[0] = np.clip(point[0], *self._LOG2_THR)
            point[1] = np.clip(point[1], *self._LOG2_CYC)
            self._current = point
        return consumed

    def _knobs(self):
        return self.fusion_threshold, self.cycle_time_ms, self.categoricals

    def _end_sample(self, score):
        if not np.isfinite(score):
            score = 0.0
        invalid, self._window_invalid = self._window_invalid, False

        if self._warmup_remaining > 0:
            # discard warmup windows (reference: warmup_samples)
            self._warmup_remaining -= 1
            return self._knobs()
        if invalid:
            self._invalid_streak += 1
            if self._invalid_streak < 3:
                # knobs weren't actually in effect for this window —
                # measuring it would poison whichever phase is active
                return self._knobs()
            # PERSISTENTLY unmeasurable (e.g. every flush downgrades the
            # 2-level strategy under a join mask): discarding forever
            # would deadlock the whole tuner. In the sweep, zero-score the
            # combo so it can never win (ties go to the configured
            # default); in the numeric phase, score the window as-is —
            # all windows are equally downgraded, so they stay comparable.
            self._invalid_streak = 0
            if not self._cat_done:
                score = 0.0
        else:
            self._invalid_streak = 0

        if not self._cat_done:
            # Categorical sweep phase (reference: CategoricalParameter
            # round-robin before the numeric tuner). Numerics stay at their
            # initial values so category scores aren't confounded.
            if self._cat_warmed != self._cat_current:
                # per-combo compile warmup: discard the first window after
                # a switch, stay on the combo for its measured passes
                self._cat_warmed = self._cat_current
                return self._knobs()
            self._cat_scores[self._cat_current].append(score)
            if self._log_file:
                with open(self._log_file, "a") as f:
                    f.write(f"cat,{self.fusion_threshold},"
                            f"{self.cycle_time_ms:.3f},"
                            f"{'|'.join(map(str, self._cat_current))},"
                            f"{score:.1f}\n")
            if self._cat_queue:
                self._cat_current = self._cat_queue.pop(0)
            else:
                # every combo measured CAT_PASSES times: best mean wins
                # (ties: earliest combo, i.e. the configured default)
                self._cat_current = max(
                    self._cat_scores,
                    key=lambda c: (float(np.mean(self._cat_scores[c])),
                                   -list(self._cat_scores).index(c)))
                self._cat_done = True
                hvd_logging.info(
                    "autotune categorical phase done: %s",
                    self.categoricals)
            return self._knobs()

        self._samples += 1
        self._bo.add_sample(self._current, score)
        if score > self._best[1]:
            self._best = (self._current.copy(), score)
        if self._log_file:
            with open(self._log_file, "a") as f:
                f.write(f"{self._samples},{self.fusion_threshold},"
                        f"{self.cycle_time_ms:.3f},"
                        f"{'|'.join(map(str, self._cat_current))},"
                        f"{score:.1f}\n")

        if self._samples >= self._max_samples:
            # freeze at the best observed configuration
            self._current = self._best[0]
            self._tuning = False
            hvd_logging.info(
                "autotune converged: fusion_threshold=%d cycle=%.2fms "
                "categoricals=%s (%.1f MB/s)", self.fusion_threshold,
                self.cycle_time_ms, self.categoricals, self._best[1] / 1e6)
        else:
            prop = np.asarray(self._bo.next_sample(), float)
            if self._max_move is not None:
                prop = np.clip(prop, self._current - self._max_move,
                               self._current + self._max_move)
                prop[0] = np.clip(prop[0], *self._LOG2_THR)
                prop[1] = np.clip(prop[1], *self._LOG2_CYC)
            self._current = prop
        return self._knobs()
