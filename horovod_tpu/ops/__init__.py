from horovod_tpu.ops import in_jit  # noqa: F401
from horovod_tpu.ops.collective_ops import *  # noqa: F401,F403
