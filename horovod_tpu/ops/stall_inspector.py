"""Stall inspector: watchdog for stuck eager collectives.

Reference: horovod/common/stall_inspector.cc/.h (185+103 LoC) — the
coordinator warns when some ranks submitted a tensor and others didn't within
``HOROVOD_STALL_CHECK_TIME_SECONDS`` (60s) and can shut the job down after
``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS``.

TPU adaptation: the rank-mismatch failure mode can't happen inside one
controller (every rank's slice is submitted atomically), but its moral
equivalent can: an async tensor enqueued into the fusion buffer and never
flushed (the user forgot ``synchronize()``/``join()``), which in the reference
would eventually stall peers. The inspector runs a daemon thread that warns
about tensors pending longer than the threshold and optionally raises the
shutdown flag checked by the next enqueue.
"""

import threading
import time

from horovod_tpu.common import logging as hvd_logging
from horovod_tpu.common.exceptions import HorovodInternalError
from horovod_tpu.flight import recorder as _flight
from horovod_tpu.metrics import instruments as _metrics


class StallInspector:
    CHECK_INTERVAL_SECS = 5.0

    def __init__(self, warning_secs=60.0, shutdown_secs=0.0):
        self.warning_secs = warning_secs
        self.shutdown_secs = shutdown_secs
        self._lock = threading.Lock()
        self._oldest_enqueue = None
        self._pending_names = []
        self._warned = False
        self.shutdown_flagged = False
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        """Terminate the watchdog thread (called on hvd.shutdown so elastic
        restart cycles don't leak threads)."""
        self._stop.set()

    def record_enqueue(self, name):
        with self._lock:
            if self._oldest_enqueue is None:
                self._oldest_enqueue = time.monotonic()
            self._pending_names.append(name)
            if self.shutdown_flagged:
                raise HorovodInternalError(
                    "collective queue stalled beyond "
                    f"{self.shutdown_secs}s (stall inspector shutdown, "
                    "reference: HOROVOD_STALL_SHUTDOWN_TIME_SECONDS)")

    def record_flush(self):
        with self._lock:
            self._oldest_enqueue = None
            self._pending_names.clear()
            self._warned = False

    def _loop(self):
        while not self._stop.wait(self.CHECK_INTERVAL_SECS):
            with self._lock:
                if self._oldest_enqueue is None:
                    continue
                age = time.monotonic() - self._oldest_enqueue
                names = list(self._pending_names[:8])
                warned = self._warned
                flagged = self.shutdown_flagged
            if age > self.warning_secs and not warned:
                # Counted as well as logged: stall_events_total makes the
                # finding scrapeable instead of a log-grep-only signal.
                _metrics.record_stall("warning")
                # ... and the flight ring dumps: the stall is exactly the
                # "wedge with no artifact" failure the recorder exists for
                # — the dump names the pending tensors' enqueue history.
                _flight.dump("stall_warning")
                hvd_logging.warning(
                    "One or more tensors submitted to the fusion queue "
                    "%.0fs ago were never reduced — missing synchronize()? "
                    "Pending: %s (reference: stall_inspector.cc "
                    "CheckForStalledTensors)", age, names)
                # record_flush clears _warned under the lock from caller
                # threads; the set must pair with it (dump/log above stay
                # outside the critical section).
                with self._lock:
                    self._warned = True
            if self.shutdown_secs > 0 and age > self.shutdown_secs:
                if not flagged:
                    _metrics.record_stall("shutdown")
                    _flight.dump("stall_shutdown")
                with self._lock:
                    self.shutdown_flagged = True
