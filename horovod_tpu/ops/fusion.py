"""Tensor-fusion (bucketing) runtime for the eager path.

Reference mechanism (horovod/common/fusion_buffer_manager.h:30-62 + the cycle
loop operations.cc:747-853): small tensors submitted within one cycle are
memcpy'd into a persistent fusion buffer and reduced with ONE collective, then
scattered back out; buffer capacity is ``HOROVOD_FUSION_THRESHOLD`` (128 MB)
and the loop wakes every ``HOROVOD_CYCLE_TIME`` (1 ms).

TPU-native design: no memcpy staging — pending tensors are raveled and
concatenated *inside one jitted program* per (names, shapes, dtypes, op)
signature, reduced with a single ``psum`` on the flat buffer, and split back,
all fused by XLA. The signature-keyed program cache means a steady-state
training loop hits the same compiled fused program every step (the
response-cache fast path, reference: response_cache.h:45).

Flush triggers: pending bytes >= fusion_threshold, an explicit
``synchronize()``/``poll()`` on any returned handle, ``flush_all()``, or the
background cycle thread — which is DEBOUNCED (fires after one
``HOROVOD_CYCLE_TIME`` of enqueue quiescence) so that a burst of hook
enqueues is never split at arbitrary time boundaries: stable burst → stable
bucket signature → compiled-program cache hit.
"""

import functools
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu import trace as _trace
from horovod_tpu.chaos import injector as _chaos
from horovod_tpu.common import basics
from horovod_tpu.common.topology import HVD_AXIS
from horovod_tpu.flight import recorder as _flight
from horovod_tpu.ops import wire as _wire
from horovod_tpu.profile import ledger as _profile
from horovod_tpu.ops.collective_ops import (ReduceOp, _localize, _prepare,
                                            _reduce_shard)


def _bucket_quant(wire_dtype, strategy, masked, op, sizes, dtypes, n):
    """Quantized-exchange eligibility for ONE fusion bucket, computed from
    STATIC bucket facts so the runtime (which must decide whether to pass
    a residual) and the compiled program (which must declare the residual
    argument) can never disagree. Returns the quantized wire label
    (``int8``/``fp8``) or None: only flat-strategy float Sum/Average
    buckets without a join mask, big enough that the exchange's n×BLOCK
    padding doesn't inflate the wire. The 2-level strategies keep their
    own wire schemes and tiny buckets keep the exact psum."""
    label = _wire.quantized_label(wire_dtype)
    if label is None or strategy != "flat" or masked \
            or op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        return None
    if sum(sizes) < n * _wire.BLOCK:
        return None
    # jnp.issubdtype, NOT np.issubdtype: ml_dtypes bfloat16 is not
    # np.floating, and bf16 buckets are the COMMON quantization target
    # (the bucket key keeps quantized buckets in their original float
    # dtype precisely so bf16 ones can ride the exchange).
    if not all(jnp.issubdtype(jnp.dtype(d), jnp.floating) for d in dtypes):
        return None
    return label


def _hier_bucket_facts(hier_mesh, total, cross_wire, all_float=True):
    """Static per-bucket facts of the torus_qcross decomposition over
    ``hier_mesh`` — one call into wire.hierarchical_wire_bytes (the
    shared integer formulas) so the runtime (residual sizing, per-tier
    byte records) and the compiled program (residual argument) can never
    disagree. ``all_float=False`` (an integer bucket) forces the exact
    cross leg — the SAME refusal ``allreduce_torus`` applies in the
    compiled program, so the accounting never claims a quantized wire the
    program didn't ride. ``width`` here only affects byte totals, not the
    cross-quantization verdict; callers re-price with the bucket's real
    itemsize for accounting."""
    from horovod_tpu.common.topology import CROSS_AXIS, LOCAL_AXIS
    cross_n = int(hier_mesh.shape[CROSS_AXIS])
    local_n = int(hier_mesh.shape[LOCAL_AXIS])
    return _wire.hierarchical_wire_bytes(
        int(total), cross_n * local_n, cross_n, 4,
        cross_wire=(cross_wire or "") if all_float else "")


class FusedHandle:
    """Handle for a tensor pending in the fusion queue. Resolves after the
    bucket it lands in is flushed (reference analog: HandleManager int handle
    + per-entry callback, torch/handle_manager.h)."""

    __slots__ = ("_runtime", "_result", "_error", "_tid", "name")

    def __init__(self, runtime, name, tid=None):
        self._runtime = runtime
        self._result = None
        self._error = None
        self._tid = tid
        self.name = name

    def _set(self, value):
        self._result = value

    def _set_error(self, exc):
        # Failure delivery for flushes that run on the cycle thread, where
        # there is no caller to raise to (reference: per-tensor status
        # callbacks carry the error, operations.cc entry.FinishWithCallback).
        self._error = exc

    def poll(self):
        if self._error is not None:
            return True  # "complete": synchronize() will raise it
        if self._result is None:
            # Polling also acts as a cycle tick: a pending bucket is flushed
            # the first time anyone asks about it. poll() must stay
            # NON-blocking (the overlap idiom is `while not h.poll():
            # compute()`), so followers only apply already-published
            # boundaries here — synchronize() is the blocking wait.
            self._runtime.ensure_flushed(self._tid, block=False)
        if self._error is not None:
            return True
        if self._result is None:
            return False
        return all(o.is_ready() if hasattr(o, "is_ready") else True
                   for o in jax.tree_util.tree_leaves(self._result))

    def synchronize(self):
        if self._error is None and self._result is None:
            self._runtime.ensure_flushed(self._tid)
        if self._error is not None:
            raise self._error
        jax.block_until_ready(self._result)
        return self._result


@functools.lru_cache(maxsize=2048)
def _fused_program(mesh, n, op, prescale, postscale, shapes, dtypes,
                   wire_dtype, active_mask=None, strategy="flat",
                   donate=(), ef=False, cross_wire=""):
    """One flat-buffer reduction for a whole bucket. ``active_mask`` carries
    join state so async collectives honor the same joined-rank exclusion as
    the sync path (reference: joined_size accounting). ``strategy``:
    "flat" runs the 1-D psum; "hierarchical"/"torus" run the 2-level
    schemes of parallel/strategies.py; "torus_qcross" is the hierarchical
    dispatch tier — local RS (exact, ICI) -> cross-slice allreduce on
    ``cross_wire`` (DCN; per-bucket error feedback when ``ef``) -> local
    AG. For every 2-level strategy ``mesh`` must be the (cross, local)
    factorization (the DCN mesh when a slice hierarchy exists; the
    autotuner's categorical knob — reference:
    HOROVOD_HIERARCHICAL_ALLREDUCE / HOROVOD_TORUS_ALLREDUCE)."""
    sizes = [int(np.prod(s[1:])) for s in shapes]
    active = None if active_mask is None else np.array(active_mask)
    if strategy != "flat":
        from horovod_tpu.common.topology import CROSS_AXIS, LOCAL_AXIS
        from horovod_tpu.parallel.strategies import (allreduce_hierarchical,
                                                     allreduce_torus)
        spec = P((CROSS_AXIS, LOCAL_AXIS))
    else:
        spec = P(HVD_AXIS)

    def reduce_buf(buf, residual=None):
        # (flat_len,) chip-local buffer -> reduced buffer (+ new residual
        # for the torus_qcross cross leg's error feedback)
        new_res = None
        if strategy == "torus":
            out = allreduce_torus(
                buf * jnp.asarray(prescale, buf.dtype) if prescale != 1.0
                else buf, average=(op == ReduceOp.AVERAGE), record=False)
        elif strategy == "torus_qcross":
            out = allreduce_torus(
                buf * jnp.asarray(prescale, buf.dtype) if prescale != 1.0
                else buf, average=(op == ReduceOp.AVERAGE),
                cross_compression=cross_wire or None,
                cross_residual=residual, record=False)
            if residual is not None:
                out, new_res = out
        elif strategy == "hierarchical":
            out = allreduce_hierarchical(
                buf * jnp.asarray(prescale, buf.dtype) if prescale != 1.0
                else buf, average=(op == ReduceOp.AVERAGE), record=False)
        else:
            return _reduce_shard(buf[None], op, n, prescale, postscale,
                                 HVD_AXIS, active)[0], None
        if postscale != 1.0:
            out = out * jnp.asarray(postscale, out.dtype)
        # the cross psum leaves the value cross-invariant; the stacked
        # out_specs need it typed varying over both mesh axes
        from horovod_tpu.ops.in_jit import mark_varying
        out = mark_varying(mark_varying(out, CROSS_AXIS), LOCAL_AXIS)
        if new_res is not None:
            new_res = mark_varying(mark_varying(new_res, CROSS_AXIS),
                                   LOCAL_AXIS)
        return out, new_res

    # Quantized wire (int8/fp8): the fused bucket rides the two-phase
    # block-scaled exchange (EQuARX-style, ops/wire.py — ~2 B/element vs
    # ~8 for an fp32 psum's internal RS+AG) instead of a cast+psum, with
    # an optional per-bucket error-feedback residual (``ef``: the program
    # takes the bucket's fp32 residual as its last input and returns the
    # new one as its last output — the runtime owns the store). The
    # eligibility verdict is STATIC (_bucket_quant) so runtime and
    # program agree on the argument list; ineligible combinations
    # quietly keep the exact psum (or the 16-bit cast wire).
    quant_label = _bucket_quant(wire_dtype, strategy,
                                active is not None, op, sizes, dtypes, n)
    use_ef = bool(ef) and quant_label is not None
    cast_wire = (wire_dtype is not None and quant_label is None
                 and strategy != "torus_qcross"
                 and not _wire.is_quantized(wire_dtype))
    total = sum(sizes)
    # torus_qcross per-bucket error feedback covers the CROSS leg's shard
    # only; the verdict is STATIC (shared wire.hierarchical_wire_bytes
    # facts) so the runtime's residual argument always matches.
    hier = _hier_bucket_facts(mesh, total, cross_wire) \
        if strategy == "torus_qcross" else None
    hier_ef = bool(ef) and hier is not None \
        and hier["cross_label"] is not None
    res_len = hier["shard_elems"] if hier_ef else total

    def body(*args):
        # xs: local slices (1, ...). Flatten each, concat per the bucket
        # layout (the MemcpyInFusionBuffer analog, fused by XLA into the
        # collective's input), one psum, then split back out. Buckets are
        # formed per effective wire dtype so the concat is homogeneous.
        # Adasum must normalize per-tensor (its coefficients are norms of the
        # individual gradients, reference: adasum.h:103+), so its tensors are
        # reduced individually inside the single dispatch instead of fused.
        xs = args[:len(shapes)]
        if op == ReduceOp.ADASUM:
            return tuple(
                _reduce_shard(x, op, n, prescale, postscale, HVD_AXIS, active)
                for x in xs)
        flats = []
        for x in xs:
            f = x.reshape(-1)
            if cast_wire and jnp.issubdtype(f.dtype, jnp.floating):
                f = f.astype(wire_dtype)
            flats.append(f)
        buf = jnp.concatenate(flats) if len(flats) > 1 else flats[0]
        new_res = None
        if quant_label is not None:
            from horovod_tpu.ops.in_jit import mark_varying
            residual = args[-1].reshape(-1) if use_ef else None
            red, new_res = _wire.block_scaled_allreduce(
                buf, residual=residual, axis_name=HVD_AXIS,
                wire=quant_label, average=(op == ReduceOp.AVERAGE),
                prescale_factor=prescale, postscale_factor=postscale)
            buf = mark_varying(red, HVD_AXIS)
        else:
            residual = args[-1].reshape(-1) if hier_ef else None
            buf, new_res = reduce_buf(buf, residual)
        outs, off = [], 0
        for x, sz in zip(xs, sizes):
            piece = lax.slice_in_dim(buf, off, off + sz).astype(x.dtype)
            outs.append(piece.reshape(x.shape))
            off += sz
        if use_ef or hier_ef:
            outs.append(new_res.reshape(1, res_len))
        return tuple(outs)

    n_args = len(shapes) + (1 if use_ef or hier_ef else 0)
    f = jax.shard_map(body, mesh=mesh,
                      in_specs=tuple(spec for _ in range(n_args)),
                      out_specs=tuple(spec for _ in range(n_args)))
    # HOROVOD_DONATE_BUFFERS (default on): staged input stacks nobody
    # reads again are donated per-argument so XLA reuses their HBM for
    # the outputs (the reference's persistent fusion buffer is likewise
    # reused across cycles, fusion_buffer_manager.h:40).
    return jax.jit(f, donate_argnums=tuple(donate))


# Flush-plan cache: bucket signature -> compiled fused program. The lru on
# _fused_program already dedupes compiles; this dict additionally pins the
# steady-state lookup to one tuple-key hit per bucket (no Mesh re-hash per
# flush) and gives clear_program_caches() a single invalidation point for
# the flush path (collective_ops.clear_program_caches clears it alongside
# the dispatch-plan cache).
_flush_plans = {}


class FusionRuntime:
    # Boundary-consumer role defaults (hierarchical control plane): also
    # the flat-layout behavior, and what partially-constructed runtimes
    # (tests drive _apply_ready_boundaries via __new__) fall back to.
    _cp_role = "root"
    _cp_slice = 0
    _cp_members = 0
    _cp_lease_s = 2.0
    _lease_wait0 = None

    # Forwarded to the native scheduler so runtime threshold changes (the
    # autotuner, tests) affect its flush decision too.
    @property
    def threshold(self):
        return self._threshold

    @threshold.setter
    def threshold(self, value):
        self._threshold = value
        if getattr(self, "_native", None) is not None:
            self._native.set_threshold(value)  # hvdrace: disable=HVR203 -- _native is set once at init before worker threads start; this is an atomic-ref read

    def __init__(self, config):
        self.threshold = config.fusion_threshold
        # fp8 resolves through the wire tier (graceful bf16 fallback when
        # the dtype doesn't exist in this jax build).
        self.wire_dtype = _wire.wire_numpy_type(config.wire_dtype)
        # Per-bucket error feedback for the quantized wire (residuals keyed
        # by bucket signature in the wire tier's store, zeroed by
        # clear_program_caches / elastic reset).
        self._wire_ef = bool(config.wire_error_feedback)
        self._donate = bool(config.donate_buffers)
        self._lock = threading.RLock()
        self._pending = []  # (tid, tensor, op, prescale, postscale, handle)
        self._pending_bytes = 0
        self._last_enqueue = 0.0
        # perf_counter of the enqueue that made the pending set non-empty:
        # flush_start - first_enqueue is the bucket's DEFER window (the
        # step profiler's fusion_defer_s).
        self._first_enqueue = 0.0
        self._next_tid = 0
        self._flushed_groups = []  # group ids to deregister after flush
        self._pending_groups = []  # follower: grouped tids awaiting replay
        # Native C++ scheduler for the per-step bookkeeping (bucket assembly,
        # LRU response-cache stats, group table); Python fallback below is
        # behavior-identical (reference: the C++ cycle loop/fusion manager,
        # operations.cc:747-853).
        self._native = None
        try:
            from horovod_tpu import native
            if native.native_built():
                self._native = native.BucketScheduler(
                    self.threshold, config.cache_capacity)
        except Exception:
            self._native = None
        # Allreduce strategy for the fused buckets (a tunable categorical;
        # the config knobs give the initial value — reference common.h:130-132;
        # torus_qcross is the hierarchical dispatch tier: 2-level with the
        # cross-slice leg on the per-tier wire).
        self.strategy = ("torus_qcross"
                         if getattr(config, "hierarchical_dispatch", False)
                         else "torus" if config.torus_allreduce
                         else "hierarchical" if config.hierarchical_allreduce
                         else "flat")
        self._config = config
        # Cross-slice (DCN) leg wire of the hierarchical strategies: the
        # per-tier policy chain (registry @dcn -> HOROVOD_WIRE_DTYPE_DCN
        # -> HOROVOD_WIRE_DTYPE). Coordinator re-resolves per flush;
        # followers adopt the boundary payload's snapshot.
        self.cross_wire = _wire.cross_wire_for("global", config)
        # Cross-leg overlap (HOROVOD_CROSS_OVERLAP): hierarchical buckets'
        # outputs are left in flight at flush return; the await point is
        # steered per flush by the step profiler's collective-vs-compute
        # attribution ("step" = widened to the fence/step boundary,
        # "next_flush" = collapsed to the next flush; overlap off blocks
        # inside the flush bracket itself).
        self._overlap = bool(getattr(config, "cross_overlap", True))
        self._overlap_mode = "step"
        # True while the autopilot pins the overlap mode at decision-
        # epoch granularity: the per-flush steering below then defers —
        # without this the controller's pin would be overwritten at the
        # very next flush and a single outlier step could flap the
        # await point mid-epoch.
        self._overlap_pinned = False
        self._inflight_cross = []    # bucket outputs awaiting their wait
        self._multi = jax.process_count() > 1
        self._coord = jax.process_index() == 0
        # Hierarchical boundary sync (HOROVOD_CONTROL_PLANE): the
        # coordinator publishes each flush boundary ONCE to the root key;
        # slice leaders re-publish to their slice key; members read only
        # the slice key — so blocking reads against the coordinator's
        # store scale with slice count, not world size. Members hold a
        # lease on their leader's promptness: a root boundary their
        # leader hasn't re-published within HOROVOD_CONTROL_LEASE_MS
        # triggers takeover (see _fetch_boundary).
        self._cp_slice, self._cp_role, self._cp_members = 0, "root", 0
        self._cp_lease_s = max(
            float(getattr(config, "control_lease_ms", 2000.0)), 100.0) \
            / 1000.0
        self._lease_wait0 = None
        if self._multi:
            from horovod_tpu.common import control_plane as _cp
            groups = _cp.exchange_groups(list(range(jax.process_count())))
            self._cp_slice, self._cp_role, self._cp_members = \
                _cp.boundary_role(jax.process_index(), groups)
        self._parameter_manager = None
        # Autotune decisions are the COORDINATOR's alone under multi-process
        # launches: strategy/wire_dtype change the compiled program, and
        # per-process managers scoring with local wall clocks could freeze
        # different winners — mismatched collectives. Followers adopt the
        # knobs published with each flush boundary instead.
        if config.autotune and (not self._multi or self._coord):
            # Categorical knobs (reference: CategoricalParameter sweep,
            # parameter_manager.h:42-252): ONE definition shared with
            # the autopilot controller (autotune.sweep_categoricals) —
            # strategy choices, the torus_qcross-needs-slices rule, and
            # the opted-into wire sweep (up in precision only). The
            # winner is adopted per process set (the boundary stream
            # carries it to followers AND to the eager wire registry).
            from horovod_tpu.autotune import (ParameterManager,
                                              sweep_categoricals)
            from horovod_tpu.common.topology import forced_slices
            topo0 = basics.topology()
            has_slices = forced_slices() or topo0.num_slices > 1
            cats = sweep_categoricals(self.strategy, config.wire_dtype,
                                      has_slices)
            self._parameter_manager = ParameterManager(
                warmup_samples=config.autotune_warmup_samples,
                steps_per_sample=config.autotune_steps_per_sample,
                bayes_opt_max_samples=config.autotune_bayes_opt_max_samples,
                gaussian_process_noise=config.autotune_gaussian_process_noise,
                log_file=config.autotune_log_file or None,
                initial_threshold=config.fusion_threshold,
                initial_cycle_ms=config.cycle_time_ms,
                categorical_knobs=cats)
        self._stall_inspector = None
        if not config.stall_check_disable:
            from horovod_tpu.ops.stall_inspector import StallInspector
            self._stall_inspector = StallInspector(
                warning_secs=config.stall_check_time_seconds,
                shutdown_secs=config.stall_shutdown_time_seconds)
        # The cycle loop (reference: RunLoopOnce wakes every
        # HOROVOD_CYCLE_TIME ms, operations.cc:747-756): without it, async
        # enqueues below the fusion threshold sit until someone polls —
        # torch-style grad hooks would get no reduction/backward overlap.
        self._cycle_stop = threading.Event()
        self._cycle_pause = False
        self._cycle_thread = None
        self._cycle_s = max(float(config.cycle_time_ms), 0.0) / 1000.0
        # Multi-process flush coordination: a rank-local wall-clock timer
        # could split the same enqueue burst at different points on
        # different ranks and issue MISMATCHED collectives. The reference
        # solves this with its coordinator: rank 0 decides every response
        # set (controller.cc:74). Same design here — process 0 is the only
        # process whose triggers (cycle timer, threshold) flush directly;
        # each of its flushes publishes a BOUNDARY (the last tid flushed)
        # through the jax.distributed KV, and every other process flushes
        # exactly the published prefixes in order: its follower thread
        # applies boundaries as they appear (restoring reduction/backward
        # overlap for torch-hook training on multi-host), and
        # poll/synchronize consume boundaries until the asked-for tensor is
        # covered. SPMD guarantees every process enqueues the same tid
        # sequence, so a prefix-by-tid is the same tensor set everywhere.
        self._boundary_seq = 0      # publisher: next seq; follower: next
        self._boundary_lock = threading.RLock()
        self._flushed_tid = -1
        # Follower: the last fetched-but-not-yet-applicable boundary
        # (seq, payload) — kept so an AHEAD boundary is fetched from the
        # KV store exactly once per seq (ADVICE.md hot-poll fix).
        self._deferred_boundary = None
        self._publish_queue = None
        self._publisher_thread = None
        if not self._multi or self._coord:
            if self._multi:
                import queue
                self._publish_queue = queue.SimpleQueue()
                self._publisher_thread = threading.Thread(
                    target=self._publisher_loop, daemon=True,
                    name="hvd-fusion-publish")
                self._publisher_thread.start()
            if self._cycle_s > 0:
                self._cycle_thread = threading.Thread(
                    target=self._cycle_loop, daemon=True,
                    name="hvd-fusion-cycle")
                self._cycle_thread.start()
        else:
            # Followers always run the boundary-consumer thread (even with
            # the cycle timer disabled: threshold flushes on process 0
            # publish boundaries that must be applied for overlap).
            self._cycle_thread = threading.Thread(
                target=self._follower_loop, daemon=True,
                name="hvd-fusion-follower")
            self._cycle_thread.start()

    def _cycle_loop(self):  # hvdrace: disable=HVR203 -- debounce heuristic reads (_cycle_s/_pending/_last_enqueue) tolerate staleness; the flush itself re-checks under _lock
        while not self._cycle_stop.wait(self._cycle_s):
            # Debounced: flush only after a full cycle with NO new
            # enqueues. Flushing mid-burst would split the pending set at
            # arbitrary time boundaries — different bucket signatures every
            # step, defeating the compiled-program cache that is this
            # runtime's steady-state fast path (the guard in
            # test_perf_guards asserts zero warm-pass compiles).
            if self._pending and not self._cycle_pause and \
                    time.perf_counter() - self._last_enqueue >= \
                    self._cycle_s:
                try:
                    # Reference: RunLoopOnce emits a CYCLE_START instant per
                    # loop when --timeline-mark-cycles is on
                    # (operations.cc:759-762).
                    from horovod_tpu.common import basics
                    tl = basics.timeline()
                    if tl is not None:
                        tl.mark_cycle()
                    self.flush_all()
                except Exception:  # noqa: BLE001
                    # _flush_locked delivers failures to the affected
                    # handles; anything escaping here must not kill the
                    # cycle thread (the reference's background loop
                    # likewise outlives op failures).
                    pass

    # ---- multi-process flush boundaries (coordinator/follower) ----------

    @staticmethod
    def _kv_client():
        from jax._src import distributed
        return distributed.global_state.client

    @staticmethod
    def _boundary_key(seq):
        from horovod_tpu.common import negotiation
        return f"hvd/fusion/e{negotiation._epoch}/b{seq}"

    def _slice_boundary_key(self, seq):
        from horovod_tpu.common import negotiation
        return (f"hvd/fusion/e{negotiation._epoch}/"
                f"s{self._cp_slice}/b{seq}")

    # Boundary keys older than this many flushes are GC'd. Unlike
    # negotiation.exchange's lag-2 (safe there because exchange is a
    # blocking all-rank rendezvous), boundary publishing is one-way — a
    # follower that lags further than this would find its next key deleted
    # and stall. The margin is sized so that any follower actually that far
    # behind has ALREADY tripped the 120s SPMD-divergence guard in
    # _apply_ready_boundaries (its consumer thread applies each boundary
    # within a 300ms window; pause does not suspend it).
    _BOUNDARY_GC_LAG = 4096

    def _publisher_loop(self):
        """Coordinator: perform the boundary KV RPCs off the runtime lock
        (a flush would otherwise hold self._lock — which every gradient-
        hook enqueue needs — across two control-plane round-trips). The
        single thread preserves publish order."""
        while True:
            item = self._publish_queue.get()
            if item is None:
                return
            seq, payload = item
            try:
                client = self._kv_client()
                if client is None:
                    continue
                client.key_value_set(self._boundary_key(seq), payload)
                from horovod_tpu.common import negotiation
                negotiation.record_fusion_kv(sets=1,
                                             payload_bytes=len(payload))
                if seq >= self._BOUNDARY_GC_LAG:
                    try:
                        client.key_value_delete(
                            self._boundary_key(seq - self._BOUNDARY_GC_LAG))
                    except Exception:
                        pass
            except Exception:  # noqa: BLE001 — keep publishing
                pass

    # Fused strategy -> eager dispatch-strategy registry value: the
    # autotuner's choice steers BOTH paths per process set at the same
    # flush boundary. torus maps to the eager RS/cross/AG decomposition
    # ("hier"); torus_qcross additionally quantizes the cross leg. The
    # legacy "hierarchical" strategy (full local reduce then whole-buffer
    # cross) has NO eager analog and must sync "flat" — mapping it to
    # "hier" would make the static model price torus-shaped bytes the
    # runtime never moves.
    _EAGER_STRATEGY = {"flat": "flat", "torus": "hier",
                       "hierarchical": "flat",
                       "torus_qcross": "hier_qcross"}

    def _sync_eager_policy(self, strategy, cross_wire, a2a_strategy="",
                           a2a_cross=""):
        """Adopt the flush snapshot's strategy + cross-wire into the eager
        registries (runtime sync: defers to explicit user pins). 'flat'
        is only synced once the registry has an entry — the default-flat
        steady state must not grow a registry lookup on every eager
        dispatch. The same rule governs the hierarchical-alltoall policy
        (``a2a_strategy`` / ``a2a_cross``, carried by the boundary stream
        so the autopilot's expert-dispatch flips land on followers at the
        same flush boundary as the allreduce levers)."""
        mapped = self._EAGER_STRATEGY.get(strategy, "flat")
        if mapped != "flat" or _wire.dispatch_strategy_for("global"):
            _wire.runtime_sync_dispatch_strategy(mapped, "global")
        if cross_wire:
            _wire.runtime_sync_wire_dtype(cross_wire, "global", tier="dcn")
        if a2a_strategy and (a2a_strategy != "flat"
                             or _wire.alltoall_strategy_for("global")):
            _wire.runtime_sync_alltoall_strategy(a2a_strategy, "global")
        if a2a_cross:
            _wire.runtime_sync_alltoall_cross_dtype(a2a_cross, "global")

    def _publish_boundary(self, last_tid, strategy, wire_dtype, cross_wire):
        """Coordinator: record that tids <= last_tid are flushed — and the
        program-shaping knobs (strategy, wire dtype, cross-leg wire) in
        effect for that flush, so followers build the identical programs
        for the identical prefix. Called under self._lock — only the seq
        assignment happens here; the RPCs run on the publisher thread."""
        import json as _json
        seq = self._boundary_seq
        self._boundary_seq += 1
        wire = jnp.dtype(wire_dtype).name if wire_dtype else ""
        if wire:
            # The eager wire registry follows the SAME boundary stream the
            # fused programs do: the coordinator adopts the snapshot when
            # it publishes, followers when they apply — so at any sync
            # eager dispatch (which fences fused work first) every process
            # reads the same per-set wire dtype. Runtime sync defers to an
            # explicit user pin (hvd.set_wire_dtype). See ops/wire.py.
            _wire.runtime_sync_wire_dtype(wire, "global")
        self._sync_eager_policy(strategy, cross_wire)
        # The hierarchical-alltoall policy rides the same boundary: the
        # coordinator's registries (autopilot / runtime sync) are the
        # source of truth, and followers adopt whatever was in effect for
        # this flushed prefix.
        a2a_s = _wire.alltoall_strategy_for("global")
        a2a_cw = _wire.wire_dtype_for("a2a:global", "", tier="dcn")
        self._publish_queue.put((seq, _json.dumps(
            {"t": int(last_tid), "s": strategy, "w": wire,
             "cw": cross_wire or "", "as": a2a_s or "",
             "acw": a2a_cw or ""})))

    def _republish_boundary(self, client, seq, raw):
        """Slice leader: mirror the root boundary onto the slice key so
        members never read the root store. Idempotent (overwrite-allowed
        — a lease takeover may race the returning leader with the same
        payload) and fail-soft: a failed re-publish costs the members one
        lease window, never the stream."""
        if self._cp_role != "leader" or self._cp_members <= 0:
            return
        from horovod_tpu.common import control_plane as _cp
        from horovod_tpu.common import negotiation
        try:
            # CoordKV owns the one allow_overwrite compatibility shim.
            _cp.CoordKV(client).set(self._slice_boundary_key(seq), raw,
                                    overwrite=True)
            negotiation.record_fusion_kv(sets=1, payload_bytes=len(raw))
            if seq >= self._BOUNDARY_GC_LAG:
                try:
                    client.key_value_delete(self._slice_boundary_key(
                        seq - self._BOUNDARY_GC_LAG))
                except Exception:  # noqa: BLE001 — GC is best-effort
                    pass
        except Exception:  # noqa: BLE001 — keep consuming
            pass

    def _fetch_boundary(self, client, seq, block_ms):
        """Role-aware boundary fetch. Leaders (and every follower on a
        flat layout) block on the ROOT key and re-publish to their slice;
        members block on the SLICE key under a leader lease: when the
        root demonstrably holds a boundary the leader hasn't mirrored
        within the lease window, the member promotes itself to leader
        (the takeover the leader-kill test exercises) and serves the
        slice from then on. Returns the raw payload, or None when no new
        boundary is available yet."""
        from horovod_tpu.common import negotiation
        if self._cp_role != "member":
            try:
                raw = client.blocking_key_value_get(
                    self._boundary_key(seq), block_ms)
            except Exception:  # noqa: BLE001 — no new boundary yet
                return None
            negotiation.record_fusion_kv(gets=1, payload_bytes=len(raw),
                                         tier="root")
            self._republish_boundary(client, seq, raw)
            return raw
        try:
            raw = client.blocking_key_value_get(
                self._slice_boundary_key(seq), block_ms)
            self._lease_wait0 = None
            negotiation.record_fusion_kv(gets=1, payload_bytes=len(raw),
                                         tier="slice")
            return raw
        except Exception:  # noqa: BLE001 — slice key not mirrored yet
            pass
        now = time.perf_counter()
        if self._lease_wait0 is None:
            self._lease_wait0 = now
            return None
        if now - self._lease_wait0 < self._cp_lease_s:
            return None
        # Lease expired: is there actually a root boundary the leader
        # failed to mirror? A short probe — an empty root means there is
        # nothing to re-publish and the lease simply renews.
        try:
            raw = client.blocking_key_value_get(self._boundary_key(seq),
                                                50)
        except Exception:  # noqa: BLE001 — nothing published anywhere
            self._lease_wait0 = now
            return None
        negotiation.record_fusion_kv(gets=1, payload_bytes=len(raw),
                                     tier="root")
        # Takeover: this member is its slice's boundary re-publisher from
        # now on (multiple members promoting concurrently is harmless —
        # the re-publish is overwrite-idempotent with the same payload).
        self._cp_role = "leader"
        self._cp_members = max(self._cp_members - 1, 1)
        self._lease_wait0 = None
        from horovod_tpu import metrics as hvd_metrics
        hvd_metrics.record_boundary("takeover")
        if _flight.armed:
            _flight.record_event("fusion_flush", seq=seq,
                                 name="boundary_lease_takeover",
                                 what=f"slice{self._cp_slice}")
        from horovod_tpu.common import logging as hvd_logging
        hvd_logging.warning(
            "fusion boundary leader for slice %d stale past %.1fs — "
            "taking over the slice re-publish at seq %d",
            self._cp_slice, self._cp_lease_s, seq)
        self._republish_boundary(client, seq, raw)
        return raw

    def _apply_ready_boundaries(self, block_ms):
        """Follower: consume and apply published boundaries in order;
        waits up to ``block_ms`` for the FIRST one (later ones drain with a
        minimal wait). Returns True when at least one was applied. The
        blocking KV get runs OUTSIDE the locks (concurrent consumers may
        fetch the same key; the seq re-check under the lock dedupes) so a
        long blocking window never delays the sync path."""
        from horovod_tpu import metrics as hvd_metrics
        applied = False
        while True:
            client = self._kv_client()
            if client is None:
                return applied
            with self._boundary_lock:
                seq = self._boundary_seq
                deferred = self._deferred_boundary
            if deferred is not None and deferred[0] == seq:
                # An AHEAD boundary for this seq was already fetched: serve
                # it from the local cache instead of re-issuing the KV get
                # — the key already exists, so blocking_key_value_get would
                # return instantly and the 1 ms follower loop would hot-
                # poll the shared coordination service ~1000x/sec while
                # waiting for the local stream (ADVICE.md round-5 finding).
                payload = deferred[1]
                with self._lock:
                    behind = self._next_tid <= int(payload["t"])
                if behind:
                    # Still ahead of us: bounded backoff (no RPC at all)
                    # paces BOTH the follower loop and ensure_flushed's
                    # blocking loop while they wait for the enqueue stream.
                    time.sleep(min(max(int(block_ms), 1), 50) / 1000.0)
                    return applied
            else:
                raw = self._fetch_boundary(client, seq,
                                           max(int(block_ms), 1))
                if raw is None:
                    return applied          # no new boundary yet
                import json as _json
                payload = _json.loads(raw)
            last_tid = int(payload["t"])
            with self._boundary_lock:
                if self._boundary_seq != seq:
                    self._deferred_boundary = None
                    block_ms = 1            # another consumer took it
                    continue
                # Adopt the coordinator's program-shaping knobs for this
                # prefix (its autotuner is the only decision maker) — and
                # mirror the wire dtype into the eager registry (the
                # coordinator did the same when it published).
                self.strategy = payload.get("s", self.strategy)
                wire = payload.get("w", "")
                self.wire_dtype = jnp.dtype(wire).type if wire else None
                self.cross_wire = payload.get("cw", "")
                if wire:
                    _wire.runtime_sync_wire_dtype(wire, "global")
                self._sync_eager_policy(self.strategy, self.cross_wire,
                                        payload.get("as", ""),
                                        payload.get("acw", ""))
                # The local enqueue stream may lag the coordinator's:
                # applying early would flush a SHORTER prefix and misalign
                # every later collective. A boundary AHEAD of the local
                # stream is DEFERRED, not waited on: on the sync path the
                # consumer IS the enqueuing thread (a handle.synchronize()
                # between enqueues), so waiting here for the next enqueue
                # would self-deadlock — the coordinator legitimately runs
                # one op ahead under an enqueue-sync-enqueue-sync pattern.
                # The fetched payload is cached at this seq and applied by
                # a later call once the local stream catches up — WITHOUT
                # touching the KV store again; the SPMD contract
                # guarantees it does catch up, and true divergence is
                # still caught by ensure_flushed's covering-boundary
                # deadline.
                with self._lock:
                    if self._next_tid <= last_tid:
                        if self._deferred_boundary is None \
                                or self._deferred_boundary[0] != seq:
                            hvd_metrics.record_boundary("deferred")
                        self._deferred_boundary = (seq, payload)
                        return applied       # ahead of us: defer
                    self._deferred_boundary = None
                    self._boundary_seq += 1
                    self._flush_locked(up_to=last_tid)  # hvdrace: disable=HVR202 -- chaos fault injection (chaos.injector fire) deliberately delays/crashes inside the flush; the perturbation under the lock IS the injected fault
                    hvd_metrics.record_boundary("applied")
            applied = True
            block_ms = 1

    def _follower_loop(self):
        # One LONG-blocking KV get per iteration, not a tight poll: the
        # coordination service blocks server-side until the boundary key
        # appears (or the window expires), so an idle follower costs a few
        # RPCs per second while a published boundary is applied within the
        # window immediately. A cycle_s-paced tight loop here measurably
        # slowed the whole control plane (it shares the coordination
        # service with collective bootstrap).
        # NOTE: _cycle_pause is deliberately ignored here. The pause
        # contract suspends time-triggered flush DECISIONS — those are the
        # coordinator's; a follower only mirrors decisions already made,
        # and suspending that would let coordinator threshold flushes go
        # unapplied (unbounded pending growth, stalled collectives).
        while not self._cycle_stop.wait(0.001):
            try:
                self._apply_ready_boundaries(block_ms=300)
            except Exception:  # noqa: BLE001 — must not kill the thread
                pass

    def fence(self):
        """Order a SYNC eager collective after all in-flight fused async
        work on EVERY process. Without this, the coordinator submits
        [fused-flush, sync-op] while a lagging follower submits
        [sync-op, fused-flush] — mismatched device-collective order, a
        hang or corruption (the reference avoids the class by routing
        every collective through one controller queue). Coordinator:
        flush now (publishing the boundary). Follower: apply boundaries
        until nothing is pending — the SPMD contract guarantees the
        coordinator's fence flushed the same pending set, so the covering
        boundary exists or is in flight. Single-process: device
        submission order is program order already. The fence is also the
        STEP-BOUNDARY await point of the cross-leg overlap: any
        hierarchical bucket's DCN leg still in flight is waited on here,
        booked to the profiler's cross_wait category (outside the flush
        critical path)."""
        if self._inflight_cross:     # unlocked peek: empty = no-op fence
            self._await_cross()
        if not self._multi:
            return
        # Coordinator: flush_all; follower: drain boundaries until the
        # last enqueued tid is covered (== pending empty, since fence
        # runs on the enqueuing thread) — exactly ensure_flushed().
        self.ensure_flushed()

    # ---- cross-leg overlap (hierarchical buckets) -----------------------

    # Inflight-reference bound: beyond this many un-awaited buckets the
    # oldest reference is dropped at append (see _flush_locked).
    _INFLIGHT_CAP = 16

    def _await_cross(self):
        """Block on every in-flight hierarchical bucket's cross leg,
        booking the wall time to the step profiler's ``cross_wait``
        category — the overlap-on A/B's 'wait moved OUT of the flush
        critical path' evidence. The inflight list is popped under the
        lock; the blocking wait runs outside it (a gradient-hook enqueue
        must never queue behind a DCN wait)."""
        with self._lock:
            inflight, self._inflight_cross = self._inflight_cross, []
        if not inflight:
            return
        t0 = time.perf_counter()
        t0_wall = time.time()
        for outs in inflight:
            try:
                jax.block_until_ready(outs)
            except Exception:  # noqa: BLE001 — failures already reached
                pass           # the bucket's handles at dispatch
        dt = time.perf_counter() - t0
        _profile.record_cross_wait(dt)
        _trace.add_span(_trace.get_active(), "cross_wait", t0_wall, dt,
                        cat="train", args={"buckets": len(inflight)})

    def _steer_overlap(self):
        """Per-flush overlap steering from the step profiler's
        collective-vs-compute attribution: compute-dominant steps WIDEN
        the overlap window (await at the fence/step boundary — there is
        backward compute to hide the DCN leg behind), communication-
        dominant steps COLLAPSE it to the next flush (nothing to overlap
        with; earlier backpressure keeps attribution honest). Returns the
        mode in effect ("off" when the knob disables overlap)."""
        if not self._overlap:
            return "off"
        if self._overlap_pinned:
            return self._overlap_mode
        if _profile.armed:
            from horovod_tpu.profile import ledger as _ledger
            rec = _ledger.step_report(1)
            if rec:
                att = rec.get("attribution", {})
                comm = att.get("collective", 0.0) \
                    + att.get("cross_wait", 0.0)
                self._overlap_mode = "next_flush" \
                    if comm > att.get("compute", 0.0) else "step"
        return self._overlap_mode

    def ensure_flushed(self, tid=None, block=True):
        """Make sure the bucket containing ``tid`` has been dispatched.
        Coordinator / single process: flush everything (the classic
        poll-as-cycle-tick). Follower: consume coordinator boundaries until
        the tid is covered — flushing locally on our own trigger would
        split the burst differently from the coordinator. ``block=False``
        (the poll() path) applies only already-published boundaries and
        returns without waiting."""
        if not self._multi or self._coord:
            self.flush_all()
            return
        if tid is None:
            tid = self._next_tid - 1  # hvdrace: disable=HVR203 -- _next_tid increments only on the enqueueing (caller) thread; reading our own counter needs no lock
        if not block:
            self._apply_ready_boundaries(block_ms=1)
            return
        deadline = time.perf_counter() + 120.0
        while True:
            with self._lock:
                if tid <= self._flushed_tid:
                    return
            self._apply_ready_boundaries(block_ms=1000)
            if time.perf_counter() > deadline:
                from horovod_tpu.common.exceptions import \
                    HorovodInternalError
                raise HorovodInternalError(
                    f"no fusion flush boundary covering tid {tid} arrived "
                    f"from the coordinator within 120s — did process 0 "
                    f"dispatch the same async collectives?")

    def cycle_paused(self):
        """Context manager: suspend time-triggered flushes (threshold and
        explicit flushes still apply). Lets tests (and bulk submitters that
        want exactly one bucket) keep the pending-set composition
        deterministic."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            self._cycle_pause = True
            try:
                yield
            finally:
                self._cycle_pause = False

        return _ctx()

    def _bucket_key(self, tensor, op, prescale, postscale):
        dt = jnp.dtype(tensor.dtype) if hasattr(tensor, "dtype") \
            else np.result_type(tensor)
        if self.wire_dtype is not None and jnp.issubdtype(dt, jnp.floating) \
                and not _wire.is_quantized(self.wire_dtype):
            # 16-bit casts make the bucket homogeneous at the wire dtype;
            # a QUANTIZED wire (int8/fp8) keeps each bucket in its
            # ORIGINAL float dtype (the exchange consumes/returns that
            # dtype — folding fp32 and bf16 tensors into one quantized
            # bucket would make the concat heterogeneous).
            dt = jnp.dtype(self.wire_dtype)
        return (ReduceOp(op), float(prescale), float(postscale), str(dt))

    def enqueue_allreduce(self, tensor, op, prescale, postscale, name=None):
        with self._lock:
            tid = self._next_tid
            self._next_tid += 1
            handle = FusedHandle(self, name, tid=tid)
            if not self._pending:
                self._first_enqueue = time.perf_counter()
            self._pending.append((tid, tensor, ReduceOp(op), float(prescale),
                                  float(postscale), handle))
            self._pending_bytes += tensor.nbytes
            self._last_enqueue = time.perf_counter()
            if _flight.armed:
                # seq carries the fusion tid here — the analyzer pairs it
                # with the covering fusion_flush boundary's last tid.
                _flight.record_event("fusion_enqueue", seq=tid,
                                     nbytes=tensor.nbytes, name=name)
            if self._stall_inspector is not None:
                self._stall_inspector.record_enqueue(name or "tensor")
            if self._multi and not self._coord:
                # Followers never trigger flushes: the coordinator's
                # threshold fires at the same enqueue (same byte stream)
                # and publishes the boundary this process will apply. Its
                # native scheduler is fed at boundary time (replaying the
                # exact prefix keeps bucket assembly identical).
                return handle
            if self._native is not None:
                key = self._bucket_key(tensor, op, prescale, postscale)
                if self._native.enqueue(tid, hash(key), tensor.nbytes):
                    self._flush_locked()
            elif self._pending_bytes >= self.threshold:
                self._flush_locked()
        return handle

    def enqueue_grouped_allreduce(self, tensors, op, prescale, postscale,
                                  name=None):
        """Grouped async allreduce: the whole group completes in one flush
        (reference: grouped collectives complete atomically via the
        GroupTable, group_table.h). Same-signature groups are additionally
        registered with the native group table so they share ONE fused
        bucket regardless of the threshold — the reference fuses only
        same-dtype responses, so mixed-signature groups are enqueued
        individually (still atomic: one flush covers all pending buckets)."""
        op = ReduceOp(op)
        with self._lock:
            tids = list(range(self._next_tid,
                              self._next_tid + len(tensors)))
            self._next_tid += len(tensors)
            handles = [FusedHandle(self, f"{name}.{i}" if name else None,
                                   tid=tid)
                       for i, tid in enumerate(tids)]
            keys = [self._bucket_key(t, op, prescale, postscale)
                    for t in tensors]
            follower = self._multi and not self._coord
            if self._native is not None and len(set(keys)) == 1 \
                    and len(tensors) > 1:
                if follower:
                    # registered with the native table at boundary-replay
                    # time, in the same order the coordinator did
                    self._pending_groups.append(list(tids))
                else:
                    self._flushed_groups.append(
                        self._native.register_group(tids))
            flush = False
            if not self._pending and tids:
                self._first_enqueue = time.perf_counter()
            for tid, t, key, h in zip(tids, tensors, keys, handles):
                self._pending.append((tid, t, op, float(prescale),
                                      float(postscale), h))
                self._pending_bytes += t.nbytes
                self._last_enqueue = time.perf_counter()
                if self._native is not None and not follower:
                    flush |= self._native.enqueue(tid, hash(key), t.nbytes)
            if _flight.armed:
                # One event per GROUP (first tid + total bytes), not per
                # tensor: grouped enqueues complete atomically anyway.
                _flight.record_event(
                    "fusion_enqueue", seq=tids[0], name=name,
                    nbytes=sum(t.nbytes for t in tensors),
                    what=f"group{len(tensors)}")
            if self._stall_inspector is not None:
                self._stall_inspector.record_enqueue(name or "grouped")
            if follower:
                # see enqueue_allreduce: boundaries drive follower flushes
                pass
            elif self._native is not None:
                if flush:
                    self._flush_locked()
            elif self._pending_bytes >= self.threshold:
                self._flush_locked()
        return GroupedFusedHandle(handles, name)

    def flush_all(self):
        if self._multi and not self._coord:
            # Followers flush only coordinator-published prefixes; a local
            # flush would split the burst differently from process 0.
            self._apply_ready_boundaries(block_ms=1)
            return
        if self._overlap_mode == "next_flush" and self._inflight_cross:  # hvdrace: disable=HVR203 -- overlap mode is a config string set at init (tuned only between steps on this same thread); stale read is benign
            # Collapsed overlap: bucket k's DCN leg is awaited when bucket
            # k+1's flush needs the wire (outside the lock and outside
            # this flush's bracket — booked to cross_wait).
            self._await_cross()
        with self._lock:
            self._flush_locked()

    def shutdown(self):
        """Flush remaining work and stop background watchdogs."""
        self._cycle_stop.set()
        if self._cycle_thread is not None:
            self._cycle_thread.join(timeout=2)
            self._cycle_thread = None
        if self._multi and not self._coord:
            # Shutdown is SPMD too: the coordinator's shutdown flush
            # publishes the final boundary — drain it (bounded), then fail
            # any handle still unresolved rather than dispatching a
            # mismatched local flush.
            deadline = time.perf_counter() + 30.0
            while time.perf_counter() < deadline:
                with self._lock:
                    if not self._pending:
                        break
                try:
                    self._apply_ready_boundaries(block_ms=500)
                except Exception:  # noqa: BLE001
                    break
        with self._lock:
            if self._multi and not self._coord:
                leftover, self._pending = self._pending, []
                self._pending_bytes = 0
                for _, _, _, _, _, h in leftover:
                    from horovod_tpu.common.exceptions import \
                        HorovodInternalError
                    h._set_error(HorovodInternalError(
                        "fusion shutdown: no coordinator boundary covered "
                        "this tensor"))
            else:
                self._flush_locked()
            # Close the native scheduler under the same lock enqueue holds,
            # so no thread can be inside hvd_sched_enqueue when the C++
            # object is destroyed.
            if self._native is not None:
                self._native.close()
                self._native = None
        # Drain any overlapped cross legs the final flush left in flight.
        self._await_cross()
        if self._publisher_thread is not None:
            # Sentinel AFTER the final flush so its boundary reaches the
            # followers; the join bounds shutdown.
            self._publish_queue.put(None)
            self._publisher_thread.join(timeout=10)
            self._publisher_thread = None
        if self._stall_inspector is not None:
            self._stall_inspector.stop()

    def cache_stats(self):
        """Response-cache statistics from the native scheduler (hits grow as
        steady-state steps reuse the same bucket signatures)."""
        with self._lock:  # shutdown() destroys the native object under it
            if self._native is None:
                return None
            return self._native.cache_stats()

    def _zero_residual(self, mesh, n, flat_len):
        from jax.sharding import NamedSharding
        return _wire.zero_residual(mesh, NamedSharding(mesh, P(HVD_AXIS)),
                                   n, flat_len)

    def _stage_local(self, raw, mesh):
        """Single-process staging for one flush bucket: already-sharded
        jax.Arrays pass through zero-copy; a mismatched jax.Array is
        device_put ONCE per distinct buffer (id-deduped — re-reducing the
        same immutable array many times in one burst, the gradient-hook
        microbench shape, used to pay a python reshard per occurrence);
        host numpy stays raw for the program's own C++ staging (mutable —
        never alias-deduped)."""
        from jax.sharding import NamedSharding
        cached = getattr(self, "_stage_sharding", None)
        if cached is None or cached[0] is not mesh:
            cached = (mesh, NamedSharding(mesh, P(HVD_AXIS)))
            self._stage_sharding = cached
        sharding = cached[1]
        staged_by_id = {}
        out = []
        for t in raw:
            if isinstance(t, jax.Array) and t.sharding != sharding:
                s = staged_by_id.get(id(t))
                if s is None:
                    s = staged_by_id[id(t)] = jax.device_put(t, sharding)
                out.append(s)
            else:
                out.append(t)
        return out

    def _flush_locked(self, up_to=None):
        """Dispatch pending tensors. ``up_to`` (follower boundary replay):
        flush only the prefix with tid <= up_to — the exact set the
        coordinator flushed when it published that boundary."""
        if not self._pending:
            return
        t_flush_wall = time.time()
        # Step-profiler bracket: the flush's wall time minus the fused
        # program dispatches recorded inside it (they book under
        # `collective` via _timeline_op) is the fusion runtime's own
        # overhead — bucket assembly, staging, scheduler bookkeeping.
        profile_on = _profile.armed
        if profile_on:
            t_f0 = time.perf_counter()
            coll0 = _profile.collective_total()
            defer_s = max(t_f0 - self._first_enqueue, 0.0) \
                if self._first_enqueue else 0.0
        if _chaos.armed:
            # Chaos site: a delay here stalls the flush UNDER the runtime
            # lock — every gradient-hook enqueue blocks behind it, the
            # fusion-flush stall mode.
            _chaos.fire("fusion.flush")
        if up_to is None:
            pending, self._pending = self._pending, []
            flushed_bytes, self._pending_bytes = self._pending_bytes, 0
        else:
            pending = [p for p in self._pending if p[0] <= up_to]
            if not pending:
                self._flushed_tid = max(self._flushed_tid, int(up_to))
                return
            self._pending = [p for p in self._pending if p[0] > up_to]
            flushed_bytes = sum(p[1].nbytes for p in pending)
            self._pending_bytes -= flushed_bytes
        if self._multi and not self._coord and self._native is not None:
            # Replay the prefix into the native scheduler now (enqueue-time
            # feeding would leave it holding tids beyond the boundary and
            # its bucket assembly would diverge from the coordinator's).
            flushed = {p[0] for p in pending}
            for gtids in [g for g in self._pending_groups
                          if g[0] in flushed]:
                self._flushed_groups.append(
                    self._native.register_group(gtids))
            self._pending_groups = [g for g in self._pending_groups
                                    if g[0] not in flushed]
            for tid, t, op, pre, post, _ in pending:
                self._native.enqueue(
                    tid, hash(self._bucket_key(t, op, pre, post)), t.nbytes)
        self._flushed_tid = max(self._flushed_tid, pending[-1][0])
        if self._stall_inspector is not None:
            self._stall_inspector.record_flush()
        from horovod_tpu import metrics as hvd_metrics
        hvd_metrics.record_fusion_flush(len(pending), flushed_bytes,
                                        self.threshold)
        if _flight.armed:
            # Flush boundary: the covering tid prefix + bucket size. The
            # fused dispatches below additionally ride the _timeline_op
            # flight bracket like every sync collective.
            _flight.record_event("fusion_flush", seq=pending[-1][0],
                                 nbytes=flushed_bytes,
                                 what=f"n{len(pending)}")
        topo = basics.topology()
        mesh = topo.mesh
        n = topo.size
        # THIS flush's programs use a snapshot of the knobs; tuner updates
        # recorded below take effect from the NEXT flush. (The tuner needs
        # the downgrade verdict — computed from the snapshot during bucket
        # assembly — BEFORE its window closes, and the boundary published
        # to followers must carry the values these programs really used.
        # The one-flush lag on a sweep switch is absorbed by the
        # ParameterManager's per-combo compile-warmup discard.)
        strategy_now, wire_now = self.strategy, self.wire_dtype
        # Cross-slice leg wire snapshot: the coordinator (and single
        # process) re-resolves the per-tier policy chain live; followers
        # keep the value adopted from the boundary.
        if not self._multi or self._coord:
            self.cross_wire = _wire.cross_wire_for("global", self._config)
        cross_now = self.cross_wire
        if not self._multi:
            # Single process: no boundary stream — adopt the snapshot into
            # the eager registries here (multi-process does it at
            # publish/apply time; see _publish_boundary). Defers to an
            # explicit user pin like every runtime sync.
            if wire_now is not None:
                _wire.runtime_sync_wire_dtype(jnp.dtype(wire_now).name,
                                              "global")
            self._sync_eager_policy(strategy_now, cross_now)
        # Bucket assembly: tensors in one bucket share one flat reduction,
        # like responses fused up to the threshold (reference:
        # controller.h:170 FuseResponses). The native scheduler assigns
        # buckets by compatibility key AND closes buckets at the threshold;
        # the Python fallback groups purely by key.
        buckets = {}
        if self._native is not None:
            assignment = self._native.flush()
            # Groups live exactly one flush (reference: DeregisterGroups
            # after the grouped response completes).
            for gid in self._flushed_groups:
                self._native.deregister_group(gid)
            self._flushed_groups = []
            for tid, t, op, pre, post, h in pending:
                bid = assignment.get(tid)
                buckets.setdefault((op, pre, post, bid), []).append((t, h))
        else:
            for tid, t, op, pre, post, h in pending:
                key = self._bucket_key(t, op, pre, post)
                buckets.setdefault((op, pre, post, key[-1]), []).append((t, h))
        from horovod_tpu.common.process_sets import global_process_set
        from horovod_tpu.ops.collective_ops import _active_mask
        active_mask = _active_mask(global_process_set)
        # Pass 1: effective strategy per bucket (the 2-level strategies
        # apply to the linear reductions without a join mask; everything
        # else stays flat) — the downgrade verdict must reach the tuner
        # BEFORE its window closes below.
        downgraded = False
        plan = []
        from horovod_tpu.ops.collective_ops import _hier_mesh, _live_slices
        slices_now, _ = _live_slices(n)
        for (op, pre, post, _), items in buckets.items():
            strategy = strategy_now
            if strategy != "flat" and (
                    op not in (ReduceOp.SUM, ReduceOp.AVERAGE)
                    or active_mask is not None
                    or getattr(topo, "mesh2d", None) is None
                    # torus_qcross requires a real slice hierarchy: over
                    # a 1-slice layout the decomposition is pure overhead
                    # and its lossy cross leg buys nothing (hvdlint
                    # HVP113) — same refusal as the eager verdict and the
                    # static model, so the per-tier cross-check stays
                    # exact.
                    or (strategy == "torus_qcross" and slices_now <= 1)):
                strategy = "flat"
                downgraded = True
            plan.append((op, pre, post, items, strategy))
        if self._parameter_manager is not None:
            if downgraded:
                # Keep the sweep from attributing these flat timings to
                # the configured 2-level combo.
                self._parameter_manager.invalidate_window()
            update = self._parameter_manager.record(flushed_bytes)
            if update is not None:
                self.threshold, new_cycle_ms, cats = update
                # Consumed live by the cycle thread on its next wake; the
                # strategy/wire knobs take effect from the NEXT flush.
                self._cycle_s = max(new_cycle_ms, 1e-3) / 1000.0
                if "strategy" in cats:
                    self.strategy = cats["strategy"]
                if "wire_dtype" in cats:
                    self.wire_dtype = jnp.dtype(cats["wire_dtype"]).type
        if self._multi and self._coord:
            # Tell the followers to flush this exact prefix with the
            # knobs these programs really use (the snapshot).
            self._publish_boundary(pending[-1][0], strategy_now, wire_now,
                                   cross_now)
        overlap_mode = self._steer_overlap()
        # Pass 2: build + dispatch.
        for op, pre, post, items, strategy in plan:
            raw = [i[0] for i in items]
            # Donate per argument, and only inputs staged from the HOST
            # (numpy/torch/etc. → staging always copies): a jax.Array
            # input with a matching sharding may ALIAS the staged buffer,
            # and donating it would invalidate the caller's array.
            donate = tuple(i for i, t in enumerate(raw)
                           if not isinstance(t, jax.Array)) \
                if self._donate else ()
            if self._multi:
                tensors = _prepare(raw, mesh, n, "fused_allreduce")
            else:
                tensors = self._stage_local(raw, mesh)
            shapes = tuple(tuple(t.shape) for t in tensors)
            dtypes = tuple(np.dtype(t.dtype).name for t in tensors)
            if self._native is not None:
                # Steady-state training flushes the same bucket signatures
                # every step; the native LRU mirrors the reference's
                # response cache and exposes hit-rate stats (cache_stats()).
                self._native.cache_lookup(
                    hash((op, pre, post, shapes, dtypes)))
            # Quantized-wire verdict for THIS bucket (static facts only —
            # the compiled program reaches the same verdict from the same
            # inputs, so the residual argument list always matches).
            sizes = [int(np.prod(s[1:])) for s in shapes]
            quant_label = _bucket_quant(wire_now, strategy,
                                        active_mask is not None, op,
                                        sizes, dtypes, n)
            use_ef = self._wire_ef and quant_label is not None
            # Hierarchical (2-level) bucket: resolve the decomposition
            # mesh live (the forced/virtual slice hierarchy wins over the
            # host-boundary mesh2d) and, for torus_qcross, the STATIC
            # cross-leg facts the program reaches identically.
            hier_bucket = strategy != "flat" and op != ReduceOp.ADASUM
            bucket_cross = cross_now if strategy == "torus_qcross" else ""
            prog_mesh = mesh
            hier_facts = None
            if strategy != "flat":
                prog_mesh = _hier_mesh(mesh, slices_now) if slices_now > 1 \
                    else topo.mesh2d
                if strategy == "torus_qcross":
                    all_float = all(
                        jnp.issubdtype(jnp.dtype(d), jnp.floating)
                        for d in dtypes)
                    hier_facts = _hier_bucket_facts(prog_mesh, sum(sizes),
                                                    bucket_cross,
                                                    all_float)
            use_hier_ef = self._wire_ef and hier_facts is not None \
                and hier_facts["cross_label"] is not None
            fkey = (mesh, op, pre, post, shapes, dtypes, wire_now,
                    active_mask, strategy, donate, use_ef or use_hier_ef,
                    bucket_cross, prog_mesh)
            prog = _flush_plans.get(fkey)
            if prog is None:
                if len(_flush_plans) >= 2048:   # runaway-signature guard
                    _flush_plans.clear()
                prog = _flush_plans[fkey] = _fused_program(
                    prog_mesh, n, op, pre, post, shapes, dtypes, wire_now,
                    active_mask, strategy, donate, use_ef or use_hier_ef,
                    bucket_cross)
            args = list(tensors)
            ef_key = ("fusion", fkey)
            if use_ef:
                res = _wire.ef_get(ef_key)
                if res is None:
                    res = self._zero_residual(mesh, n, sum(sizes))
                args.append(res)
            elif use_hier_ef:
                # The torus_qcross residual covers the CROSS leg's shard
                # only, sharded over the decomposition mesh.
                res = _wire.ef_get(ef_key)
                if res is None:
                    from horovod_tpu.common.topology import (CROSS_AXIS,
                                                             LOCAL_AXIS)
                    from jax.sharding import NamedSharding
                    res = _wire.zero_residual(
                        prog_mesh,
                        NamedSharding(prog_mesh, P((CROSS_AXIS,
                                                    LOCAL_AXIS))),
                        n, hier_facts["shard_elems"])
                args.append(res)
            # Wire accounting for the bucket (buckets are dtype-
            # homogeneous, so dtypes[0] stands for the payload).
            bucket_bytes = sum(
                int(np.prod(s)) * np.dtype(d).itemsize
                for s, d in zip(shapes, dtypes))
            eff_wire = quant_label or (
                jnp.dtype(wire_now).name
                if wire_now is not None
                and not _wire.is_quantized(wire_now)
                and strategy != "torus_qcross"
                and np.issubdtype(np.dtype(dtypes[0]), np.floating)
                else dtypes[0])
            if hier_bucket and slices_now > 1 \
                    and strategy in ("torus", "torus_qcross"):
                # Per-tier accounting of the decomposition (the same
                # wire.hierarchical_wire_bytes integers the static
                # model's hierarchical what-if predicts): ICI legs at the
                # effective payload width, the DCN leg at the cross wire.
                # Gated on a REAL slice hierarchy — over the 1-slice
                # mesh2d fallback the "cross" axis is the host boundary
                # inside one slice, where the static model (rightly)
                # predicts zero DCN; the legacy "hierarchical" strategy
                # keeps the flat-formula accounting below for the same
                # reason (its whole-buffer cross has no static mirror).
                from horovod_tpu.common.topology import CROSS_AXIS
                width = np.dtype(eff_wire).itemsize
                h = _wire.hierarchical_wire_bytes(
                    sum(sizes), n, int(prog_mesh.shape[CROSS_AXIS]),
                    width,
                    cross_wire=(hier_facts or {}).get("cross_label") or "")
                cross_label = h["cross_label"]
                wire_recs = [
                    ("fused", eff_wire, h["ici"],
                     eff_wire != dtypes[0], {"ici": h["ici"]}),
                    ("fused", cross_label or eff_wire, h["dcn"],
                     cross_label is not None or eff_wire != dtypes[0],
                     {"dcn": h["dcn"]})]
                wire_nbytes = h["ici"] + h["dcn"]
            else:
                wire_nbytes = _wire.allreduce_wire_bytes(
                    bucket_bytes, np.dtype(dtypes[0]).itemsize, n,
                    eff_wire)
                tiers = None
                if quant_label is not None:
                    from horovod_tpu.ops.collective_ops import \
                        _quantized_wire_tiers
                    tiers = _quantized_wire_tiers(sum(sizes), n,
                                                  list(range(n)))
                wire_recs = [("fused", eff_wire, wire_nbytes,
                              eff_wire != dtypes[0], tiers)]
            # _timeline_op supplies BOTH the timeline span and the
            # transport-failure → HorovodInternalError translation: a peer
            # dying mid fused collective must be recoverable by the elastic
            # @run wrapper exactly like the sync ops (the async path is the
            # DistributedOptimizer hot path). Failures are delivered to the
            # bucket's HANDLES (raised at synchronize) rather than raised
            # here — the flush may be running on the cycle thread, where
            # there is no caller.
            from horovod_tpu.ops.collective_ops import _timeline_op
            any_ef = use_ef or use_hier_ef
            try:
                with _timeline_op(f"fused_allreduce[{len(items)}]",
                                  "ALLREDUCE", tensors, wire=wire_recs):
                    outs = prog(*args)
                    if any_ef:
                        # The residual stays a device-resident global
                        # array between flushes; the next key-matched
                        # bucket feeds it straight back.
                        _wire.ef_put(ef_key, outs[-1])
                        outs = outs[:-1]
                    # Multi-process: hand back this process's local rows,
                    # matching the sync ops' contract.
                    outs = _localize(list(outs), mesh)
                    if hier_bucket and overlap_mode == "off":
                        # Overlap collapsed entirely: the cross leg's
                        # wait lands INSIDE the flush bracket (booked to
                        # collective — the A/B's baseline arm).
                        jax.block_until_ready(outs)
            except Exception as e:  # noqa: BLE001
                # A failed dispatch also evicts its flush plan (never pin
                # a program that just raised — rebuild costs one lru hit)
                # and its residual (its pairing with the result stream is
                # broken; after elastic recovery it would be a
                # dead-backend array).
                _flush_plans.pop(fkey, None)
                if any_ef:
                    _wire.ef_pop(ef_key)
                for _, h in items:
                    h._set_error(e)
                continue
            if hier_bucket and overlap_mode != "off":
                # Overlap on: leave the DCN leg in flight; the await
                # happens at the mode's deferred sync point (next flush /
                # fence / shutdown) and books to cross_wait. Runs under
                # self._lock (we are inside _flush_locked). BOUNDED: a
                # pure-async workload that never fences must not pin an
                # unbounded tail of result buffers — beyond the cap the
                # oldest entry is simply dropped (its handles own the
                # arrays; only the cross_wait attribution for that bucket
                # is forfeited, never correctness).
                if len(self._inflight_cross) >= self._INFLIGHT_CAP:
                    self._inflight_cross.pop(0)
                self._inflight_cross.append(outs)
            for (_, h), o in zip(items, outs):
                h._set(o)
        if profile_on:
            self._first_enqueue = 0.0 if not self._pending \
                else self._first_enqueue
            _profile.record_fusion_flush(
                time.perf_counter() - t_f0,
                _profile.collective_total() - coll0, defer_s,
                wire_dtype=jnp.dtype(wire_now).name if wire_now else None,
                wire_bytes=flushed_bytes)
        # Mirror registry totals into the timeline as counter events
        # (throttled inside), so aggregate series and op spans land in the
        # same chrome://tracing file.
        tl = basics.timeline()
        if tl is not None:
            hvd_metrics.maybe_emit_timeline_counters(tl)
        # Whole-flush span under the active step trace (the per-bucket
        # dispatch spans above nest beside it in the same tree).
        _trace.add_span(_trace.get_active(), "fusion_flush", t_flush_wall,
                        time.time() - t_flush_wall, cat="train",
                        args={"n": len(pending), "bytes": flushed_bytes})


class GroupedFusedHandle:
    """One handle for a whole grouped enqueue; resolves to the list of
    reduced tensors (reference: grouped ops return one handle,
    torch/mpi_ops.py grouped_allreduce_async)."""

    __slots__ = ("_handles", "name")

    def __init__(self, handles, name):
        self._handles = handles
        self.name = name

    def poll(self):
        return all(h.poll() for h in self._handles)

    def synchronize(self):
        return [h.synchronize() for h in self._handles]


def get_runtime():
    st = basics._get_state()
    if st.fusion is None:
        from horovod_tpu.ops.fusion import FusionRuntime
        st.fusion = FusionRuntime(st.config)
    return st.fusion
