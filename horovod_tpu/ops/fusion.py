"""Tensor-fusion (bucketing) runtime for the eager path.

Reference mechanism (horovod/common/fusion_buffer_manager.h:30-62 + the cycle
loop operations.cc:747-853): small tensors submitted within one cycle are
memcpy'd into a persistent fusion buffer and reduced with ONE collective, then
scattered back out; buffer capacity is ``HOROVOD_FUSION_THRESHOLD`` (128 MB)
and the loop wakes every ``HOROVOD_CYCLE_TIME`` (1 ms).

TPU-native design: no memcpy staging — pending tensors are raveled and
concatenated *inside one jitted program* per (names, shapes, dtypes, op)
signature, reduced with a single ``psum`` on the flat buffer, and split back,
all fused by XLA. The signature-keyed program cache means a steady-state
training loop hits the same compiled fused program every step (the
response-cache fast path, reference: response_cache.h:45).

Flush triggers: pending bytes >= fusion_threshold, an explicit
``synchronize()``/``poll()`` on any returned handle, ``flush_all()``, or the
background cycle thread — which is DEBOUNCED (fires after one
``HOROVOD_CYCLE_TIME`` of enqueue quiescence) so that a burst of hook
enqueues is never split at arbitrary time boundaries: stable burst → stable
bucket signature → compiled-program cache hit.
"""

import functools
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.common import basics
from horovod_tpu.common.topology import HVD_AXIS
from horovod_tpu.ops.collective_ops import (ReduceOp, _localize, _prepare,
                                            _reduce_shard)


class FusedHandle:
    """Handle for a tensor pending in the fusion queue. Resolves after the
    bucket it lands in is flushed (reference analog: HandleManager int handle
    + per-entry callback, torch/handle_manager.h)."""

    __slots__ = ("_runtime", "_result", "_error", "name")

    def __init__(self, runtime, name):
        self._runtime = runtime
        self._result = None
        self._error = None
        self.name = name

    def _set(self, value):
        self._result = value

    def _set_error(self, exc):
        # Failure delivery for flushes that run on the cycle thread, where
        # there is no caller to raise to (reference: per-tensor status
        # callbacks carry the error, operations.cc entry.FinishWithCallback).
        self._error = exc

    def poll(self):
        if self._error is not None:
            return True  # "complete": synchronize() will raise it
        if self._result is None:
            # Polling also acts as a cycle tick: a pending bucket is flushed
            # the first time anyone asks about it.
            self._runtime.flush_all()
        if self._error is not None:
            return True
        return all(o.is_ready() if hasattr(o, "is_ready") else True
                   for o in jax.tree_util.tree_leaves(self._result))

    def synchronize(self):
        if self._error is None and self._result is None:
            self._runtime.flush_all()
        if self._error is not None:
            raise self._error
        jax.block_until_ready(self._result)
        return self._result


@functools.lru_cache(maxsize=2048)
def _fused_program(mesh, n, op, prescale, postscale, shapes, dtypes,
                   wire_dtype, active_mask=None):
    """One flat-buffer reduction for a whole bucket. ``active_mask`` carries
    join state so async collectives honor the same joined-rank exclusion as
    the sync path (reference: joined_size accounting)."""
    sizes = [int(np.prod(s[1:])) for s in shapes]
    active = None if active_mask is None else np.array(active_mask)

    def body(*xs):
        # xs: local slices (1, ...). Flatten each, concat per the bucket
        # layout (the MemcpyInFusionBuffer analog, fused by XLA into the
        # collective's input), one psum, then split back out. Buckets are
        # formed per effective wire dtype so the concat is homogeneous.
        # Adasum must normalize per-tensor (its coefficients are norms of the
        # individual gradients, reference: adasum.h:103+), so its tensors are
        # reduced individually inside the single dispatch instead of fused.
        if op == ReduceOp.ADASUM:
            return tuple(
                _reduce_shard(x, op, n, prescale, postscale, HVD_AXIS, active)
                for x in xs)
        flats = []
        for x in xs:
            f = x.reshape(-1)
            if wire_dtype is not None and jnp.issubdtype(f.dtype, jnp.floating):
                f = f.astype(wire_dtype)
            flats.append(f)
        buf = jnp.concatenate(flats) if len(flats) > 1 else flats[0]
        buf = _reduce_shard(buf[None], op, n, prescale, postscale, HVD_AXIS,
                            active)[0]
        outs, off = [], 0
        for x, sz in zip(xs, sizes):
            piece = lax.slice_in_dim(buf, off, off + sz).astype(x.dtype)
            outs.append(piece.reshape(x.shape))
            off += sz
        return tuple(outs)

    f = jax.shard_map(body, mesh=mesh,
                      in_specs=tuple(P(HVD_AXIS) for _ in shapes),
                      out_specs=tuple(P(HVD_AXIS) for _ in shapes))
    return jax.jit(f)


class FusionRuntime:
    # Forwarded to the native scheduler so runtime threshold changes (the
    # autotuner, tests) affect its flush decision too.
    @property
    def threshold(self):
        return self._threshold

    @threshold.setter
    def threshold(self, value):
        self._threshold = value
        if getattr(self, "_native", None) is not None:
            self._native.set_threshold(value)

    def __init__(self, config):
        self.threshold = config.fusion_threshold
        self.wire_dtype = jnp.dtype(config.wire_dtype).type \
            if config.wire_dtype else None
        self._lock = threading.RLock()
        self._pending = []  # (tid, tensor, op, prescale, postscale, handle)
        self._pending_bytes = 0
        self._last_enqueue = 0.0
        self._next_tid = 0
        self._flushed_groups = []  # group ids to deregister after flush
        # Native C++ scheduler for the per-step bookkeeping (bucket assembly,
        # LRU response-cache stats, group table); Python fallback below is
        # behavior-identical (reference: the C++ cycle loop/fusion manager,
        # operations.cc:747-853).
        self._native = None
        try:
            from horovod_tpu import native
            if native.native_built():
                self._native = native.BucketScheduler(
                    self.threshold, config.cache_capacity)
        except Exception:
            self._native = None
        self._parameter_manager = None
        if config.autotune:
            from horovod_tpu.autotune import ParameterManager
            self._parameter_manager = ParameterManager(
                warmup_samples=config.autotune_warmup_samples,
                steps_per_sample=config.autotune_steps_per_sample,
                bayes_opt_max_samples=config.autotune_bayes_opt_max_samples,
                gaussian_process_noise=config.autotune_gaussian_process_noise,
                log_file=config.autotune_log_file or None,
                initial_threshold=config.fusion_threshold,
                initial_cycle_ms=config.cycle_time_ms)
        self._stall_inspector = None
        if not config.stall_check_disable:
            from horovod_tpu.ops.stall_inspector import StallInspector
            self._stall_inspector = StallInspector(
                warning_secs=config.stall_check_time_seconds,
                shutdown_secs=config.stall_shutdown_time_seconds)
        # The cycle loop (reference: RunLoopOnce wakes every
        # HOROVOD_CYCLE_TIME ms, operations.cc:747-756): without it, async
        # enqueues below the fusion threshold sit until someone polls —
        # torch-style grad hooks would get no reduction/backward overlap.
        self._cycle_stop = threading.Event()
        self._cycle_pause = False
        self._cycle_thread = None
        self._cycle_s = max(float(config.cycle_time_ms), 0.0) / 1000.0
        # SINGLE-process only: the timer is rank-local wall clock. In a
        # multi-process job two ranks could split the same enqueue burst at
        # different points and issue mismatched collectives (the reference
        # may fuse per-cycle only because its coordinator negotiates the
        # ready set across ranks first, controller.cc:74). Multi-process
        # flush triggers stay the SPMD-deterministic ones: threshold,
        # poll/synchronize, flush_all.
        if self._cycle_s > 0 and jax.process_count() <= 1:
            self._cycle_thread = threading.Thread(
                target=self._cycle_loop, daemon=True,
                name="hvd-fusion-cycle")
            self._cycle_thread.start()

    def _cycle_loop(self):
        while not self._cycle_stop.wait(self._cycle_s):
            # Debounced: flush only after a full cycle with NO new
            # enqueues. Flushing mid-burst would split the pending set at
            # arbitrary time boundaries — different bucket signatures every
            # step, defeating the compiled-program cache that is this
            # runtime's steady-state fast path (the guard in
            # test_perf_guards asserts zero warm-pass compiles).
            if self._pending and not self._cycle_pause and \
                    time.perf_counter() - self._last_enqueue >= \
                    self._cycle_s:
                try:
                    # Reference: RunLoopOnce emits a CYCLE_START instant per
                    # loop when --timeline-mark-cycles is on
                    # (operations.cc:759-762).
                    from horovod_tpu.common import basics
                    tl = basics.timeline()
                    if tl is not None:
                        tl.mark_cycle()
                    self.flush_all()
                except Exception:  # noqa: BLE001
                    # _flush_locked delivers failures to the affected
                    # handles; anything escaping here must not kill the
                    # cycle thread (the reference's background loop
                    # likewise outlives op failures).
                    pass

    def cycle_paused(self):
        """Context manager: suspend time-triggered flushes (threshold and
        explicit flushes still apply). Lets tests (and bulk submitters that
        want exactly one bucket) keep the pending-set composition
        deterministic."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            self._cycle_pause = True
            try:
                yield
            finally:
                self._cycle_pause = False

        return _ctx()

    def _bucket_key(self, tensor, op, prescale, postscale):
        dt = jnp.dtype(tensor.dtype) if hasattr(tensor, "dtype") \
            else np.result_type(tensor)
        if self.wire_dtype is not None and jnp.issubdtype(dt, jnp.floating):
            dt = jnp.dtype(self.wire_dtype)
        return (ReduceOp(op), float(prescale), float(postscale), str(dt))

    def enqueue_allreduce(self, tensor, op, prescale, postscale, name=None):
        handle = FusedHandle(self, name)
        with self._lock:
            tid = self._next_tid
            self._next_tid += 1
            self._pending.append((tid, tensor, ReduceOp(op), float(prescale),
                                  float(postscale), handle))
            self._pending_bytes += tensor.nbytes
            self._last_enqueue = time.perf_counter()
            if self._stall_inspector is not None:
                self._stall_inspector.record_enqueue(name or "tensor")
            if self._native is not None:
                key = self._bucket_key(tensor, op, prescale, postscale)
                if self._native.enqueue(tid, hash(key), tensor.nbytes):
                    self._flush_locked()
            elif self._pending_bytes >= self.threshold:
                self._flush_locked()
        return handle

    def enqueue_grouped_allreduce(self, tensors, op, prescale, postscale,
                                  name=None):
        """Grouped async allreduce: the whole group completes in one flush
        (reference: grouped collectives complete atomically via the
        GroupTable, group_table.h). Same-signature groups are additionally
        registered with the native group table so they share ONE fused
        bucket regardless of the threshold — the reference fuses only
        same-dtype responses, so mixed-signature groups are enqueued
        individually (still atomic: one flush covers all pending buckets)."""
        handles = [FusedHandle(self, f"{name}.{i}" if name else None)
                   for i in range(len(tensors))]
        op = ReduceOp(op)
        with self._lock:
            tids = list(range(self._next_tid,
                              self._next_tid + len(tensors)))
            self._next_tid += len(tensors)
            keys = [self._bucket_key(t, op, prescale, postscale)
                    for t in tensors]
            if self._native is not None and len(set(keys)) == 1 \
                    and len(tensors) > 1:
                self._flushed_groups.append(
                    self._native.register_group(tids))
            flush = False
            for tid, t, key, h in zip(tids, tensors, keys, handles):
                self._pending.append((tid, t, op, float(prescale),
                                      float(postscale), h))
                self._pending_bytes += t.nbytes
                self._last_enqueue = time.perf_counter()
                if self._native is not None:
                    flush |= self._native.enqueue(tid, hash(key), t.nbytes)
            if self._stall_inspector is not None:
                self._stall_inspector.record_enqueue(name or "grouped")
            if self._native is not None:
                if flush:
                    self._flush_locked()
            elif self._pending_bytes >= self.threshold:
                self._flush_locked()
        return GroupedFusedHandle(handles, name)

    def flush_all(self):
        with self._lock:
            self._flush_locked()

    def shutdown(self):
        """Flush remaining work and stop background watchdogs."""
        self._cycle_stop.set()
        if self._cycle_thread is not None:
            self._cycle_thread.join(timeout=2)
            self._cycle_thread = None
        with self._lock:
            # Close the native scheduler under the same lock enqueue holds,
            # so no thread can be inside hvd_sched_enqueue when the C++
            # object is destroyed.
            self._flush_locked()
            if self._native is not None:
                self._native.close()
                self._native = None
        if self._stall_inspector is not None:
            self._stall_inspector.stop()

    def cache_stats(self):
        """Response-cache statistics from the native scheduler (hits grow as
        steady-state steps reuse the same bucket signatures)."""
        with self._lock:  # shutdown() destroys the native object under it
            if self._native is None:
                return None
            return self._native.cache_stats()

    def _flush_locked(self):
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        flushed_bytes, self._pending_bytes = self._pending_bytes, 0
        if self._stall_inspector is not None:
            self._stall_inspector.record_flush()
        if self._parameter_manager is not None:
            update = self._parameter_manager.record(flushed_bytes)
            if update is not None:
                self.threshold, new_cycle_ms = update
                # Consumed live by the cycle thread on its next wake.
                self._cycle_s = max(new_cycle_ms, 1e-3) / 1000.0
        topo = basics.topology()
        mesh = topo.mesh
        n = topo.size
        # Bucket assembly: tensors in one bucket share one flat reduction,
        # like responses fused up to the threshold (reference:
        # controller.h:170 FuseResponses). The native scheduler assigns
        # buckets by compatibility key AND closes buckets at the threshold;
        # the Python fallback groups purely by key.
        buckets = {}
        if self._native is not None:
            assignment = self._native.flush()
            # Groups live exactly one flush (reference: DeregisterGroups
            # after the grouped response completes).
            for gid in self._flushed_groups:
                self._native.deregister_group(gid)
            self._flushed_groups = []
            for tid, t, op, pre, post, h in pending:
                bid = assignment.get(tid)
                buckets.setdefault((op, pre, post, bid), []).append((t, h))
        else:
            for tid, t, op, pre, post, h in pending:
                key = self._bucket_key(t, op, pre, post)
                buckets.setdefault((op, pre, post, key[-1]), []).append((t, h))
        from horovod_tpu.common.process_sets import global_process_set
        from horovod_tpu.ops.collective_ops import _active_mask
        active_mask = _active_mask(global_process_set)
        for (op, pre, post, _), items in buckets.items():
            tensors = [i[0] for i in items]
            tensors = _prepare(tensors, mesh, n, "fused_allreduce")
            shapes = tuple(tuple(t.shape) for t in tensors)
            dtypes = tuple(str(t.dtype) for t in tensors)
            if self._native is not None:
                # Steady-state training flushes the same bucket signatures
                # every step; the native LRU mirrors the reference's
                # response cache and exposes hit-rate stats (cache_stats()).
                self._native.cache_lookup(
                    hash((op, pre, post, shapes, dtypes)))
            prog = _fused_program(mesh, n, op, pre, post, shapes, dtypes,
                                  self.wire_dtype, active_mask)
            # _timeline_op supplies BOTH the timeline span and the
            # transport-failure → HorovodInternalError translation: a peer
            # dying mid fused collective must be recoverable by the elastic
            # @run wrapper exactly like the sync ops (the async path is the
            # DistributedOptimizer hot path). Failures are delivered to the
            # bucket's HANDLES (raised at synchronize) rather than raised
            # here — the flush may be running on the cycle thread, where
            # there is no caller.
            from horovod_tpu.ops.collective_ops import _timeline_op
            try:
                with _timeline_op(f"fused_allreduce[{len(items)}]",
                                  "ALLREDUCE"):
                    outs = prog(*tensors)
                    # Multi-process: hand back this process's local rows,
                    # matching the sync ops' contract.
                    outs = _localize(list(outs), mesh)
            except Exception as e:  # noqa: BLE001
                for _, h in items:
                    h._set_error(e)
                continue
            for (_, h), o in zip(items, outs):
                h._set(o)


class GroupedFusedHandle:
    """One handle for a whole grouped enqueue; resolves to the list of
    reduced tensors (reference: grouped ops return one handle,
    torch/mpi_ops.py grouped_allreduce_async)."""

    __slots__ = ("_handles", "name")

    def __init__(self, handles, name):
        self._handles = handles
        self.name = name

    def poll(self):
        return all(h.poll() for h in self._handles)

    def synchronize(self):
        return [h.synchronize() for h in self._handles]


def get_runtime():
    st = basics._get_state()
    if st.fusion is None:
        from horovod_tpu.ops.fusion import FusionRuntime
        st.fusion = FusionRuntime(st.config)
    return st.fusion
