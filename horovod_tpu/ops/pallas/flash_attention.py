"""Flash attention as a Pallas TPU kernel.

The hot op of every transformer in the model zoo (models/bert.py,
models/gpt.py, parallel/tp.py). Tiled online-softmax attention: for each
query block the kernel streams key/value blocks through VMEM, keeping the
running max/denominator in registers — O(L) memory instead of materializing
the (L, L) score matrix, and every matmul lands on the MXU as a
(block_q x D) @ (D x block_k) tile.

The reference framework has no attention code (SURVEY.md §5.7 — Horovod
operates below the model level); this kernel is part of the TPU build's
model-level capability, in the spirit of the reference's hand-written CUDA
hot loops (reference: horovod/common/ops/cuda/cuda_kernels.cu).

Backward pass: custom VJP using the saved per-row logsumexp, fused as two
Pallas kernels on TPU (a dQ pass tiled over query blocks and a dK/dV pass
tiled over key blocks, each recomputing its score tile in VMEM) — O(L)
memory end to end. Interpret mode (CPU tests) keeps the plain jnp backward,
which doubles as the numerical oracle for the kernels.

On CPU (tests, no TPU) the kernel runs through the Pallas interpreter.
Sequence lengths with no aligned block size are padded to the next block
multiple with the padding masked inside the kernels (kv_valid), so
arbitrary lengths run the kernel path.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific bits are absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30  # finite big-negative: avoids inf-inf NaNs in the masking

try:  # pre-VMA jax (< 0.7): ShapeDtypeStruct has no ``vma`` kwarg
    jax.ShapeDtypeStruct((1,), jnp.float32, vma=frozenset())
    _SDS_TAKES_VMA = True
except TypeError:
    _SDS_TAKES_VMA = False


def _out_struct(shape, dtype, vma):
    """ShapeDtypeStruct carrying the varying-manual-axes set when this jax
    understands it. On pre-VMA jax the computed ``vma`` is always empty
    (avals have no ``vma`` attribute), so omitting the kwarg is exact."""
    if _SDS_TAKES_VMA:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _interpret():
    return jax.default_backend() != "tpu"


def _pick_block(length, cap=1024):
    # 512-row tiles keep the MXU fed far better than 128 (measured on v5e:
    # 32.1k -> 70.5k tok/s on GPT-2 @4k); 1024 overflows scoped VMEM.
    # HVD_FLASH_BLOCK caps the tile lower for on-chip sweeps (the MFU
    # tuning loop: sweep 128/256/512 per model without code edits).
    import os
    env_cap = os.environ.get("HVD_FLASH_BLOCK")
    if env_cap:
        cap = min(cap, int(env_cap))
    for b in (cap, 512, 256, 128, 64, 32, 16, 8):
        if b <= cap and length % b == 0:
            return b
    return None


def _scratch(shape):
    """VMEM scratch accumulator (persists across the sequential innermost
    grid sweep on one core). Callers guard on ``pltpu is not None``."""
    return pltpu.VMEM(shape, jnp.float32)


def _compiler_params():
    """Raise mosaic's scoped-VMEM budget (default 16 MB) — the 512-row MXU
    tiles this kernel prefers need ~17-32 MB of stack at long context; v5e
    has far more physical VMEM than the default budget admits."""
    if pltpu is None or _interpret():
        return None
    return pltpu.CompilerParams(vmem_limit_bytes=64 * 1024 * 1024)


def _pick_chunk(length, block, cap=4096):
    """Largest multiple of ``block`` dividing ``length``, capped.

    The chunk is the unit the grid streams through VMEM (bounding VMEM at
    O(chunk) so 8k+ contexts fit the ~16 MB scoped budget); within a chunk
    a register-carried fori_loop sweeps ``block``-sized MXU tiles (grid
    steps are too fine-grained to carry the softmax state efficiently).
    """
    c = min(length, cap)
    while c > block and length % c:
        c -= block
    return c


def _apply_mask(s, *, causal, masked, q0, k0, kv_valid, block_q, block_k):
    """Combined causal + key-validity masking for one (BQ, BK) score tile.

    ``masked`` (static) is True when the key axis was padded to a block
    multiple: keys at global position >= kv_valid are padding and must not
    receive weight. ``q0``/``k0`` are the tile's global row/key offsets.
    """
    if not (causal or masked):
        return s
    k_pos = k0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    ok = None
    if masked:
        ok = k_pos < kv_valid
    if causal:
        q_pos = q0 + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        c = q_pos >= k_pos
        ok = c if ok is None else ok & c
    return jnp.where(ok, s, NEG_INF)


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
               *, sm_scale, causal, block_q, block_k, k_chunk, q_offset,
               n_kc, kv_valid, masked):
    """One (query-block, key-chunk) grid step of the online softmax.

    The key-chunk sweep is the INNERMOST grid dimension; the running
    (m, l, acc) state lives in VMEM scratch across chunk steps and in
    registers within the chunk's fori tile sweep.
    """
    qi = pl.program_id(1)
    # Single-chunk grids (n_kc == 1) are specialized to STATIC control
    # flow: jc is the literal 0, init/finalize run unconditionally, and
    # the masked trip count below is a compile-time constant. The generic
    # path's pl.when(contributes) + dynamically-clipped fori_loop is only
    # ever needed when the chunk index is a real grid variable; on padded
    # single-chunk grids it is the suspected Mosaic compile hang
    # (docs/troubleshooting.md "Padded flash attention").
    single = n_kc == 1
    jc = 0 if single else pl.program_id(2)

    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    if single:
        _init()
    else:
        pl.when(jc == 0)(_init)

    # End-aligned causal convention (tril with k = Lk - Lq), matching
    # local_attention and the backward pass: query row i may attend keys
    # <= i + (Lk - Lq). q_offset = Lk - Lq.
    q_end = q_offset + (qi + 1) * block_q - 1  # last query row's key bound
    contributes = None                 # None == statically always-true
    if causal:
        contributes = q_end >= jc * k_chunk
    if masked and not single:
        c = jc * k_chunk < kv_valid
        contributes = c if contributes is None else contributes & c

    def _compute():
        q = q_ref[0].astype(jnp.float32) * sm_scale        # (BQ, D)

        def body(t, carry):
            m, l, acc = carry
            kb = k_ref[0, pl.ds(t * block_k, block_k), :].astype(jnp.float32)
            vb = v_ref[0, pl.ds(t * block_k, block_k), :].astype(jnp.float32)
            s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            s = _apply_mask(s, causal=causal, masked=masked,
                            q0=q_offset + qi * block_q,
                            k0=jc * k_chunk + t * block_k,
                            kv_valid=kv_valid, block_q=block_q,
                            block_k=block_k)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            corr = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[:, None])
            # Rows where every score is masked give exp(0)=1; zero them.
            p = jnp.where(s > NEG_INF * 0.5, p, 0.0)
            l_new = l * corr + jnp.sum(p, axis=-1)
            acc_new = acc * corr[:, None] + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        n_t = k_chunk // block_k
        if masked and single:
            # The chunk starts at key 0, so the last valid key tile is a
            # compile-time constant: a static trip count, no dynamic clip.
            n_t = min(n_t, max(0, (kv_valid + block_k - 1) // block_k))
        if causal:
            # Bound the tile sweep at the diagonal within this chunk.
            n_t = jnp.clip(
                pl.cdiv(q_end + 1 - jc * k_chunk, block_k), 0, n_t)
        if masked and not single:
            # ...and at the last VALID key tile.
            n_t = jnp.clip(
                pl.cdiv(kv_valid - jc * k_chunk, block_k), 0, n_t)
        m, l, acc = jax.lax.fori_loop(
            0, n_t, body, (m_ref[:, 0], l_ref[:, 0], acc_ref[...]))
        m_ref[...] = m[:, None]
        l_ref[...] = l[:, None]
        acc_ref[...] = acc

    if contributes is None:
        _compute()
    else:
        pl.when(contributes)(_compute)

    def _finalize():
        l_safe = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)
        # lse rides a (1, block_q, 1) block: TPU mosaic requires the
        # block's last two dims to be (8k, 128k) or equal to the array's —
        # a trailing singleton satisfies that where (1, block_q) cannot.
        lse_ref[0] = (m_ref[:, 0] + jnp.log(l_safe))[:, None]

    if single:
        _finalize()
    else:
        pl.when(jc == n_kc - 1)(_finalize)


def _fa_forward(q, k, v, causal, sm_scale, block_q, block_k,
                q_offset=None, kv_valid=None, heads=None, kv_heads=None):
    """(B*H, Lq, D) x (B*KV, Lk, D)^2 -> (o, lse).

    ``q_offset``/``kv_valid`` override the end-aligned causal offset and
    the number of VALID keys when the inputs were padded to block
    multiples (positions are always in ORIGINAL coordinates).

    Grouped-query attention: with ``kv_heads < heads`` the K/V tensors
    carry only the grouped heads and the kernel streams each kv head's
    chunks to its ``heads/kv_heads`` query heads via the BlockSpec index
    map — no materialized broadcast, 1/g the K/V HBM traffic."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    if q_offset is None:
        q_offset = lk - lq
    if kv_valid is None:
        kv_valid = lk
    if heads is None or kv_heads is None or heads == kv_heads:
        def kv_map(b, i, j):
            return (b, j, 0)
    else:
        g = heads // kv_heads

        def kv_map(b, i, j):
            return ((b // heads) * kv_heads + (b % heads) // g, j, 0)
    masked = kv_valid < lk
    k_chunk = _pick_chunk(lk, block_k)
    n_kc = lk // k_chunk
    grid = (bh, lq // block_q, n_kc)
    kernel = functools.partial(_fa_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               k_chunk=k_chunk, q_offset=q_offset,
                               n_kc=n_kc, kv_valid=kv_valid, masked=masked)
    # Inside a VMA-checked shard_map the outputs must declare how they vary
    # over the mesh (they vary exactly like the operands).
    vma = frozenset().union(*(getattr(jax.typeof(t), "vma", frozenset())
                              for t in (q, k, v)))
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, k_chunk, d), kv_map),
            pl.BlockSpec((1, k_chunk, d), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _out_struct((bh, lq, d), q.dtype, vma),
            _out_struct((bh, lq, 1), jnp.float32, vma),
        ],
        scratch_shapes=[_scratch((block_q, 1)), _scratch((block_q, 1)),
                        _scratch((block_q, d))],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v)
    return o, lse[..., 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash(q, k, v, causal, sm_scale, block_q, block_k, q_offset=None,
           kv_valid=None, heads=None, kv_heads=None):
    """``heads``/``kv_heads`` (static) turn on grouped-query attention:
    q carries B*heads rows, k/v only B*kv_heads. The forward streams the
    NARROW k/v through the kernel (index-mapped, no broadcast); the
    backward broadcasts once and group-sums dK/dV — forward/serving
    bandwidth is where GQA pays."""
    o, _ = _fa_forward(q, k, v, causal, sm_scale, block_q, block_k,
                       q_offset, kv_valid, heads=heads, kv_heads=kv_heads)
    return o


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, q_offset=None,
               kv_valid=None, heads=None, kv_heads=None):
    o, lse = _fa_forward(q, k, v, causal, sm_scale, block_q, block_k,
                         q_offset, kv_valid, heads=heads, kv_heads=kv_heads)
    return o, (q, k, v, o, lse)


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, acc_ref, *, sm_scale, causal, block_q,
                      block_k, k_chunk, q_offset, n_kc, kv_valid, masked):
    """dQ pass: (query-block, key-chunk) grid with the dq accumulator in
    scratch across chunks and a register fori sweep within each chunk."""
    qi = pl.program_id(1)
    # Same single-chunk static specialization as _fa_kernel (see there).
    single = n_kc == 1
    jc = 0 if single else pl.program_id(2)

    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    if single:
        _init()
    else:
        pl.when(jc == 0)(_init)

    q_end = q_offset + (qi + 1) * block_q - 1
    contributes = None
    if causal:
        contributes = q_end >= jc * k_chunk
    if masked and not single:
        c = jc * k_chunk < kv_valid
        contributes = c if contributes is None else contributes & c

    def _compute():
        q = q_ref[0].astype(jnp.float32)                   # (BQ, D)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, :, 0]                             # (BQ,)
        delta = delta_ref[0, :, 0]

        def body(t, dq):
            kb = k_ref[0, pl.ds(t * block_k, block_k), :].astype(jnp.float32)
            vb = v_ref[0, pl.ds(t * block_k, block_k), :].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale
            s = _apply_mask(s, causal=causal, masked=masked,
                            q0=q_offset + qi * block_q,
                            k0=jc * k_chunk + t * block_k,
                            kv_valid=kv_valid, block_q=block_q,
                            block_k=block_k)
            p = jnp.where(s > NEG_INF * 0.5, jnp.exp(s - lse[:, None]), 0.0)
            dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta[:, None]) * sm_scale
            return dq + jax.lax.dot_general(
                ds, kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        n_t = k_chunk // block_k
        if masked and single:
            n_t = min(n_t, max(0, (kv_valid + block_k - 1) // block_k))
        if causal:
            n_t = jnp.clip(
                pl.cdiv(q_end + 1 - jc * k_chunk, block_k), 0, n_t)
        if masked and not single:
            n_t = jnp.clip(
                pl.cdiv(kv_valid - jc * k_chunk, block_k), 0, n_t)
        acc_ref[...] = jax.lax.fori_loop(0, n_t, body, acc_ref[...])

    if contributes is None:
        _compute()
    else:
        pl.when(contributes)(_compute)

    def _finalize():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)

    if single:
        _finalize()
    else:
        pl.when(jc == n_kc - 1)(_finalize)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, dk_acc, dv_acc, *, sm_scale, causal,
                       block_q, block_k, q_chunk, q_offset, n_qc, kv_valid,
                       masked):
    """dK/dV pass: (key-block, query-chunk) grid; per-key-block accumulators
    in scratch across query chunks, register fori sweep within."""
    ki = pl.program_id(1)
    # Single-chunk static specialization for the QUERY-chunk grid dim
    # (n_qc == 1): literal jc, unconditional init/finalize. The masked
    # and causal gates ride ki — a real grid variable — and remain.
    single = n_qc == 1
    jc = 0 if single else pl.program_id(2)

    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    if single:
        _init()
    else:
        pl.when(jc == 0)(_init)

    contributes = None
    if causal:
        # Query chunks ending above this key block's diagonal contribute
        # nothing: rows i attend keys <= i + q_offset.
        contributes = (q_offset + (jc + 1) * q_chunk - 1) >= ki * block_k
    if masked:
        # Entirely-padding key blocks receive zero gradient.
        c = ki * block_k < kv_valid
        contributes = c if contributes is None else contributes & c

    def _compute():
        kb = k_ref[0].astype(jnp.float32)                  # (BK, D)
        vb = v_ref[0].astype(jnp.float32)

        def body(t, carry):
            dk, dv = carry
            qb = q_ref[0, pl.ds(t * block_q, block_q), :].astype(jnp.float32)
            dob = do_ref[0, pl.ds(t * block_q, block_q), :].astype(
                jnp.float32)
            lse_b = lse_ref[0, pl.ds(t * block_q, block_q), 0]
            delta_b = delta_ref[0, pl.ds(t * block_q, block_q), 0]
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale
            s = _apply_mask(s, causal=causal, masked=masked,
                            q0=q_offset + jc * q_chunk + t * block_q,
                            k0=ki * block_k, kv_valid=kv_valid,
                            block_q=block_q, block_k=block_k)
            p = jnp.where(s > NEG_INF * 0.5,
                          jnp.exp(s - lse_b[:, None]), 0.0)
            dv = dv + jax.lax.dot_general(
                p, dob, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta_b[:, None]) * sm_scale
            dk = dk + jax.lax.dot_general(
                ds, qb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return dk, dv

        n_t = q_chunk // block_q
        if causal:
            # First query row attending key block ki within this chunk.
            t0 = jnp.clip(
                (ki * block_k - q_offset - jc * q_chunk) // block_q, 0, n_t)
        else:
            t0 = 0
        dk, dv = jax.lax.fori_loop(
            t0, n_t, body, (dk_acc[...], dv_acc[...]))
        dk_acc[...] = dk
        dv_acc[...] = dv

    if contributes is None:
        _compute()
    else:
        pl.when(contributes)(_compute)

    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)

    if single:
        _finalize()
    else:
        pl.when(jc == n_qc - 1)(_finalize)


def _fa_backward(q, k, v, o, lse, do, causal, sm_scale, block_q, block_k,
                 q_offset=None, kv_valid=None):
    """Fused O(L)-memory backward: (dq, dk, dv) via two pallas_calls."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    if q_offset is None:
        q_offset = lk - lq
    if kv_valid is None:
        kv_valid = lk
    masked = kv_valid < lk
    k_chunk = _pick_chunk(lk, block_k)
    q_chunk = _pick_chunk(lq, block_q)
    n_kc = lk // k_chunk
    n_qc = lq // q_chunk
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                # (BH, Lq, 1)
    lse3 = lse[..., None]                                  # (BH, Lq, 1)
    common = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
                  block_k=block_k, q_offset=q_offset, kv_valid=kv_valid,
                  masked=masked)
    q_blk = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    r_blk = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    kc_swept = pl.BlockSpec((1, k_chunk, d), lambda b, i, j: (b, j, 0))
    vma = frozenset().union(*(getattr(jax.typeof(t), "vma", frozenset())
                              for t in (q, k, v, do)))
    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, k_chunk=k_chunk, n_kc=n_kc,
                          **common),
        grid=(bh, lq // block_q, n_kc),
        in_specs=[q_blk, kc_swept, kc_swept, q_blk, r_blk, r_blk],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=_out_struct((bh, lq, d), q.dtype, vma),
        scratch_shapes=[_scratch((block_q, d))],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v, do, lse3, delta)
    # dK/dV: grid over key blocks; query chunks stream innermost.
    qc_swept = pl.BlockSpec((1, q_chunk, d), lambda b, i, j: (b, j, 0))
    rc_swept = pl.BlockSpec((1, q_chunk, 1), lambda b, i, j: (b, j, 0))
    k_blk = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, q_chunk=q_chunk, n_qc=n_qc,
                          **common),
        grid=(bh, lk // block_k, n_qc),
        in_specs=[qc_swept, k_blk, k_blk, qc_swept, rc_swept, rc_swept],
        out_specs=[pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
                   pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0))],
        out_shape=[_out_struct((bh, lk, d), k.dtype, vma),
                   _out_struct((bh, lk, d), v.dtype, vma)],
        scratch_shapes=[_scratch((block_k, d)), _scratch((block_k, d))],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v, do, lse3, delta)
    return dq, dk, dv


def _mask_jnp(s, causal, q_offset, kv_valid):
    """Full-matrix analog of _apply_mask for the jnp oracles."""
    lq, lk = s.shape[1], s.shape[2]
    if q_offset is None:
        q_offset = lk - lq
    if kv_valid is None:
        kv_valid = lk
    ok = None
    if kv_valid < lk:
        ok = (jnp.arange(lk) < kv_valid)[None, :]
    if causal:
        c = (q_offset + jnp.arange(lq))[:, None] >= jnp.arange(lk)[None, :]
        ok = c if ok is None else ok & c
    if ok is None:
        return s
    return jnp.where(ok[None], s, NEG_INF)


def _jnp_block_fwd(q3, k3, v3, causal, scale, q_offset=None, kv_valid=None):
    """jnp oracle for one attention block on (BH, Lq, D): returns
    (o, lse) with the same contract as the forward kernel (end-aligned
    causal, per-row logsumexp, optional key-validity bound). Shared by the
    interpret-mode paths here and the ring hops in parallel/sequence.py."""
    s = jnp.einsum("bqd,bkd->bqk", q3.astype(jnp.float32),
                   k3.astype(jnp.float32)) * scale
    s = _mask_jnp(s, causal, q_offset, kv_valid)
    m = jnp.max(s, axis=-1)
    p = jnp.where(s > NEG_INF * 0.5, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.maximum(jnp.sum(p, axis=-1), 1e-30)
    o = (jnp.einsum("bqk,bkd->bqd", p, v3.astype(jnp.float32))
         / l[..., None]).astype(q3.dtype)
    return o, m + jnp.log(l)


def _jnp_block_bwd(q3, k3, v3, o3, lse, do3, causal, scale,
                   q_offset=None, kv_valid=None):
    """jnp oracle for the block backward against a given logsumexp: with
    the block's own lse this is exact flash backward; with a ring-wide lse
    it yields the hop's contribution to the global gradient."""
    qf, kf, vf, of, dof = (t.astype(jnp.float32)
                           for t in (q3, k3, v3, o3, do3))
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * scale
    s = _mask_jnp(s, causal, q_offset, kv_valid)
    # Masked entries have s = NEG_INF and a fully-masked row has
    # lse ~= NEG_INF, where exp(s - lse) would blow up instead of vanishing
    # — zero them explicitly (the forward kernel does the same).
    p = jnp.where(s > NEG_INF * 0.5, jnp.exp(s - lse[..., None]), 0.0)
    dv = jnp.einsum("bqk,bqd->bkd", p, dof)
    dp = jnp.einsum("bqd,bkd->bqk", dof, vf)
    delta = jnp.sum(dof * of, axis=-1)                    # (BH, Lq)
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf)
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
    return dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype)


def gqa_repeat3(t3, b, kv, g):
    """(B*KV, L, D) -> (B*KV*g, L, D): each kv head's block repeated g
    times CONTIGUOUSLY, matching the (B*heads, L, D) query row layout the
    kernels (and their GQA index maps) use."""
    _, L, D = t3.shape
    return jnp.repeat(t3.reshape(b, kv, L, D), g, axis=1).reshape(
        b * kv * g, L, D)


def gqa_fold3(t3, b, kv, g):
    """Group-sum (B*heads, L, D) gradients back onto the narrow kv rows —
    the VJP of :func:`gqa_repeat3`."""
    _, L, D = t3.shape
    return t3.reshape(b, kv, g, L, D).sum(axis=2).reshape(
        b * kv, L, D).astype(t3.dtype)


def _flash_bwd(causal, sm_scale, block_q, block_k, q_offset, kv_valid,
               heads, kv_heads, res, do):
    q, k, v, o, lse = res
    gqa = heads is not None and kv_heads is not None and heads != kv_heads
    if gqa:
        # Broadcast the narrow residual k/v once, run the MHA backward,
        # then group-sum dK/dV back to the kv heads (the VJP of the
        # implicit broadcast).
        g = heads // kv_heads
        b = q.shape[0] // heads
        k = gqa_repeat3(k, b, kv_heads, g)
        v = gqa_repeat3(v, b, kv_heads, g)
    if not _interpret():
        dq, dk, dv = _fa_backward(q, k, v, o, lse, do, causal, sm_scale,
                                  block_q, block_k, q_offset, kv_valid)
    else:
        dq, dk, dv = _jnp_block_bwd(q, k, v, o, lse, do, causal, sm_scale,
                                    q_offset=q_offset, kv_valid=kv_valid)
    if gqa:
        dk, dv = gqa_fold3(dk, b, kv_heads, g), gqa_fold3(dv, b, kv_heads, g)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, sm_scale=None):
    """Tiled attention over (B, L, H, D) tensors (the layout used throughout
    this codebase, e.g. parallel/sequence.py).

    Lengths with no aligned block size are PADDED to the next block
    multiple and the padding masked inside the kernels (``kv_valid``), so
    arbitrary sequence lengths (e.g. ViT's 196 patches) run the kernels.
    Falls back to :func:`horovod_tpu.parallel.sequence.local_attention`
    (the correctness oracle, same end-aligned causal convention) only
    where the kernels can't run at all (no pltpu; VMA-checked shard_map
    under the interpreter).
    """
    b, lq, h, d = q.shape
    lk, kv = k.shape[1], k.shape[2]
    if kv != h and (kv == 0 or h % kv):
        raise ValueError(
            f"kv heads {kv} must divide query heads {h} (grouped-query)")
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)

    def plain_fallback():
        """local_attention with any custom scale folded into q (it scales
        by 1/sqrt(D) internally, and broadcasts grouped K/V itself)."""
        from horovod_tpu.parallel.sequence import local_attention
        q_adj = q if sm_scale == 1.0 / (d ** 0.5) \
            else q * (sm_scale * d ** 0.5)
        return local_attention(q_adj, k, v, causal=causal)

    # Interpret mode (CPU tests) lowers the kernel body to ordinary JAX ops,
    # whose internal dynamic_slices the shard_map VMA checker rejects when
    # the operands are device-varying; the plain path is bit-compatible
    # there. On TPU the compiled kernel is opaque to the checker.
    vma = frozenset().union(*(getattr(jax.typeof(t), "vma", frozenset())
                              for t in (q, k, v)))
    if pltpu is None or (_interpret() and vma):
        return plain_fallback()

    # Pad only genuinely unaligned lengths (e.g. ViT's 196): aligned ones
    # keep their unpadded, unmasked kernels (no pad copy, no mask work).
    pad_q = 0 if _pick_block(lq) else (-lq) % 128
    pad_k = 0 if _pick_block(lk) else (-lk) % 128
    lq_p, lk_p = lq + pad_q, lk + pad_k

    # SAFETY GATE: the padded-kernel path once HUNG on real silicon (ViT
    # 197->256, >20 min with no progress — undiagnosed; the kv_valid
    # masking/padded-grid interaction under Mosaic is the prime suspect,
    # see docs/troubleshooting.md "Padded flash attention"). Until it is
    # validated on-chip, unaligned lengths on REAL TPU fall back to plain
    # XLA attention; HVD_FLASH_ALLOW_PADDED=1 re-enables the kernels (the
    # on-chip validation queue runs exactly that, bounded). Interpret mode
    # (CPU tests) keeps the padded kernels — they are correct there and
    # serve as the oracle. Reference analog: CUDA kernels are CI-exercised
    # on hardware before they ship (horovod/common/ops/cuda/).
    if (pad_q or pad_k) and not _interpret():
        import os
        if os.environ.get("HVD_FLASH_ALLOW_PADDED", "0") != "1":
            return plain_fallback()

    def to3(t, pad):
        nh = t.shape[2]
        t3 = jnp.moveaxis(t, 2, 1).reshape(t.shape[0] * nh, t.shape[1], d)
        if pad:
            t3 = jnp.pad(t3, ((0, 0), (0, pad), (0, 0)))
        return t3

    def from3(t):
        return jnp.moveaxis(t[:, :lq].reshape(b, h, lq, d), 1, 2)

    # kv != h: grouped-query — the kernels stream the NARROW k/v (1/g the
    # HBM traffic); no broadcast is materialized on the forward path.
    out = _flash(to3(q, pad_q), to3(k, pad_k), to3(v, pad_k), causal,
                 sm_scale, _pick_block(lq_p), _pick_block(lk_p),
                 lk - lq, lk, h, kv)
    return from3(out)
