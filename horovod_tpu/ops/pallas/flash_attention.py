"""Flash attention as a Pallas TPU kernel.

The hot op of every transformer in the model zoo (models/bert.py,
models/gpt.py, parallel/tp.py). Tiled online-softmax attention: for each
query block the kernel streams key/value blocks through VMEM, keeping the
running max/denominator in registers — O(L) memory instead of materializing
the (L, L) score matrix, and every matmul lands on the MXU as a
(block_q x D) @ (D x block_k) tile.

The reference framework has no attention code (SURVEY.md §5.7 — Horovod
operates below the model level); this kernel is part of the TPU build's
model-level capability, in the spirit of the reference's hand-written CUDA
hot loops (reference: horovod/common/ops/cuda/cuda_kernels.cu).

Backward pass: custom VJP using the saved per-row logsumexp. The backward is
currently a (blockwise-correct but unfused) jnp implementation that
rematerializes scores — O(L^2) transient memory in the backward only; fuse it
into a second kernel if profiles demand.

On CPU (tests, no TPU) the kernel runs through the Pallas interpreter;
shapes whose sequence length has no aligned block size fall back to plain
attention.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific bits are absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30  # finite big-negative: avoids inf-inf NaNs in the masking


def _interpret():
    return jax.default_backend() != "tpu"


def _pick_block(length, cap=128):
    for b in (cap, 64, 32, 16, 8):
        if length % b == 0:
            return b
    return None


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
               block_q, block_k, q_offset):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale            # (BQ, D)
    n_k = k_ref.shape[1] // block_k

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    # End-aligned causal convention (tril with k = Lk - Lq), matching
    # local_attention and the backward pass: query row i may attend keys
    # <= i + (Lk - Lq). q_offset = Lk - Lq.
    q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        # Rows where every score is masked would give exp(0)=1; zero them.
        p = jnp.where(s > NEG_INF * 0.5, p, 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # Blocks entirely above the diagonal contribute nothing: bound the
        # sweep at the last block overlapping this query block's rows.
        n_k_eff = jnp.minimum(
            n_k, pl.cdiv(q_offset + (qi + 1) * block_q, block_k))
    else:
        n_k_eff = n_k
    m, l, acc = jax.lax.fori_loop(0, n_k_eff, body, (m0, l0, acc0))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)


def _fa_forward(q, k, v, causal, sm_scale, block_q, block_k):
    """(BH, Lq, D) x (BH, Lk, D)^2 -> (o, lse)."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    grid = (bh, lq // block_q)
    kernel = functools.partial(_fa_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               q_offset=lk - lq)
    # Inside a VMA-checked shard_map the outputs must declare how they vary
    # over the mesh (they vary exactly like the operands).
    vma = frozenset().union(*(getattr(jax.typeof(t), "vma", frozenset())
                              for t in (q, k, v)))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, lk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, lk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, d), q.dtype, vma=vma),
            jax.ShapeDtypeStruct((bh, lq), jnp.float32, vma=vma),
        ],
        interpret=_interpret(),
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, sm_scale, block_q, block_k):
    o, _ = _fa_forward(q, k, v, causal, sm_scale, block_q, block_k)
    return o


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    o, lse = _fa_forward(q, k, v, causal, sm_scale, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, res, do):
    q, k, v, o, lse = res
    qf, kf, vf, of, dof = (t.astype(jnp.float32) for t in (q, k, v, o, do))
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * sm_scale
    if causal:
        lq, lk = s.shape[1], s.shape[2]
        mask = jnp.tril(jnp.ones((lq, lk), bool), k=lk - lq)
        s = jnp.where(mask[None], s, NEG_INF)
    # Masked entries have s = NEG_INF and a fully-masked row has
    # lse ~= NEG_INF, where exp(s - lse) would blow up instead of vanishing
    # — zero them explicitly (the forward kernel does the same).
    p = jnp.where(s > NEG_INF * 0.5,
                  jnp.exp(s - lse[..., None]), 0.0)       # uses saved lse
    dv = jnp.einsum("bqk,bqd->bkd", p, dof)
    dp = jnp.einsum("bqd,bkd->bqk", dof, vf)
    delta = jnp.sum(dof * of, axis=-1)                    # (BH, Lq)
    ds = p * (dp - delta[..., None]) * sm_scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf)
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, sm_scale=None):
    """Tiled attention over (B, L, H, D) tensors (the layout used throughout
    this codebase, e.g. parallel/sequence.py).

    Falls back to :func:`horovod_tpu.parallel.sequence.local_attention` (the
    codebase's correctness oracle, same end-aligned causal convention) when
    the sequence lengths admit no aligned block size; semantics are identical
    either way.
    """
    b, lq, h, d = q.shape
    lk = k.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)

    def to3(t):
        return jnp.moveaxis(t, 2, 1).reshape(t.shape[0] * h, t.shape[1], d)

    def from3(t):
        return jnp.moveaxis(t.reshape(b, h, lq, d), 1, 2)

    block_q = _pick_block(lq)
    block_k = _pick_block(lk)
    # Interpret mode (CPU tests) lowers the kernel body to ordinary JAX ops,
    # whose internal dynamic_slices the shard_map VMA checker rejects when
    # the operands are device-varying; the plain path is bit-compatible
    # there. On TPU the compiled kernel is opaque to the checker.
    vma = frozenset().union(*(getattr(jax.typeof(t), "vma", frozenset())
                              for t in (q, k, v)))
    if block_q is None or block_k is None or (_interpret() and vma):
        from horovod_tpu.parallel.sequence import local_attention
        # local_attention scales by 1/sqrt(D); fold any custom scale into q.
        q_adj = q if sm_scale == 1.0 / (d ** 0.5) \
            else q * (sm_scale * d ** 0.5)
        return local_attention(q_adj, k, v, causal=causal)
    return from3(_flash(to3(q), to3(k), to3(v), causal, sm_scale,
                        block_q, block_k))
