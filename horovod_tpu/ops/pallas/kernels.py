"""Small fused Pallas kernels: buffer scaling and the Adasum combiner.

TPU counterparts of the reference's CUDA utility kernels:

- ``scale_buffer(s)`` — the fused buffer-scale kernel
  (reference: horovod/common/ops/cuda/cuda_kernels.cu scale kernels, used for
  prescale/postscale on the fusion buffer). ``scale_buffers`` applies ONE
  kernel launch to a whole list of tensors, the analog of the reference's
  batched fused memcpy+scale over fusion-buffer entries.
- ``adasum_combine_pallas`` — the pairwise Adasum combine
  (reference: horovod/common/ops/adasum/adasum.h:103+ — dot product and the
  two squared norms computed in one AVX pass, then the weighted sum). Here
  one VPU pass computes all three reductions and the combined output without
  leaving VMEM.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANES = 128
_SUBLANES = 8
# Single-block kernels keep everything resident in VMEM (~16 MB/core);
# beyond this element count fall back to plain XLA ops.
_VMEM_ELEMENT_CAP = 1 << 20


def _interpret():
    return jax.default_backend() != "tpu"


def _to_rows(flat):
    """Pad a flat vector to a (rows, 128) tile-aligned block."""
    unit = _LANES * _SUBLANES
    pad = (-flat.size) % unit
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, _LANES), pad


def _scale_kernel(x_ref, s_ref, o_ref):
    o_ref[:] = (x_ref[:].astype(jnp.float32) * s_ref[0, 0]).astype(o_ref.dtype)


def scale_buffer(x, scale):
    """``x * scale`` as one Pallas kernel (any shape/dtype)."""
    if x.size == 0 or x.size > _VMEM_ELEMENT_CAP:
        return (x.astype(jnp.float32) * scale).astype(x.dtype)
    rows, _ = _to_rows(x.reshape(-1))
    s = jnp.asarray(scale, jnp.float32).reshape(1, 1)
    out = pl.pallas_call(
        _scale_kernel,
        out_shape=jax.ShapeDtypeStruct(rows.shape, x.dtype),
        interpret=_interpret(),
    )(rows, s)
    return out.reshape(-1)[:x.size].reshape(x.shape)


def scale_buffers(tensors, scale):
    """Scale a list of tensors with ONE fused kernel launch (the batched
    fusion-buffer scale of the reference's cuda_kernels.cu)."""
    if not tensors:
        return []
    flat = jnp.concatenate([t.reshape(-1).astype(jnp.float32)
                            for t in tensors])
    scaled = scale_buffer(flat, scale)
    out, off = [], 0
    for t in tensors:
        out.append(scaled[off:off + t.size].reshape(t.shape).astype(t.dtype))
        off += t.size
    return out


def _adasum_kernel(a_ref, b_ref, o_ref, *, eps):
    a = a_ref[:].astype(jnp.float32)
    b = b_ref[:].astype(jnp.float32)
    # One VPU pass over the operands yields all three reductions.
    dot = jnp.sum(a * b)
    na = jnp.sum(a * a)
    nb = jnp.sum(b * b)
    ca = jnp.where(na > eps, 1.0 - dot / (2.0 * jnp.maximum(na, eps)), 1.0)
    cb = jnp.where(nb > eps, 1.0 - dot / (2.0 * jnp.maximum(nb, eps)), 1.0)
    o_ref[:] = (ca * a + cb * b).astype(o_ref.dtype)


def adasum_combine_pallas(a, b, eps=1e-30):
    """Pairwise Adasum combine (reference: adasum.h:103+) in one kernel.

    Exactly :func:`horovod_tpu.ops.adasum.adasum_combine` numerically; large
    tensors fall back to that implementation.
    """
    if a.size == 0 or a.size > _VMEM_ELEMENT_CAP:
        from horovod_tpu.ops.adasum import adasum_combine
        return adasum_combine(a, b, eps=eps)
    ar, pad = _to_rows(a.reshape(-1))
    br, _ = _to_rows(b.reshape(-1))
    # Padding zeros contribute nothing to dot/norms, so no masking needed.
    out = pl.pallas_call(
        functools.partial(_adasum_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct(ar.shape, a.dtype),
        interpret=_interpret(),
    )(ar, br)
    return out.reshape(-1)[:a.size].reshape(a.shape)
