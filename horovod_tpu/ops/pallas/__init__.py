"""Pallas TPU kernels for the framework's hot ops.

The reference keeps its device hot loops in hand-written CUDA
(reference: horovod/common/ops/cuda/cuda_kernels.cu — batched fused memcpy
and buffer-scale kernels; horovod/common/ops/adasum/adasum.h — AVX'd dot
product/norm math). The TPU equivalents live here as Pallas kernels: they
compile through Mosaic onto the MXU/VPU and run in interpret mode on CPU for
tests.
"""

from horovod_tpu.ops.pallas.flash_attention import flash_attention  # noqa: F401
from horovod_tpu.ops.pallas.kernels import (  # noqa: F401
    adasum_combine_pallas, scale_buffer, scale_buffers,
)
