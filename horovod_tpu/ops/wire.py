"""Quantized wire tier: block-scaled int8/fp8 collectives with error feedback.

This module owns everything "bytes-on-the-wire" shaped that used to live
scattered across the stack: the symmetric block quantizers (promoted out of
``parallel/strategies.py`` — one definition for the wire exchange AND the
quantized KV cache), the EQuARX-style two-phase quantized allreduce
(arXiv:2506.17615 — quantization fused into the reduce-scatter→all-gather
phases inside XLA), per-bucket **error-feedback** accumulators (residual
kept in fp32, added back before the next quantize — the Horovod compression
design of arXiv:1802.05799 pairs lossy wire formats with exactly this), the
per-process-set wire-dtype registry the autotuner steers (with per-LINK-TIER
keys — ``ps@dcn`` is the cross-slice leg's policy of the hierarchical
dispatch tier — and the sibling dispatch-strategy registry), the
slice-boundary tier-split math shared with the static cost model
(``ring_dcn_fraction``/``a2a_dcn_fraction``/``hierarchical_wire_bytes``),
and the wire-byte accounting behind ``wire_bytes_total{dtype,tier}``.

Three dispatch paths consume it (each records
``wire_compression_events_total{path,dtype}``):

- **eager** — ``ops/collective_ops.grouped_allreduce`` routes float
  Sum/Average allreduces through :func:`block_scaled_allreduce` when the
  effective wire dtype is quantized (``_WireDispatchPlan``), with the
  residual held in the process-local :func:`ef_get`/:func:`ef_put` store.
- **fused** — ``ops/fusion._fused_program`` rides the same exchange per
  fusion bucket, one residual per bucket signature.
- **jit** — ``parallel/strategies.allreduce_int8`` /
  ``scaled_allreduce_int8`` delegate here for use inside user
  ``shard_map``/``pjit`` steps; :func:`block_scaled_allreduce` with an
  explicit ``residual`` is the in-jit error-feedback entry point (the
  caller threads the residual through its own optimizer state — and must
  zero it on elastic reset; hvdlint HVP109 flags the configuration).

Wire formats: ``int8`` (symmetric, ±127) and ``fp8`` (e4m3, ±448 — gated
on the installed jax exposing ``float8_e4m3fn``; otherwise the tier falls
back to a bf16 cast wire with a one-time warning). Scales are one fp32 per
:data:`BLOCK` (1024) elements — block scales keep small-magnitude tensors
in a mixed fused bucket from rounding to zero (≈0.4 % wire overhead).

Error-feedback residuals live in the SUM domain after prescale: the
residual is added after the prescale multiply and before quantization, so
the compensated error re-enters the very next reduction of the same
bucket. Residuals are device arrays of the torn-down backend after an
elastic resize, so :func:`reset_error_feedback` is wired into
``collective_ops.clear_program_caches`` — a resized mesh must never replay
stale residuals.
"""

import threading
import warnings

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# One fp32 scale per this many elements (EQuARX's block quantization).
BLOCK = 1024

# Largest finite magnitude of float8_e4m3fn.
FP8_MAX = 448.0

# Quantized wire labels (the rest of the accepted wire dtypes are casts).
QUANTIZED = ("int8", "fp8")


def fp8_dtype():
    """The fp8 wire element type, or None when this jax doesn't have it."""
    return getattr(jnp, "float8_e4m3fn", None)


_warned_fp8 = False


def resolve_wire_dtype(name):
    """Normalize a configured wire dtype string; ``fp8`` degrades to
    ``bfloat16`` (one-time warning) when the dtype doesn't exist in this
    jax build — a 16-bit cast wire is the graceful fallback that still
    halves fp32 bytes."""
    if not name:
        return ""
    if name == "fp8" and fp8_dtype() is None:
        global _warned_fp8
        if not _warned_fp8:
            warnings.warn(
                "wire_dtype=fp8 requested but this jax build has no "
                "float8_e4m3fn — falling back to the bfloat16 cast wire",
                stacklevel=2)
            _warned_fp8 = True
        return "bfloat16"
    return name


def quantized_label(dtype_like):
    """``"int8"``/``"fp8"`` when ``dtype_like`` (a wire string, numpy/jnp
    dtype, or scalar type) names a quantized wire format, else None —
    including ``"fp8"`` on a build without the dtype (the fallback there
    is the bf16 CAST wire, which is not a quantized format; callers fall
    back to their exact/cast path)."""
    if dtype_like is None or dtype_like == "":
        return None
    if isinstance(dtype_like, str) and dtype_like in QUANTIZED:
        if dtype_like == "fp8":
            return "fp8" if fp8_dtype() is not None else None
        return dtype_like
    try:
        name = jnp.dtype(dtype_like).name
    except TypeError:
        return None
    if name == "int8":
        return "int8"
    if name.startswith("float8"):
        return "fp8"
    return None


def is_quantized(name):
    return quantized_label(name) is not None


def wire_numpy_type(name):
    """Numpy/jnp scalar type for a configured wire dtype string (after the
    fp8 fallback), or None for the full-precision wire. This is what the
    fusion runtime stores in ``wire_dtype`` (its bucket keys and boundary
    payloads serialize it via ``jnp.dtype(...).name``)."""
    name = resolve_wire_dtype(name)
    if not name:
        return None
    if name == "fp8":
        return fp8_dtype()
    return jnp.dtype(name).type


# ----------------------------------------------------------------------------
# Block quantizers
# ----------------------------------------------------------------------------

def symmetric_int8_quantize(t):
    """THE symmetric int8 quantizer (one definition for the wire exchange
    AND the quantized KV cache): per-LAST-axis scale ``max|t|/127``
    clamped at 1e-30, round + clip to ±127. Returns ``(q8, scale)`` with
    ``scale.shape == t.shape[:-1]`` (fp32 math expected in ``t``)."""
    scale = jnp.maximum(jnp.max(jnp.abs(t), axis=-1) / 127.0, 1e-30)
    q = jnp.clip(jnp.round(t / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def symmetric_fp8_quantize(t):
    """fp8 (e4m3) sibling of :func:`symmetric_int8_quantize`: per-LAST-axis
    scale ``max|t|/448``, cast to ``float8_e4m3fn`` (the cast rounds).
    fp8's mantissa gives ~2 decimal digits but its exponent keeps relative
    error flat across each block's dynamic range — better than int8 on
    heavy-tailed gradient blocks, same 1 byte/element on the wire."""
    f8 = fp8_dtype()
    scale = jnp.maximum(jnp.max(jnp.abs(t), axis=-1) / FP8_MAX, 1e-30)
    q = (t / scale[..., None]).astype(f8)
    return q, scale


def quantize_blocks(t, wire):
    """Dispatch to the block quantizer for wire format ``wire``."""
    if wire == "fp8":
        return symmetric_fp8_quantize(t)
    return symmetric_int8_quantize(t)


def dequantize(q, scale):
    return q.astype(jnp.float32) * scale[..., None]


# ----------------------------------------------------------------------------
# The two-phase block-scaled exchange (EQuARX shape), with optional
# error feedback.
# ----------------------------------------------------------------------------

def block_scaled_allreduce(x, residual=None, axis_name="hvd", wire="int8",
                           average=False, prescale_factor=1.0,
                           postscale_factor=1.0):
    """Quantized allreduce: ``wire`` bytes on the wire, fp32 accumulation.

    Two-phase exchange built from XLA collectives:

    1. each rank splits its buffer into n destination shards and quantizes
       block-wise (one fp32 scale per :data:`BLOCK` elements),
    2. one AllToAll moves the 1-byte shards (+ a tiny fp32 scale AllToAll),
    3. each rank dequantizes and accumulates its shard in fp32
       (the reduce-scatter leg, 1 byte/element on the wire),
    4. the reduced shard is requantized block-wise and AllGathered
       (+ fp32 scales), then dequantized (the all-gather leg, 1 B/el).

    Total wire traffic ≈ 2 bytes/element vs ~8 for an fp32 psum's internal
    reduce-scatter + all-gather — at the cost of one quantization error
    per leg, bounded per element by its own block's ``max/254`` (int8).

    ``residual`` (error feedback): an fp32 buffer of ``x``'s flat size
    holding the previous round's quantization error in the prescaled SUM
    domain. It is added before quantization; the new residual — this
    round's first-leg error plus the second-leg error of the shard this
    rank owns — is returned alongside the result. Returns ``(out, None)``
    without a residual, ``(out, new_residual)`` with one.

    Works on any local shape; ``out`` has the same shape/dtype as ``x``.
    """
    n = lax.axis_size(axis_name)
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    if prescale_factor != 1.0:
        flat = flat * jnp.asarray(prescale_factor, flat.dtype)
    ef = residual is not None
    if ef:
        flat = flat + residual.reshape(-1).astype(jnp.float32)
    size = flat.size
    pad = (-size) % (n * BLOCK)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    nb = flat.size // (n * BLOCK)                    # blocks per shard
    blocks = flat.reshape(n, nb, BLOCK)              # [dest, block, elem]
    q, scale = quantize_blocks(blocks, wire)         # scale (n, nb)
    if ef:
        err1 = blocks - dequantize(q, scale)         # first-leg local error
    # Row d goes to rank d; row r of the result came from rank r.
    qt = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    st = lax.all_to_all(scale, axis_name, split_axis=0, concat_axis=0)
    part = jnp.sum(dequantize(qt, st), axis=0)       # (nb, BLOCK) fp32
    q2, s2 = quantize_blocks(part, wire)             # s2 (nb,)
    deq2 = dequantize(q2, s2)
    full_q = lax.all_gather(q2, axis_name, axis=0, tiled=False)  # (n,nb,blk)
    full_s = lax.all_gather(s2, axis_name, axis=0, tiled=False)  # (n, nb)
    out = dequantize(full_q, full_s).reshape(-1)
    new_res = None
    if ef:
        # This rank compensates (a) the quantization error of everything it
        # SENT (first leg, whole buffer) and (b) the requantization error
        # of the one shard it OWNS (second leg) — each global error term is
        # thus re-injected into the sum exactly once, by exactly one rank.
        res = err1.reshape(-1)
        shard_len = nb * BLOCK
        start = lax.axis_index(axis_name) * shard_len
        err2 = part - deq2                           # (nb, BLOCK)
        own = lax.dynamic_slice_in_dim(res, start, shard_len)
        res = lax.dynamic_update_slice_in_dim(
            res, own + err2.reshape(-1), start, axis=0)
        new_res = res[:size] if pad else res
    if pad:
        out = out[:-pad]
    if average:
        out = out / jnp.asarray(n, out.dtype)
    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, out.dtype)
    return out.reshape(orig_shape).astype(orig_dtype), new_res


# ----------------------------------------------------------------------------
# Error-feedback store (eager + fused paths; process-local, fp32 residuals
# as device arrays). In-jit callers thread residuals through their own
# state instead — this store cannot reach inside a jitted optimizer.
# ----------------------------------------------------------------------------

_EF_CAP = 64
_ef_lock = threading.RLock()
_ef_store = {}


def ef_get(key):
    with _ef_lock:
        return _ef_store.get(key)


def ef_put(key, residual):
    with _ef_lock:
        if key not in _ef_store and len(_ef_store) >= _EF_CAP:
            # Runaway-signature guard: evict the OLDEST entry (insertion
            # order), never the whole store — residuals are a convergence
            # aid, and a job legitimately cycling through many bucket
            # signatures must not lose every other bucket's feedback each
            # time one new key arrives. Dropping one costs that bucket a
            # single uncompensated round, never a wrong result.
            _ef_store.pop(next(iter(_ef_store)))
        _ef_store[key] = residual


def ef_pop(key):
    with _ef_lock:
        return _ef_store.pop(key, None)


def ef_keys():
    with _ef_lock:
        return list(_ef_store)


def reset_error_feedback():
    """Drop every error-feedback residual. Wired into
    ``collective_ops.clear_program_caches`` (and through it the elastic
    reset path): residuals are device arrays of the torn-down backend, and
    a resized mesh must not replay stale errors sized for the old world."""
    with _ef_lock:
        n = len(_ef_store)
        _ef_store.clear()
    return n


# ----------------------------------------------------------------------------
# Per-process-set wire-dtype registry.
#
# The config knob (HOROVOD_WIRE_DTYPE) is the default for every set; the
# registry overrides per set — set by the user (hvd.set_wire_dtype) or by
# the autotuner's categorical sweep. Multi-process discipline: the fusion
# coordinator updates "global" when it PUBLISHES a flush boundary (the
# knob snapshot its programs really used) and followers update when they
# ADOPT that boundary — so at any sync-collective program point (which
# fences fused work first) every process reads the same value. Direct
# set_wire_dtype calls under multi-process launches are themselves subject
# to the SPMD contract: every process must make the same call at the same
# program point.
# ----------------------------------------------------------------------------

_wire_lock = threading.RLock()
_wire_registry = {}            # key -> (value, source: "user"|"runtime")
#                                key = ps_label, or "ps@tier" for the
#                                per-link-tier policy (tier="dcn": the
#                                cross-slice leg of the hierarchical
#                                dispatch tier)

_ACCEPTED = ("", "float16", "bfloat16", "int8", "fp8")

# Link tiers of the slice hierarchy (the wire_bytes_total{tier} label
# values): "ici" = in-slice interconnect, "dcn" = the scarce cross-slice
# network the 2-level decomposition exists to relieve.
TIERS = ("ici", "dcn")


def _registry_key(ps_label, tier=None):
    return str(ps_label) if not tier else f"{ps_label}@{tier}"


def _normalize(dtype):
    name = {"fp16": "float16", "bf16": "bfloat16"}.get(dtype or "",
                                                       dtype or "")
    try:
        name = name if name in _ACCEPTED else jnp.dtype(name).name
    except TypeError:
        raise ValueError(
            f"wire dtype {dtype!r}: expected one of {_ACCEPTED}") from None
    if name.startswith("float8"):
        name = "fp8"
    if name not in _ACCEPTED:
        raise ValueError(
            f"wire dtype {dtype!r}: expected one of {_ACCEPTED}")
    return resolve_wire_dtype(name)


def set_wire_dtype(dtype, ps_label="global", tier=None):
    """Set the wire dtype for one process set ('' restores full
    precision). Returns the normalized value in effect. Dispatch plans are
    keyed on the wire dtype, so a flip simply routes subsequent eager
    collectives through differently-keyed plans — no explicit
    invalidation, no desync window. An explicit call here PINS the set:
    the fusion runtime's boundary sync (the autotuner's adoption path)
    no longer overwrites it — that is what makes the troubleshooting
    'bisect with the registry' A/B stick while async flushes continue.
    ``tier="dcn"`` sets the per-link-tier policy instead: the wire of the
    cross-slice leg of the hierarchical dispatch tier."""
    name = _normalize(dtype)
    with _wire_lock:
        _wire_registry[_registry_key(ps_label, tier)] = (name, "user")
    return name


def runtime_sync_wire_dtype(dtype, ps_label="global", tier=None):
    """Fusion-boundary adoption of the runtime/autotuner wire snapshot:
    like :func:`set_wire_dtype` but it DEFERS to an explicit user pin
    (see above). Returns the value actually in effect."""
    name = _normalize(dtype)
    with _wire_lock:
        key = _registry_key(ps_label, tier)
        cur = _wire_registry.get(key)
        if cur is not None and cur[1] == "user":
            return cur[0]
        _wire_registry[key] = (name, "runtime")
    return name


def wire_dtype_for(ps_label, default="", tier=None):
    """Effective wire dtype for a process set: the registry's entry, else
    ``default`` (normally the config knob). With ``tier`` the per-tier
    entry (``ps@tier``) is consulted; absent one, ``default`` applies —
    pass the resolved tier default (e.g. ``config.wire_dtype_dcn or
    config.wire_dtype`` for the DCN leg)."""
    with _wire_lock:
        v = _wire_registry.get(_registry_key(ps_label, tier))
    return resolve_wire_dtype(default) if v is None else v[0]


def cross_wire_for(ps_label, config):
    """Effective wire dtype of the CROSS-SLICE (DCN) leg for one process
    set — THE resolution chain runtime and static model share: per-tier
    registry entry (``ps@dcn``), else ``HOROVOD_WIRE_DTYPE_DCN``, else
    the flat wire knob (a job that quantizes its flat wire wants the
    scarce leg quantized at least as much)."""
    default = getattr(config, "wire_dtype_dcn", "") \
        or getattr(config, "wire_dtype", "")
    return wire_dtype_for(ps_label, default, tier="dcn")


def clear_wire_registry():
    with _wire_lock:
        _wire_registry.clear()


# ----------------------------------------------------------------------------
# Per-process-set dispatch-strategy registry (flat / hier / hier_qcross).
#
# The autotuner's strategy categorical is adopted per process set at flush
# boundaries exactly like the wire dtype above: the coordinator syncs when
# it publishes a boundary, followers when they apply it, and an explicit
# hvd.set_dispatch_strategy call pins the set against runtime sync.
# ----------------------------------------------------------------------------

STRATEGIES = ("", "flat", "hier", "hier_qcross")

_strategy_registry = {}        # ps_label -> (value, source)


def _normalize_strategy(strategy):
    s = strategy or ""
    if s not in STRATEGIES:
        raise ValueError(
            f"dispatch strategy {strategy!r}: expected one of {STRATEGIES}"
            " ('' = config default; hier = 2-level RS/cross/AG; "
            "hier_qcross = hierarchical with the cross leg on the "
            "quantized wire)")
    return s


def set_dispatch_strategy(strategy, ps_label="global"):
    """Pin the eager allreduce dispatch strategy for one process set
    ('' restores the config default). Like :func:`set_wire_dtype`, plans
    are keyed on the strategy, so a flip routes the next dispatch through
    a differently-keyed plan with no desync window."""
    s = _normalize_strategy(strategy)
    with _wire_lock:
        _strategy_registry[str(ps_label)] = (s, "user")
    return s


def runtime_sync_dispatch_strategy(strategy, ps_label="global"):
    """Flush-boundary adoption of the autotuner's strategy choice; defers
    to an explicit user pin like :func:`runtime_sync_wire_dtype`."""
    s = _normalize_strategy(strategy)
    with _wire_lock:
        cur = _strategy_registry.get(str(ps_label))
        if cur is not None and cur[1] == "user":
            return cur[0]
        _strategy_registry[str(ps_label)] = (s, "runtime")
    return s


def dispatch_strategy_for(ps_label, default=""):
    """Effective dispatch strategy for a process set: registry entry,
    else ``default`` (normally derived from
    ``config.hierarchical_dispatch``)."""
    with _wire_lock:
        v = _strategy_registry.get(str(ps_label))
    return (default or "") if v is None or not v[0] else v[0]


def clear_strategy_registry():
    with _wire_lock:
        _strategy_registry.clear()
        _a2a_strategy_registry.clear()


# ----------------------------------------------------------------------------
# Per-process-set ALLTOALL strategy + cross-wire registry.
#
# The hierarchical alltoall tier (MoE expert dispatch) has its own lever
# pair — strategy (flat / hier / hier_qcross) and cross-slice wire dtype —
# steered by the autopilot at flush boundaries exactly like the allreduce
# pair above. It is a SEPARATE registry: alltoall moves activations, not
# error-fed gradients, so its quantization policy must never ride the
# allreduce knobs implicitly (docs/performance.md: when NOT to quantize
# the expert leg). The cross dtype reuses the wire registry under the
# namespaced ``a2a:<ps>@dcn`` key so user pins / runtime sync / clear all
# behave identically.
# ----------------------------------------------------------------------------

_a2a_strategy_registry = {}    # ps_label -> (value, source)


def set_alltoall_strategy(strategy, ps_label="global"):
    """Pin the eager/moe alltoall dispatch strategy for one process set
    ('' restores the config default). Plans are keyed on the strategy, so
    a flip routes the next dispatch through a differently-keyed plan with
    no desync window — the same contract as
    :func:`set_dispatch_strategy`."""
    s = _normalize_strategy(strategy)
    with _wire_lock:
        _a2a_strategy_registry[str(ps_label)] = (s, "user")
    return s


def runtime_sync_alltoall_strategy(strategy, ps_label="global"):
    """Flush-boundary adoption of the autotuner's alltoall strategy
    choice; defers to an explicit user pin like
    :func:`runtime_sync_dispatch_strategy`."""
    s = _normalize_strategy(strategy)
    with _wire_lock:
        cur = _a2a_strategy_registry.get(str(ps_label))
        if cur is not None and cur[1] == "user":
            return cur[0]
        _a2a_strategy_registry[str(ps_label)] = (s, "runtime")
    return s


def alltoall_strategy_for(ps_label, default=""):
    """Effective alltoall dispatch strategy for a process set: registry
    entry, else ``default`` (normally derived from
    ``config.hierarchical_alltoall``)."""
    with _wire_lock:
        v = _a2a_strategy_registry.get(str(ps_label))
    return (default or "") if v is None or not v[0] else v[0]


def set_alltoall_cross_dtype(dtype, ps_label="global"):
    """Pin the wire dtype of the hierarchical alltoall's cross-slice
    (DCN) leg for one process set ('' restores the config default)."""
    return set_wire_dtype(dtype, f"a2a:{ps_label}", tier="dcn")


def runtime_sync_alltoall_cross_dtype(dtype, ps_label="global"):
    """Flush-boundary adoption of the autotuner's expert cross-wire
    choice; defers to an explicit user pin."""
    return runtime_sync_wire_dtype(dtype, f"a2a:{ps_label}", tier="dcn")


def alltoall_cross_wire_for(ps_label, config):
    """Effective wire dtype of the hierarchical alltoall's CROSS-SLICE
    (DCN) leg — THE resolution chain runtime and static model share:
    per-set registry entry (``a2a:<ps>@dcn``), else
    ``HOROVOD_ALLTOALL_CROSS_DTYPE``. Deliberately does NOT fall back to
    the allreduce DCN wire: alltoall payloads are activations without
    error feedback, so quantizing them must be an explicit choice."""
    default = getattr(config, "alltoall_cross_dtype", "")
    return wire_dtype_for(f"a2a:{ps_label}", default, tier="dcn")


def zero_residual(mesh, sharding, n, flat_len):
    """Fresh all-zero error-feedback residual for one bucket: global
    ``(n, flat_len)`` fp32, sharded rank-major like the bucket's stacked
    inputs — the ONE constructor both the eager wire plan and the fusion
    runtime use."""
    from horovod_tpu.ops.collective_ops import _local_mesh_info
    multi, local_pos = _local_mesh_info(mesh)
    if multi:
        loc = np.zeros((len(local_pos), flat_len), np.float32)
        return jax.make_array_from_process_local_data(
            sharding, loc, (n, flat_len))
    return jax.device_put(jnp.zeros((n, flat_len), jnp.float32), sharding)


# ----------------------------------------------------------------------------
# One-shot per-dispatch wire request (the Compression.int8 eager route:
# compress() arms it, the immediately-following eager allreduce consumes
# it — read-and-clear, so it can never leak past one dispatch).
# ----------------------------------------------------------------------------

_tls = threading.local()


def request_wire_once(dtype):
    _tls.once = dtype


def consume_wire_request():
    v = getattr(_tls, "once", None)
    _tls.once = None
    return v


# ----------------------------------------------------------------------------
# Wire-byte accounting (the metrics registry's wire_bytes_total{dtype}).
# ----------------------------------------------------------------------------

def exchange_leg_bytes(per_rank_elems, n):
    """Bytes on the wire for ONE leg of the block-scaled exchange over
    ``n`` ranks of a ``per_rank_elems``-element buffer: the 1-byte payload
    plus the fp32 block scales, padding included (the exchange pads to
    n×BLOCK). Both legs move the same byte count, but over different
    schedules — the first is an AllToAll, the second an AllGather — which
    is why the analysis cost model splits them per leg when classifying
    ICI vs DCN traffic."""
    per_rank_elems = int(per_rank_elems)
    n = max(int(n), 1)
    padded = -(-per_rank_elems // (n * BLOCK)) * n * BLOCK
    blocks = padded // BLOCK
    return n * (padded + blocks * 4)


def exchange_wire_bytes(per_rank_elems, n):
    """Bytes on the wire for one block-scaled exchange over ``n`` ranks of
    a ``per_rank_elems``-element buffer: both 1-byte legs plus the fp32
    block scales, padding included (the exchange pads to n×BLOCK)."""
    return 2 * exchange_leg_bytes(per_rank_elems, n)


def quantized_eligible(total_per_rank_elems, n, all_float, sum_or_avg):
    """THE quantized-wire eligibility predicate shared by the runtime
    (``collective_ops._eager_wire_for``) and the static cost model
    (``analysis/cost.py``), so the analyzer can never predict a wire the
    dispatch layer would refuse: only float Sum/Average payloads of at
    least one BLOCK per destination rank ride the exchange — below that
    the n×BLOCK padding INFLATES the wire and the exact collective moves
    fewer bytes."""
    return bool(all_float and sum_or_avg
                and int(total_per_rank_elems) >= max(int(n), 1) * BLOCK)


def ring_dcn_fraction(members, slice_size):
    """Fraction of a rank-ordered ring's hops that cross a slice boundary
    (wraparound included): ``S/n`` for the world-spanning global set. THE
    tier-split rule shared by the runtime counters
    (``metrics.record_wire``'s default split) and the static cost model
    (``analysis/cost.py``), so the two can never disagree."""
    m = len(members)
    if m <= 1:
        return 0.0
    from horovod_tpu.common.topology import slice_of_rank
    crossings = sum(
        slice_of_rank(members[i], slice_size)
        != slice_of_rank(members[(i + 1) % m], slice_size)
        for i in range(m))
    return crossings / m


def a2a_dcn_fraction(members, slice_size):
    """Fraction of all-to-all destination rows that land in a foreign
    slice: ``1 - slice_size/n`` for the world-spanning global set (shared
    with the static cost model like :func:`ring_dcn_fraction`)."""
    m = len(members)
    if m <= 1:
        return 0.0
    from horovod_tpu.common.topology import slice_of_rank
    counts = {}
    for r in members:
        s = slice_of_rank(r, slice_size)
        counts[s] = counts.get(s, 0) + 1
    same = sum(c * c for c in counts.values())
    return (m * m - same) / (m * m)


def split_tiers(nbytes, frac_dcn):
    """``{"ici": b, "dcn": b}`` for one leg's bytes at a DCN fraction —
    one rounding rule (round-half-even on the DCN share) everywhere, so
    runtime counters and static predictions agree to the byte."""
    nbytes = int(nbytes)
    dcn = int(round(nbytes * frac_dcn))
    return {"ici": nbytes - dcn, "dcn": dcn}


def hierarchical_wire_bytes(per_rank_elems, n, num_slices, itemsize,
                            cross_wire=""):
    """Per-tier byte accounting for ONE 2-level hierarchical allreduce
    (local RS -> cross-slice allreduce -> local AG) of a
    ``per_rank_elems``-element per-rank buffer over ``n`` ranks in
    ``num_slices`` slices — the SAME integer formulas the runtime
    dispatch records and the static model's hierarchical what-if
    predicts, which is what makes ``cross_check_bytes`` exact (delta 0)
    on the CPU tier.

    Convention (matching the flat accounting): each leg counts
    participants x per-participant payload x width; the exact cross
    allreduce counts both its internal legs. Returns ``{"ici", "dcn",
    "cross_label", "shard_elems", "local_size", "num_slices"}`` —
    ``cross_label`` is the quantized label actually eligible on the
    cross leg (None = exact: sub-block shards would INFLATE on the
    exchange's S x BLOCK padding, same refusal as the flat wire)."""
    n = max(int(n), 1)
    num_slices = max(int(num_slices), 1)
    itemsize = max(int(itemsize), 1)
    local = max(n // num_slices, 1)
    shard = -(-int(per_rank_elems) // local)         # ceil
    padded = shard * local
    ici = 2 * n * padded * itemsize                  # local RS + local AG
    label = quantized_label(cross_wire)
    if label is not None and not quantized_eligible(
            shard, num_slices, True, True):
        label = None
    if label is not None:
        dcn = local * exchange_wire_bytes(shard, num_slices)
    else:
        dcn = 2 * n * shard * itemsize               # exact cross RS+AG
    return {"ici": ici, "dcn": dcn, "cross_label": label,
            "shard_elems": shard, "local_size": local,
            "num_slices": num_slices}


def hierarchical_a2a_bytes(per_rank_elems, n, num_slices, itemsize,
                           cross_wire=""):
    """Per-tier byte accounting for ONE 2-level hierarchical alltoall
    (slice-local a2a on ICI -> cross-slice a2a on the per-tier wire) of a
    ``per_rank_elems``-element per-rank buffer over ``n`` ranks in
    ``num_slices`` slices — the SAME integer formulas the runtime dispatch
    records and the static model's a2a what-if predicts, which is what
    keeps ``cross_check_bytes`` at delta 0 on the CPU tier.

    Convention (matching the flat accounting): each leg counts
    participants x per-participant payload x width, self-destined chunks
    included. The local leg is entirely in-slice (all ici). The cross leg
    runs one a2a over ``num_slices`` participants per local group — its
    members sit in ``num_slices`` DISTINCT slices, so its own
    :func:`a2a_dcn_fraction` is ``(S-1)/S`` and :func:`split_tiers` books
    that share to dcn (the genuinely cross-slice rows move exactly once,
    the information-theoretic floor). Returns ``{"local", "cross",
    "cross_tiers", "ici", "dcn", "cross_label", "local_size",
    "num_slices"}`` — ``cross_label`` is the quantized label actually
    eligible on the cross leg (None = exact: payloads below one BLOCK per
    destination slice would INFLATE on the exchange's S x BLOCK padding,
    the same refusal as the flat wire)."""
    n = max(int(n), 1)
    num_slices = max(int(num_slices), 1)
    itemsize = max(int(itemsize), 1)
    local_size = max(n // num_slices, 1)
    per = int(per_rank_elems)
    local_leg = n * per * itemsize
    label = quantized_label(cross_wire)
    if label is not None and not quantized_eligible(
            per, num_slices, True, True):
        label = None
    if label is not None:
        cross_leg = local_size * exchange_leg_bytes(per, num_slices)
    else:
        cross_leg = n * per * itemsize
    frac = (num_slices - 1) / num_slices if num_slices > 1 else 0.0
    cross_tiers = split_tiers(cross_leg, frac)
    return {"local": local_leg, "cross": cross_leg,
            "cross_tiers": cross_tiers,
            "ici": local_leg + cross_tiers["ici"],
            "dcn": cross_tiers["dcn"],
            "cross_label": label, "local_size": local_size,
            "num_slices": num_slices}


def allreduce_wire_bytes(payload_bytes, itemsize, n, wire):
    """Bytes-on-wire estimate for one allreduce of a global rank-major
    payload. Full-precision / cast wires model the ring allreduce's
    internal reduce-scatter + all-gather (every element crosses the wire
    twice at the wire width); quantized wires use the exchange's exact
    accounting. This is the estimate ``wire_bytes_total`` accumulates —
    the <0.3x int8-vs-fp32 guard in tests/test_wire.py holds it honest."""
    itemsize = max(int(itemsize), 1)
    elems = int(payload_bytes) // itemsize
    if quantized_label(wire):
        return exchange_wire_bytes(max(elems // max(int(n), 1), 0), n)
    width = {"float16": 2, "bfloat16": 2}.get(wire or "", itemsize)
    return 2 * elems * width
