"""Adasum: scale-invariant gradient combination.

Reference algorithm (horovod/common/ops/adasum/adasum.h:103+): combine two
gradient vectors ``a``, ``b`` as

    adasum(a, b) = (1 - a.b / (2*||a||^2)) * a  +  (1 - a.b / (2*||b||^2)) * b

applied pairwise in a recursive-halving-doubling tree (VHDD) so the result is
invariant to gradient scale and converges like a trust-region method.

TPU-native design: the dot products and norms are tiny reductions XLA fuses
into the surrounding program, so instead of the reference's hand-rolled MPI
recursive halving (adasum_mpi.cc) we gather shards over ICI once and run the
combine tree locally on every chip — identical math, one collective. The
numerics run in fp32 regardless of input dtype, matching the reference's
accumulate-in-float behavior for fp16 (adasum.h AVX fp16 paths).
"""

import jax.numpy as jnp
from jax import lax


def adasum_combine(a, b, eps=1e-30):
    """Combine two same-shaped gradient tensors (reference: adasum.h:103+)."""
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    dot = jnp.sum(af * bf)
    na = jnp.sum(af * af)
    nb = jnp.sum(bf * bf)
    ca = jnp.where(na > eps, 1.0 - dot / (2.0 * jnp.maximum(na, eps)), 1.0)
    cb = jnp.where(nb > eps, 1.0 - dot / (2.0 * jnp.maximum(nb, eps)), 1.0)
    return (ca * af + cb * bf).astype(a.dtype)


def adasum_tree(tensors):
    """Pairwise combine a list of tensors in a binary tree, matching the
    reference's recursive halving-doubling combination order."""
    level = list(tensors)
    while len(level) > 1:
        nxt = []
        for i in range(0, len(level) - 1, 2):
            nxt.append(adasum_combine(level[i], level[i + 1]))
        if len(level) % 2 == 1:
            nxt.append(level[-1])
        level = nxt
    return level[0]


def adasum_reduce_shard(x, axis_name, n):
    """In-shard_map Adasum reduction across ``axis_name``.

    ``x`` is this rank's local slice. Gathers all ranks' slices (one ICI
    all-gather) and evaluates the combine tree locally; every rank computes the
    same result, mirroring the allreduce contract of
    AdasumMPIAllreduceOp (reference: adasum_mpi_operations.cc).
    """
    g = lax.all_gather(x, axis_name)  # (n, ...) leading axis = ranks
    return adasum_tree([g[i] for i in range(n)])
