"""Eager collective operations over the TPU mesh.

Reference surface being matched: ``hvd.allreduce / grouped_allreduce / allgather /
broadcast / alltoall / reducescatter`` + async variants and handles
(reference: horovod/torch/mpi_ops.py:134-1285, horovod/common/operations.cc:1453-2086
``EnqueueTensorAllreduces`` etc., op math in horovod/common/ops/
collective_operations.cc).

TPU-native design — NOT a port of the background-thread/NCCL model:

- A Horovod rank is a chip in the global ``Mesh``. Eager tensors use the
  **rank-major stacked layout**: a collective input has leading axis ``set_size``
  and is sharded over the mesh's ``hvd`` axis, so slice ``[r]`` lives on chip
  ``r`` — the moral equivalent of "each rank's local tensor".
- Each (op, signature) pair compiles once into a ``shard_map``-wrapped XLA
  program using native ICI collectives (``lax.psum/all_gather/psum_scatter/
  all_to_all``). The compile cache keyed on the signature replaces the
  reference's coordinator negotiation + response cache
  (reference: horovod/common/controller.cc:74 ComputeResponseList,
  response_cache.h:45): a cache hit is a steady-state step with zero
  host-side negotiation.
- Async semantics come for free: JAX dispatch is asynchronous, so ``*_async``
  returns a handle wrapping the in-flight device array; ``synchronize`` blocks,
  ``poll`` checks readiness — matching the HandleManager contract
  (reference: horovod/torch/handle_manager.h, mpi_ops.py:1245-1283).
"""

import enum
import functools
import weakref

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import trace as _trace
from horovod_tpu.chaos import injector as _chaos
from horovod_tpu.common import basics
from horovod_tpu.flight import recorder as _flight
from horovod_tpu.ops import wire as _wire
from horovod_tpu.profile import ledger as _profile
from horovod_tpu.common.exceptions import TensorShapeMismatchError
from horovod_tpu.common.process_sets import global_process_set
from horovod_tpu.common.topology import HVD_AXIS


class ReduceOp(enum.IntEnum):
    """reference: horovod/common/message.h:43-50 (enum ReduceOp)."""
    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


# Public aliases matching hvd.Average / hvd.Sum / hvd.Adasum / ...
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT


# ----------------------------------------------------------------------------
# Static-analysis interception (horovod_tpu/analysis/program.py).
#
# ``hvd.check_program`` abstract-evals a user step function per simulated
# rank with ZERO device execution; while it traces, every eager entry point
# below routes through this hook, which records the would-be dispatch
# (op, process set, signature) and returns an abstract stand-in result.
# One ``is not None`` check on the hot path when no analysis is running.
# ----------------------------------------------------------------------------

_intercept = None


def set_intercept(hook):
    """Install (or clear, with ``None``) the eager-dispatch interceptor.
    ``hook(kind, args, kwargs)`` may return ``NotImplemented`` to fall
    through to the real dispatch. Analysis-only: not thread-safe by
    design — the analyzer owns the process while tracing."""
    global _intercept
    prev = _intercept
    _intercept = hook
    return prev


def _interceptable(kind):
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            hook = _intercept
            if hook is not None:
                out = hook(kind, args, kwargs)
                if out is not NotImplemented:
                    return out
            return fn(*args, **kwargs)

        wrapper.__wrapped__ = fn
        return wrapper

    return deco


def _mesh_for(process_set):
    ps = process_set if process_set is not None else global_process_set
    return ps.mesh, ps


@functools.lru_cache(maxsize=1024)
def _local_mesh_info(mesh):
    """``(spans_processes, local_positions)`` for a mesh: whether it includes
    devices owned by other processes, and the flat positions of this
    process's devices within it (rank-major).

    Multi-process eager semantics: each process supplies/receives the
    **local** slice of the rank-major stack — ``local_positions`` rows —
    while the compiled program runs over the global mesh (the multi-host
    contract the reference implements with per-rank buffers + NCCL/Gloo;
    here the global array is assembled with
    ``jax.make_array_from_process_local_data``).
    """
    devs = list(mesh.devices.flat)
    me = jax.process_index()
    local = tuple(i for i, d in enumerate(devs) if d.process_index == me)
    return len(local) != len(devs), local


def _mesh_processes(mesh):
    """Sorted process indices owning devices of ``mesh`` — the participant
    list for control-plane negotiations scoped to a process set."""
    return sorted({d.process_index for d in mesh.devices.flat})


def _expected_rows(mesh, n):
    """Leading-axis size of the eager stacked layout this process must
    supply: all ``n`` rows single-process, only the local rows otherwise."""
    multi, local_pos = _local_mesh_info(mesh)
    return len(local_pos) if multi else n


def _check_stacked(x, n, what):
    if x.ndim < 1 or x.shape[0] != n:
        raise TensorShapeMismatchError(
            f"{what}: expected rank-major stacked tensor with leading axis "
            f"{n} (one slice per rank), got shape {tuple(x.shape)}. ")


import contextlib
import time


def _ps_label(process_set):
    """Bounded-cardinality process-set label for metrics series: 'global'
    or the registered set id."""
    if process_set is None or process_set.ranks is None:
        return "global"
    pid = getattr(process_set, "process_set_id", None)
    return f"set{pid}" if pid is not None else "unregistered"


def _translate_dispatch_error(name, op_label, e):
    """Runtime-failure epilogue shared by :func:`_timeline_op` and the
    dispatch-plan fast path: count the error, then re-raise — translating
    transport/peer failures to :class:`HorovodInternalError`.

    Inside the dispatch only the compiled program executes (inputs were
    validated before it). Translate ONLY transport/peer failures to
    HorovodInternalError — those are what elastic recovery can fix by
    re-rendezvousing (e.g. status UNKNOWN "Gloo all-reduce failed:
    Connection closed by peer" maps to ValueError, coordination
    aborts to JaxRuntimeError). Deterministic runtime errors (OOM =
    RESOURCE_EXHAUSTED, shape/layout issues) must propagate as-is or
    the elastic @run wrapper would retry them forever."""
    from horovod_tpu.metrics import instruments as hvd_metrics
    hvd_metrics.record_collective_error(op_label)
    if _flight.armed:
        # The flight recorder's reason to exist: a failed dispatch leaves
        # a per-rank JSONL dump (ring of recent collectives + this error)
        # for horovod_tpu.flight.analyze to merge — no pre-arming needed.
        _flight.record_event("error", op=op_label, name=name,
                             what=(str(e).splitlines() or [""])[0][:200])
        _flight.dump("dispatch_error")
    from horovod_tpu.common.exceptions import HorovodInternalError
    if isinstance(e, HorovodInternalError):
        raise e
    msg = str(e)
    transport = any(m in msg for m in (
        "UNAVAILABLE", "UNKNOWN", "DEADLINE_EXCEEDED", "ABORTED",
        "CANCELLED", "Gloo", "gloo", "onnection",  # Connection/connection
        "peer", "heartbeat", "oordination", "socket", "Socket"))
    if jax.process_count() > 1 and transport:
        raise HorovodInternalError(
            f"collective {name} failed at runtime: "
            f"{(msg.splitlines() or [''])[0][:200]}") from e
    raise e


def _set_wire_tiers(process_set, wire_nbytes, sched):
    """Per-tier split of a NON-planned eager dispatch's wire bytes over
    its process set's member ranks — the plan path's ``_flat_tiers`` rule
    (the static model classifies by real members, so a set confined to
    one slice books zero dcn even when the world spans several). Returns
    ``None`` for the global set / single-slice layouts, where
    ``record_wire``'s world-level default split already matches."""
    try:
        if process_set is None or getattr(process_set, "ranks", None) is None:
            return None
        st = basics._state
        world = st.topology.size if st is not None else 0
        slices, slice_size = _live_slices(world) if world else (1, 1)
        if slices <= 1 or not wire_nbytes:
            return None
        members = process_set.rank_list()
        frac = _wire.a2a_dcn_fraction(members, slice_size) \
            if sched == "a2a" \
            else _wire.ring_dcn_fraction(members, slice_size)
        return _wire.split_tiers(wire_nbytes, frac)
    except Exception:  # noqa: BLE001 — accounting must never break a
        return None    # dispatch


@contextlib.contextmanager
def _timeline_op(name, op_kind, tensors=(), process_set=None,
                 op_label=None, ps_label=None, wire=None):
    """Timeline span + metrics + failure translation around one eager
    collective.

    Metrics: the span is the single choke point every eager dispatch (sync
    ops AND fused flush buckets) passes through, so per-op count/bytes go
    in at entry (failures still count as attempts) and the latency
    histogram on successful return — the aggregate layer the reference
    never had (its observability stops at the timeline trace).
    ``op_label``/``ps_label``: precomputed label strings (the dispatch-plan
    fast path passes them so nothing is re-formatted per call).

    ``wire``: optional ``(path, dtype_label, wire_nbytes, compressed[,
    tiers])`` override — or a LIST of such tuples (the hierarchical
    dispatch paths record one per link tier) — for the wire-byte
    accounting (the fused flush and the quantized eager path pass their
    exact on-wire estimate); without it the payload dtype/bytes are
    derived here (allreduce counts both internal RS+AG legs).

    A collective that dies at runtime (peer process gone, transport torn
    down mid-op) must surface as :class:`HorovodInternalError` so the
    elastic ``@run`` wrapper can restore the last commit and re-rendezvous
    (reference: common/exceptions.py — op status callbacks raise
    HorovodInternalError; nccl_operations.h:70 async error polling)."""
    from horovod_tpu.metrics import instruments as hvd_metrics
    if op_label is None:
        op_label = op_kind.lower()
    # Profiler bracket opens BEFORE the chaos site: an injected delay is a
    # host-side stall of THIS rank's dispatch path, and landing it in the
    # ledger's host_dispatch category is what lets the watchdog name the
    # straggler by its own-rank signal (its peers book the wait under
    # `collective` instead).
    profile_on = _profile.armed
    if profile_on:
        t_api = time.perf_counter()
    if _chaos.armed:
        # Chaos site: a delay here holds THIS rank's enqueue back while its
        # peers dispatch — the straggler mode of the SPMD contract.
        _chaos.fire("collective.dispatch")
    # Gated HERE, not just inside the helpers: the nbytes sum is
    # O(n_tensors) and must cost nothing under HOROVOD_METRICS=0.
    metrics_on = hvd_metrics.enabled()
    flight_on = _flight.armed
    if metrics_on or flight_on or profile_on:
        nbytes = sum(getattr(t, "nbytes", 0) for t in tensors)
        if ps_label is None:
            ps_label = _ps_label(process_set)
        t0 = time.perf_counter()
    if metrics_on:
        hvd_metrics.record_collective(op_label, nbytes, ps_label)
        if wire is not None:
            for w in (wire if isinstance(wire, list) else [wire]):
                hvd_metrics.record_wire(
                    w[0], w[1], w[2], w[3],
                    tiers=w[4] if len(w) > 4 else None)
        elif tensors:
            wb = nbytes * (2 if op_kind == "ALLREDUCE" else 1)
            sched = "a2a" if op_kind == "ALLTOALL" else "ring"
            hvd_metrics.record_wire(
                "eager", str(_dtype_of(tensors[0])), wb, sched=sched,
                tiers=_set_wire_tiers(process_set, wb, sched))
    if flight_on:
        # SPMD contract: every process dispatches the same collectives in
        # the same order, so the per-process-set seq assigned here lines
        # up across ranks — the analyzer's desync key. Caveat: seq is
        # arrival-ordered, so when the fusion CYCLE THREAD flushes
        # concurrently with main-thread eager dispatches the eager/fused
        # interleaving (and thus seq->op mapping) can differ per rank;
        # max-seq comparisons stay valid, first-diverging identification
        # is corroborated by op/sig in the analyzer.
        fl_seq = _flight.record_dispatch(op_label, ps_label, nbytes,
                                         _flight.signature(tensors), name)
    tl = basics.timeline()
    span = tl.op_span(name, op_kind) if tl is not None \
        else contextlib.nullcontext()
    try:
        # TraceAnnotation mirrors the span into jax.profiler XPlane traces,
        # so device profiles correlate with timeline buckets by name
        # (SURVEY §5.1: the reference's NVTX ranges around every enqueue,
        # nvtx_op_range.h).
        with jax.profiler.TraceAnnotation(f"hvd::{op_kind}::{name}"):
            with span:
                yield
        if metrics_on or flight_on or profile_on:
            dur = time.perf_counter() - t0
        if metrics_on:
            hvd_metrics.record_collective_latency(op_label, dur)
        if flight_on:
            _flight.record_complete(op_label, ps_label, fl_seq, dur)
            # Dispatch span under the ACTIVE step trace (rotated by
            # step_marker); correlates with the flight ring via the seq
            # the dispatch event carries.
            _trace.add_span(_trace.get_active(), "dispatch",
                            time.time() - dur, dur, cat="train",
                            args={"op": op_label, "seq": fl_seq})
        if profile_on:
            # dur covers the program call (+ localize on the caller side
            # of the yield) = `collective`; everything else between the
            # bracket open and here is dispatch-path overhead.
            _profile.record_dispatch(
                op_label, dur, time.perf_counter() - t_api - dur, nbytes)
    except (ValueError, RuntimeError) as e:
        _translate_dispatch_error(name, op_label, e)


def _is_float(dtype):
    return jnp.issubdtype(dtype, jnp.floating) or \
        jnp.issubdtype(dtype, jnp.complexfloating)


def _dtype_of(t):
    """Dtype without materializing a device array (hot-path friendly)."""
    dt = getattr(t, "dtype", None)
    return dt if dt is not None else np.result_type(t)


# ----------------------------------------------------------------------------
# In-jit reduction bodies (applied per-shard inside shard_map).
# ----------------------------------------------------------------------------

def _reduce_shard(x, op, n, prescale, postscale, axis_name, active=None):
    """Reduce one rank's shard across ``axis_name``. x: (1, ...) local slice.

    ``active``: optional 0/1 numpy vector over ranks — joined ranks are
    excluded (reference: JOIN / joined_size accounting,
    controller.cc:269-327): Sum treats them as zeros, Average divides by the
    active count, Min/Max/Product/Adasum statically drop their slices.
    """
    if prescale != 1.0:
        x = x * jnp.asarray(prescale, x.dtype)
    n_active = n if active is None else int(active.sum())
    if op in (ReduceOp.SUM, ReduceOp.AVERAGE):
        if active is not None:
            keep = jnp.asarray(active)[lax.axis_index(axis_name)]
            x = x * keep.astype(x.dtype)
        y = lax.psum(x, axis_name)
        if op == ReduceOp.AVERAGE:
            y = y / jnp.asarray(n_active, y.dtype)
    elif active is not None:
        # non-linear ops: gather all, statically select the active ranks
        g = lax.all_gather(jnp.squeeze(x, 0), axis_name)
        g = g[np.nonzero(active)[0]]
        if op == ReduceOp.MIN:
            y = jnp.min(g, axis=0)[None]
        elif op == ReduceOp.MAX:
            y = jnp.max(g, axis=0)[None]
        elif op == ReduceOp.PRODUCT:
            y = jnp.prod(g, axis=0)[None]
        elif op == ReduceOp.ADASUM:
            from horovod_tpu.ops.adasum import adasum_tree
            y = adasum_tree([g[i] for i in range(n_active)])[None]
        else:
            raise ValueError(f"Unknown reduce op {op}")
    elif op == ReduceOp.MIN:
        y = lax.pmin(x, axis_name)
    elif op == ReduceOp.MAX:
        y = lax.pmax(x, axis_name)
    elif op == ReduceOp.PRODUCT:
        g = lax.all_gather(x, axis_name)  # (n, 1, ...)
        y = jnp.prod(g, axis=0)
    elif op == ReduceOp.ADASUM:
        from horovod_tpu.ops.adasum import adasum_reduce_shard
        y = adasum_reduce_shard(x, axis_name, n)
    else:
        raise ValueError(f"Unknown reduce op {op}")
    if postscale != 1.0:
        y = y * jnp.asarray(postscale, y.dtype)
    return y


# ----------------------------------------------------------------------------
# Compiled-program cache: signature -> jitted shard_map program.
# ----------------------------------------------------------------------------

@functools.lru_cache(maxsize=4096)
def _allreduce_program(mesh, n, op, prescale, postscale, shapes, dtypes,
                       active_mask=None, donate=False):
    """``active_mask``: optional tuple of 0/1 per rank — joined ranks are
    masked out of the reduction and Average divides by the active count
    (reference: JOIN handling / joined_size accounting, controller.cc:269-327
    and operations.cc global joined_size). ``donate``: donate every input
    buffer to XLA so the output reuses its HBM — the eager-path opt-in
    (``HOROVOD_DONATE_BUFFERS`` set explicitly; used by the dispatch-plan
    fast path only when the inputs are already sharded jax.Arrays, where
    in-place reuse is actually possible)."""
    active = None if active_mask is None else np.array(active_mask)

    def body(*xs):
        return tuple(
            _reduce_shard(x, op, n, prescale, postscale, HVD_AXIS, active)
            for x in xs)

    f = jax.shard_map(body, mesh=mesh,
                      in_specs=tuple(P(HVD_AXIS) for _ in shapes),
                      out_specs=tuple(P(HVD_AXIS) for _ in shapes))
    return jax.jit(f, donate_argnums=tuple(range(len(shapes)))
                   if donate else ())


@functools.lru_cache(maxsize=1024)
def _quantized_allreduce_program(mesh, n, op, prescale, postscale, shapes,
                                 dtypes, wire_name, ef):
    """Eager allreduce over the block-scaled quantized exchange
    (ops/wire.py): the group's tensors are concatenated into ONE flat
    fp32 buffer (minimizing the exchange's n×BLOCK padding, exactly like
    the fused path), exchanged at 1 byte/element with per-block scales,
    then split/cast back per tensor. With ``ef`` the program additionally
    takes the bucket's fp32 residual — global ``(n, L)`` sharded rank-major
    — and returns the new residual as its last output (error feedback:
    residual added after prescale, before quantization)."""
    sizes = [int(np.prod(s[1:])) for s in shapes]
    flat_len = sum(sizes)

    def body(*args):
        xs = args[:len(shapes)]
        flats = [x.reshape(-1).astype(jnp.float32) for x in xs]
        buf = jnp.concatenate(flats) if len(flats) > 1 else flats[0]
        residual = args[-1].reshape(-1) if ef else None
        red, new_res = _wire.block_scaled_allreduce(
            buf, residual=residual, axis_name=HVD_AXIS, wire=wire_name,
            average=(op == ReduceOp.AVERAGE), prescale_factor=prescale,
            postscale_factor=postscale)
        outs, off = [], 0
        for x, sz in zip(xs, sizes):
            piece = lax.slice_in_dim(red, off, off + sz).astype(x.dtype)
            outs.append(piece.reshape(x.shape))
            off += sz
        if ef:
            outs.append(new_res.reshape(1, flat_len))
        return tuple(outs)

    n_args = len(shapes) + (1 if ef else 0)
    f = jax.shard_map(body, mesh=mesh,
                      in_specs=tuple(P(HVD_AXIS) for _ in range(n_args)),
                      out_specs=tuple(P(HVD_AXIS) for _ in range(n_args)))
    return jax.jit(f)


def _live_slices(n):
    """``(num_slices, slice_size)`` the dispatch layer sees RIGHT NOW for
    an ``n``-rank world: the forced ``HOROVOD_MESH_SLICES`` knob (read
    live, like the static model's ``resolve_slices``), else the
    initialized topology's DCN hierarchy — both through
    ``topology.slice_layout``'s divisibility rules, so runtime and static
    layouts can never disagree."""
    from horovod_tpu.common import topology as _topology
    k = _topology.forced_slices()
    if not k:
        st = basics._state
        topo = st.topology if st is not None else None
        if topo is not None and topo.num_slices > 1 and topo.size == n:
            k = topo.num_slices
        else:
            return 1, max(int(n), 1)
    return _topology.slice_layout(n, k)


@functools.lru_cache(maxsize=64)
def _hier_mesh(mesh, num_slices):
    """(slice x chips-per-slice) mesh over one process set's devices — the
    2-level decomposition's (cross=DCN, local=ICI) factorization. The
    initialized topology's real DCN mesh is preferred when it covers the
    same devices (its device order is slice-sorted); a forced/virtual
    hierarchy reshapes the set's rank-major device array like
    ``topology._build_dcn_mesh`` does. Cleared by
    :func:`clear_program_caches` — an elastic resize must never replay a
    stale slice layout."""
    from jax.sharding import Mesh
    from horovod_tpu.common.topology import CROSS_AXIS, LOCAL_AXIS
    devs = list(mesh.devices.flat)
    st = basics._state
    topo = st.topology if st is not None else None
    if topo is not None and topo.mesh_dcn is not None \
            and topo.num_slices == num_slices \
            and set(topo.mesh_dcn.devices.flat) == set(devs):
        return topo.mesh_dcn
    per = len(devs) // int(num_slices)
    arr = np.array(devs, dtype=object).reshape(int(num_slices), per)
    return Mesh(arr, (CROSS_AXIS, LOCAL_AXIS))


@functools.lru_cache(maxsize=1024)
def _hier_allreduce_program(hier_mesh, n, op, prescale, postscale, shapes,
                            dtypes, cross_wire, ef):
    """Eager allreduce through the hierarchical dispatch tier: the group's
    (dtype-homogeneous) tensors are concatenated into ONE flat buffer,
    decomposed as local RS (exact, ICI) -> cross-slice allreduce on
    ``cross_wire`` (DCN; ``""`` = exact psum) -> local AG
    (``strategies.allreduce_torus`` — the fork's NCCLTorusAllreduce
    shape), then split back per tensor. With ``ef`` the program takes the
    bucket's fp32 cross-leg residual — global ``(n, shard_len)`` sharded
    rank-major — and returns the new residual as its last output."""
    from horovod_tpu.common.topology import CROSS_AXIS, LOCAL_AXIS
    from horovod_tpu.ops.in_jit import mark_varying
    from horovod_tpu.parallel.strategies import allreduce_torus
    sizes = [int(np.prod(s[1:])) for s in shapes]
    total = sum(sizes)
    local_n = int(hier_mesh.shape[LOCAL_AXIS])
    shard_len = -(-total // local_n)
    spec = P((CROSS_AXIS, LOCAL_AXIS))

    def body(*args):
        xs = args[:len(shapes)]
        flats = [x.reshape(-1) for x in xs]
        buf = jnp.concatenate(flats) if len(flats) > 1 else flats[0]
        if prescale != 1.0:
            buf = buf * jnp.asarray(prescale, buf.dtype)
        residual = args[-1].reshape(-1) if ef else None
        out = allreduce_torus(buf, average=(op == ReduceOp.AVERAGE),
                              cross_compression=cross_wire or None,
                              cross_residual=residual, record=False)
        if residual is not None:
            out, new_res = out
        if postscale != 1.0:
            out = out * jnp.asarray(postscale, out.dtype)
        # The cross psum/exchange leaves the value cross-invariant; the
        # stacked out_specs need it typed varying over both mesh axes.
        out = mark_varying(mark_varying(out, CROSS_AXIS), LOCAL_AXIS)
        outs, off = [], 0
        for x, sz in zip(xs, sizes):
            piece = lax.slice_in_dim(out, off, off + sz).astype(x.dtype)
            outs.append(piece.reshape(x.shape))
            off += sz
        if ef:
            res_out = mark_varying(
                mark_varying(new_res.reshape(1, shard_len), CROSS_AXIS),
                LOCAL_AXIS)
            outs.append(res_out)
        return tuple(outs)

    n_args = len(shapes) + (1 if ef else 0)
    f = jax.shard_map(body, mesh=hier_mesh,
                      in_specs=tuple(spec for _ in range(n_args)),
                      out_specs=tuple(spec for _ in range(n_args)))
    return jax.jit(f)


@functools.lru_cache(maxsize=4096)
def _allgather_program(mesh, n, shapes, dtypes, active_mask=None,
                       hierarchical=False):
    """``active_mask``: joined ranks contribute a zero-size slice, i.e. their
    rows are statically dropped from the concatenated output (reference: JOIN
    gives joined ranks zero-size allgather contributions,
    controller.cc:269-327). ``hierarchical``: 2-level gather over the
    (cross, local) mesh2d — ``mesh`` must then be it (knob
    HOROVOD_HIERARCHICAL_ALLGATHER; reference MPIHierarchicalAllgather)."""
    active_idx = None if active_mask is None else \
        np.nonzero(np.array(active_mask))[0]
    if hierarchical:
        from horovod_tpu.common.topology import CROSS_AXIS, LOCAL_AXIS
        from horovod_tpu.parallel.strategies import allgather_hierarchical
        spec = P((CROSS_AXIS, LOCAL_AXIS))
    else:
        spec = P(HVD_AXIS)

    def body(*xs):
        out = []
        for x in xs:
            # x: (1, m, ...) local slice; gather along the stacked axis and
            # flatten to the concatenated layout Horovod returns
            # (reference: collective_operations.h:137-174 size/displacement math).
            if hierarchical:
                # record=False: this eager program's dispatches are
                # metered per call by the plan/_timeline_op — trace-time
                # recording on top would double-count.
                g = allgather_hierarchical(x[0], record=False)  # (n, m, …)
                from horovod_tpu.ops.in_jit import mark_varying
                g = mark_varying(mark_varying(g, CROSS_AXIS), LOCAL_AXIS)
            else:
                g = lax.all_gather(x, HVD_AXIS, axis=0, tiled=True)
            if active_idx is not None:
                g = g[active_idx]
            g = g.reshape((1, -1) + g.shape[2:]) if g.ndim > 1 else g
            out.append(g)
        return tuple(out)

    f = jax.shard_map(body, mesh=mesh,
                      in_specs=tuple(spec for _ in shapes),
                      out_specs=tuple(spec for _ in shapes))
    return jax.jit(f)


@functools.lru_cache(maxsize=4096)
def _broadcast_program(mesh, n, root_rank, shapes, dtypes):
    def body(*xs):
        out = []
        for x in xs:
            idx = lax.axis_index(HVD_AXIS)
            mask = (idx == root_rank)
            # One-hot mask + psum == broadcast from root; a single ICI
            # collective, like the reference's tree broadcast
            # (reference: MPIBroadcast mpi_operations.cc).
            if _is_float(x.dtype) or jnp.issubdtype(x.dtype, jnp.integer):
                masked = jnp.where(mask, x, jnp.zeros_like(x))
                out.append(lax.psum(masked, HVD_AXIS))
            else:  # bool etc.
                masked = jnp.where(mask, x.astype(jnp.int32),
                                   jnp.zeros(x.shape, jnp.int32))
                out.append(lax.psum(masked, HVD_AXIS).astype(x.dtype))
        return tuple(out)

    f = jax.shard_map(body, mesh=mesh,
                      in_specs=tuple(P(HVD_AXIS) for _ in shapes),
                      out_specs=tuple(P(HVD_AXIS) for _ in shapes))
    return jax.jit(f)


@functools.lru_cache(maxsize=4096)
def _reducescatter_program(mesh, n, op, prescale, postscale, shapes, dtypes,
                           active_mask=None):
    """``active_mask``: joined ranks contribute zeros to the reduction and
    Average divides by the active count (reference: joined_size accounting,
    controller.cc:269-327)."""
    active = None if active_mask is None else np.array(active_mask)
    n_active = n if active is None else int(active.sum())

    def body(*xs):
        out = []
        for x in xs:
            # x: (1, m, ...) — scatter the reduction of the m-axis across ranks
            # (reference: ReducescatterOp shape math collective_operations.h:282-309).
            x = jnp.squeeze(x, 0)
            if prescale != 1.0:
                x = x * jnp.asarray(prescale, x.dtype)
            if active is not None:
                keep = jnp.asarray(active)[lax.axis_index(HVD_AXIS)]
                x = x * keep.astype(x.dtype)
            if op in (ReduceOp.SUM, ReduceOp.AVERAGE):
                y = lax.psum_scatter(x, HVD_AXIS, scatter_dimension=0, tiled=True)
                if op == ReduceOp.AVERAGE:
                    y = y / jnp.asarray(n_active, y.dtype)
            else:
                raise ValueError(
                    "reducescatter supports Sum/Average (reference parity: "
                    "reducescatter has no min/max/product either, message.h:43-50)")
            if postscale != 1.0:
                y = y * jnp.asarray(postscale, y.dtype)
            out.append(y[None])
        return tuple(out)

    f = jax.shard_map(body, mesh=mesh,
                      in_specs=tuple(P(HVD_AXIS) for _ in shapes),
                      out_specs=tuple(P(HVD_AXIS) for _ in shapes))
    return jax.jit(f)


@functools.lru_cache(maxsize=4096)
def _alltoall_program(mesh, n, shapes, dtypes):
    def body(*xs):
        out = []
        for x in xs:
            x = jnp.squeeze(x, 0)  # (m, ...), m divisible by n
            y = lax.all_to_all(x, HVD_AXIS, split_axis=0, concat_axis=0,
                               tiled=True)
            out.append(y[None])
        return tuple(out)

    f = jax.shard_map(body, mesh=mesh,
                      in_specs=tuple(P(HVD_AXIS) for _ in shapes),
                      out_specs=tuple(P(HVD_AXIS) for _ in shapes))
    return jax.jit(f)


@functools.lru_cache(maxsize=1024)
def _hier_alltoall_program(hier_mesh, n, shapes, dtypes, cross_wire):
    """Eager equal-splits alltoall through the hierarchical dispatch
    tier: slice-local a2a (ICI) then ONE cross-slice a2a on the per-tier
    wire (DCN; ``""`` = exact, ``int8``/``fp8`` = block-scaled), compiled
    over the (slice x chips-per-slice) mesh
    (``strategies.alltoall_tiered`` — the a2a twin of
    :func:`_hier_allreduce_program`)."""
    from horovod_tpu.common.topology import CROSS_AXIS, LOCAL_AXIS
    from horovod_tpu.ops.in_jit import mark_varying
    from horovod_tpu.parallel.strategies import alltoall_tiered
    spec = P((CROSS_AXIS, LOCAL_AXIS))

    def body(*xs):
        out = []
        for x in xs:
            x = jnp.squeeze(x, 0)  # (m, ...), m divisible by n
            # record=False: this eager program's dispatches are metered
            # per call by the plan — trace-time recording on top would
            # double-count.
            y = alltoall_tiered(x, cross_wire=cross_wire or None,
                                record=False)
            y = mark_varying(mark_varying(y, CROSS_AXIS), LOCAL_AXIS)
            out.append(y[None])
        return tuple(out)

    f = jax.shard_map(body, mesh=hier_mesh,
                      in_specs=tuple(spec for _ in shapes),
                      out_specs=tuple(spec for _ in shapes))
    return jax.jit(f)


def clear_program_caches():
    """Drop all compiled eager-collective programs (and the mesh/device
    objects they capture). Needed when the backend is rebuilt — e.g. an
    elastic membership change (basics.teardown_distributed); the analog of
    the reference invalidating its response cache on world reconfig
    (response_cache.h:45, elastic abort path)."""
    for prog in (_local_mesh_info, _allreduce_program,
                 _quantized_allreduce_program, _hier_allreduce_program,
                 _hier_mesh, _allgather_program,
                 _broadcast_program, _reducescatter_program,
                 _alltoall_program, _hier_alltoall_program,
                 _barrier_program,
                 _alltoall_pack_index, _hier_verdict, _a2a_hier_verdict):
        prog.cache_clear()
    # The cached flat-schedule tier split reads the slice layout; a
    # resized/re-sliced mesh must re-resolve it (like the hierarchy-keyed
    # plans and programs above — elastic resize never replays a stale
    # slice layout).
    from horovod_tpu.metrics import instruments as _ins
    _ins.reset_tier_split()
    # Error-feedback residuals are device arrays of the torn-down backend
    # (and sized for the old world): a resized mesh must start clean.
    _wire.reset_error_feedback()
    # Dispatch plans capture compiled programs + NamedShardings of the
    # torn-down backend; a stale hit after an elastic resize would dispatch
    # into a dead client.
    _invalidate_plans()
    # Fused eager programs are keyed by Mesh too; stale entries would pin a
    # torn-down XLA client (and its buffers) for the rest of the job.
    from horovod_tpu.ops import fusion
    fusion._fused_program.cache_clear()
    fusion._flush_plans.clear()


@functools.lru_cache(maxsize=1024)
def _barrier_program(mesh):
    def body(x):
        return lax.psum(x, HVD_AXIS)

    f = jax.shard_map(body, mesh=mesh, in_specs=P(HVD_AXIS), out_specs=P(HVD_AXIS))
    return jax.jit(f)


# ----------------------------------------------------------------------------
# Input normalization
# ----------------------------------------------------------------------------

def _order_check(what, tensors, mesh):
    """HOROVOD_ORDER_CHECK=1 (debug): verify every process is dispatching
    THIS op with THIS signature — the runtime cross-rank analog of the
    reference coordinator's shape/dtype mismatch errors
    (controller.h:158-163), extended to catch order divergence (which
    otherwise surfaces as a hang or silent corruption). A rank calling a
    different number of collectives times out inside the exchange instead
    of hanging forever."""
    st = basics._get_state()
    if not st.config.order_check or jax.process_count() <= 1:
        return
    from horovod_tpu.common import negotiation
    # Leading axis excluded: it is the LOCAL chip count, which legitimately
    # differs across heterogeneous hosts.
    sig = [what] + [f"{tuple(getattr(t, 'shape', ()))[1:]}:"
                    f"{getattr(t, 'dtype', type(t).__name__)}"
                    for t in tensors]
    sigs = negotiation.exchange("order_check", sig,
                                procs=_mesh_processes(mesh))
    bad = {i: s for i, s in enumerate(sigs) if s != sig}
    if bad:
        raise TensorShapeMismatchError(
            f"collective order/signature mismatch: this process dispatched "
            f"{sig}, but process(es) {sorted(bad)} dispatched "
            f"{list(bad.values())[:3]} at the same point in the program — "
            f"every process must issue the same collectives in the same "
            f"order (docs/api.md eager multi-process contract).")


def _prepare(tensors, mesh, n, what):
    """Convert to device arrays sharded rank-major over the mesh.

    Single process: a single device_put per tensor (host numpy goes straight
    to the sharded layout; device arrays just reshard) — the moral analog of
    the fusion buffer's one-memcpy-in guarantee
    (reference: fusion_buffer_manager.h:40).

    Multi-process: each process passes the **local** rows of the rank-major
    stack (one per chip it owns); the global sharded array is assembled from
    the per-process pieces without touching non-addressable devices.
    """
    _order_check(what, tensors, mesh)
    sharding = NamedSharding(mesh, P(HVD_AXIS))
    multi, local_pos = _local_mesh_info(mesh)
    out = []
    for t in tensors:
        if not hasattr(t, "ndim"):
            t = np.asarray(t)
        if multi:
            n_local = len(local_pos)
            if t.ndim < 1 or t.shape[0] != n_local:
                raise TensorShapeMismatchError(
                    f"{what}: multi-process eager collectives take the "
                    f"local rank-major stack — leading axis {n_local} (one "
                    f"slice per local chip), got shape {tuple(t.shape)}.")
            out.append(jax.make_array_from_process_local_data(
                sharding, np.asarray(t), (n,) + tuple(t.shape[1:])))
        else:
            _check_stacked(t, n, what)
            out.append(jax.device_put(t, sharding))
    return out


def _localize(outs, mesh):
    """Return per-process local results in multi-process mode.

    The compiled program yields global arrays whose shards live on every
    host; a process can only read its own. Mirroring ``_prepare``'s input
    contract, each output is narrowed to the local rank-major stack (rows of
    this process's chips, in rank order).
    """
    multi, _ = _local_mesh_info(mesh)
    if not multi:
        return outs
    res = []
    for o in outs:
        shards = sorted(o.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        res.append(np.concatenate([np.asarray(s.data) for s in shards],
                                  axis=0))
    return res


def _signature(tensors):
    return (tuple(tuple(t.shape) for t in tensors),
            tuple(str(t.dtype) for t in tensors))


# ----------------------------------------------------------------------------
# Dispatch-plan cache: the eager hot path's one-cache-hit steady state.
#
# The compiled-program cache already replaces the reference's negotiation
# (response_cache.h:45), but every eager call still paid Python-side costs
# the program cache does not amortize: signature/string formatting,
# NamedSharding construction, per-call device_put of inputs, timeline/
# metrics setup even when observability is off, and a sort-per-call
# _localize. A _DispatchPlan resolves all of that ONCE per
# (op kind, mesh, process set, op params, tensor signature); steady state
# is: tuple-key dict hit -> compiled-program call -> indexed localization.
#
# Input staging on the plan path is the compiled program's own C++
# dispatch: jit uploads/reshards host or mismatched-sharding inputs and
# caches one executable per input-sharding signature, so no Python-side
# device_put runs per call (measured ~2x cheaper than device_put + call on
# the CPU tier), and an input that is already a correctly-sharded
# jax.Array passes through zero-copy. Multi-process keeps the explicit
# make_array_from_process_local_data assembly (local rows -> global array
# cannot be inferred by jit).
# ----------------------------------------------------------------------------

_PLAN_CAP = 4096
_plans = {}
_plan_stats = {"hits": 0, "misses": 0, "invalidations": 0}


def plan_cache_stats():
    """Copy of the dispatch-plan cache counters (always on — plain ints;
    the metrics registry carries the same series when enabled)."""
    return dict(_plan_stats, size=len(_plans))


def _invalidate_plans():
    if _plans:
        _plan_stats["invalidations"] += 1
        _plans.clear()


def _plan_sig(tensors):
    """Hashable per-tensor (shape, dtype) signature of a call, or None
    when any input is not ndarray-like (python scalars/lists take the
    generic path — they need np.asarray normalization first)."""
    sig = []
    for t in tensors:
        if not isinstance(t, (jax.Array, np.ndarray)):
            return None
        sig.append((t.shape, t.dtype))
    return tuple(sig)


def _plan_lookup(key, ps):
    """Return the hit plan for ``key`` — after re-checking the runtime
    conditions a plan cannot capture (join armed/active, debug order
    check), which re-route to the generic negotiated path. A hit fences
    in-flight fused async work exactly like :func:`_join_sync` does."""
    st = basics._state
    if st is None:
        return None
    plan = _plans.get(key)
    if plan is None:
        _plan_stats["misses"] += 1
        from horovod_tpu.metrics import instruments as hvd_metrics
        hvd_metrics.record_plan_cache("miss")
        return None
    cfg = st.config
    if cfg.order_check or st.joined_ranks or ps.joined_ranks \
            or (cfg.join_mode and jax.process_count() > 1):
        return None
    if st.fusion is not None:
        st.fusion.fence()
    _plan_stats["hits"] += 1
    from horovod_tpu.metrics import instruments as hvd_metrics
    hvd_metrics.record_plan_cache("hit")
    return plan


def _plan_eligible(st, active_mask):
    """A plan may be registered only for dispatches whose control path is
    pure (no join mask, no armed per-op negotiation, no debug order
    check) — everything a plan precomputes is then call-invariant."""
    return (active_mask is None and not st.config.order_check
            and not (st.config.join_mode and jax.process_count() > 1))


def _register_plan(key, plan):
    if len(_plans) >= _PLAN_CAP:
        _plans.pop(next(iter(_plans)))      # drop the oldest entry
    _plans[key] = plan
    return plan


class _DispatchPlan:
    """Everything one eager-collective signature needs per call, resolved
    once: compiled program (plus the opt-in donating variant), input
    NamedSharding, global stacked shapes, metrics label strings, and the
    output localization order (shard order resolved on first use —
    localization becomes indexed ``np.asarray`` without re-sorting)."""

    __slots__ = ("kind", "op_kind", "op_label", "default_name", "program",
                 "donate_program", "mesh", "sharding", "ps", "ps_label",
                 "multi", "global_shapes", "nbytes", "sig", "wire_label",
                 "wire_nbytes", "wire_sched", "wire_tiers",
                 "_localize_order", "_stage_memo")

    _STAGE_MEMO_CAP = 16

    @staticmethod
    def _spec_for(mesh):
        """Input/output PartitionSpec over ``mesh`` — the rank-major 1-D
        stack by default; the hierarchical plan shards the same leading
        axis over its (cross, local) factorization instead."""
        return P(HVD_AXIS)

    def __init__(self, kind, op_kind, program, mesh, ps, staged,
                 default_name, donate_program=None):
        self.kind = kind
        self.op_kind = op_kind
        self.op_label = op_kind.lower()
        self.default_name = default_name
        self.program = program
        self.donate_program = donate_program
        self.mesh = mesh
        self.sharding = NamedSharding(mesh, self._spec_for(mesh))
        self.ps = ps
        self.ps_label = _ps_label(ps)
        self.multi = _local_mesh_info(mesh)[0]
        # Derived from the registration call's staged (global) tensors:
        # every later key-matched call has the same shapes/dtypes, so the
        # metrics byte count is a plan constant, not a per-call walk.
        self.global_shapes = tuple(tuple(t.shape) for t in staged)
        self.nbytes = sum(getattr(t, "nbytes", 0) for t in staged)
        # Flight-recorder signature: a plan constant (every key-matched
        # call shares shapes/dtypes), so the hot path never re-hashes.
        self.sig = _flight.signature(staged)
        # Wire accounting constants (first tensor's dtype stands for the
        # group; allreduce counts both internal RS+AG legs; the leg
        # schedule steers the default tier split — alltoall legs use the
        # foreign-destination fraction like the static model).
        self.wire_label = str(staged[0].dtype) if staged else None
        self.wire_nbytes = self.nbytes * (2 if op_kind == "ALLREDUCE" else 1)
        self.wire_sched = "a2a" if op_kind == "ALLTOALL" else "ring"
        # Plan-constant tier split over THIS SET'S member ranks (the
        # static model classifies by real members, and e.g. a process set
        # confined to one slice must book zero dcn even though the world
        # spans several): None on single-slice layouts — record_wire's
        # default (which matches for the global set) then applies.
        self.wire_tiers = self._flat_tiers()
        self._localize_order = None
        # id(src) -> (weakref(src), staged): re-sharding the SAME
        # immutable jax.Array every step (re-reducing a pinned buffer)
        # is pure waste — stage once, reuse while the source is alive.
        # WEAK source refs: a fresh-gradient-per-step loop gets no memo
        # hits, and strong refs would pin up to CAP dead source+staged
        # buffer pairs per plan; the weakref callback drops the staged
        # copy the moment the caller's array dies, and the liveness
        # check (wr() is t) guards id reuse. Host numpy is NEVER
        # memoized (mutable in place).
        self._stage_memo = {}

    def _flat_tiers(self):
        """{"ici","dcn"} split of this plan's wire bytes by its set's
        member ranks against the live slice layout, or None when
        single-slice (everything defaults to ici)."""
        try:
            st = basics._state
            world = st.topology.size if st is not None else 0
            slices, slice_size = _live_slices(world) if world else (1, 1)
            if slices <= 1 or not self.wire_nbytes:
                return None
            n = self.global_shapes[0][0] if self.global_shapes else 1
            members = self.ps.rank_list() if self.ps.ranks is not None \
                else list(range(n))
            frac = _wire.a2a_dcn_fraction(members, slice_size) \
                if self.wire_sched == "a2a" \
                else _wire.ring_dcn_fraction(members, slice_size)
            return _wire.split_tiers(self.wire_nbytes, frac)
        except Exception:  # noqa: BLE001 — accounting must never break
            return None    # plan construction

    def run(self, tensors, name=None):
        # Profiler bracket opens at API entry so input staging (and the
        # chaos delay site inside dispatch) land in host_dispatch.
        t_api = time.perf_counter() if _profile.armed else None
        if self.multi:
            sharding = self.sharding
            staged = [jax.make_array_from_process_local_data(
                          sharding, np.asarray(t), g)
                      for t, g in zip(tensors, self.global_shapes)]
            return self.dispatch(staged, name, prog=self.program,
                                 t_api=t_api)
        sharding = self.sharding
        staged = []
        passthrough = True
        memo = self._stage_memo
        for t in tensors:
            if isinstance(t, jax.Array):
                if t.sharding == sharding:
                    staged.append(t)        # zero-copy passthrough
                    continue
                passthrough = False
                m = memo.get(id(t))
                if m is not None and m[0]() is t:
                    staged.append(m[1])
                    continue
                s = jax.device_put(t, sharding)
                if len(memo) >= self._STAGE_MEMO_CAP:
                    memo.clear()
                try:
                    wr = weakref.ref(
                        t, lambda _, k=id(t), m=memo: m.pop(k, None))
                except TypeError:
                    pass            # not weakref-able: stage, don't memo
                else:
                    memo[id(t)] = (wr, s)
                staged.append(s)
            else:
                # Host numpy: the program's own C++ dispatch stages it.
                passthrough = False
                staged.append(t)
        # Donation ONLY for all-passthrough calls: the caller's own
        # correctly-sharded arrays (the explicit opt-in contract). A
        # memoized staged copy must never be donated — its buffer would
        # be dead on the next memo hit.
        prog = self.donate_program \
            if self.donate_program is not None and passthrough \
            else self.program
        return self.dispatch(staged, name, prog=prog, t_api=t_api)

    def _program_for(self, staged):
        """The donating program applies only when every input is already a
        correctly-sharded jax.Array: donation is then real in-place buffer
        reuse (and the caller explicitly opted into losing its inputs via
        HOROVOD_DONATE_BUFFERS); anything else keeps the plain program —
        donating a to-be-resharded buffer is a no-op plus an XLA warning."""
        if self.donate_program is None:
            return self.program
        sharding = self.sharding
        for t in staged:
            if not (isinstance(t, jax.Array) and t.sharding == sharding):
                return self.program
        return self.donate_program

    def dispatch(self, staged, name=None, prog=None, t_api=None):
        from horovod_tpu.metrics import instruments as hvd_metrics
        profile_on = _profile.armed
        if profile_on and t_api is None:
            t_api = time.perf_counter()
        if _chaos.armed:
            _chaos.fire("collective.dispatch")
        if prog is None:
            # Slow-path registration call: staged buffers are fresh
            # _prepare outputs, safe to donate under the opt-in.
            prog = self._program_for(staged)
        metrics_on = hvd_metrics.enabled()
        flight_on = _flight.armed
        if flight_on:
            # Plan fast path stays plan-cheap: every flight field (label,
            # byte count, signature) is a plan constant resolved once.
            fl_seq = _flight.record_dispatch(self.op_label, self.ps_label,
                                             self.nbytes, self.sig, name)
            t0f = time.perf_counter()
        tl = basics.timeline()
        if tl is None and not metrics_on:
            # Observability (timeline/metrics) off: no span/annotation
            # bookkeeping — the compiled call, error translation, and the
            # always-armed flight record above.
            if profile_on:
                t0p = time.perf_counter()
            try:
                outs = prog(*staged)
            except (ValueError, RuntimeError) as e:
                _translate_dispatch_error(name or self.default_name,
                                          self.op_label, e)
            if flight_on:
                _flight.record_complete(self.op_label, self.ps_label,
                                        fl_seq, time.perf_counter() - t0f)
            outs = self._localize(outs)
            if profile_on:
                # collective = program + localize (the multi-process
                # peer-wait); host_dispatch = everything before the call.
                _profile.record_dispatch(
                    self.op_label, time.perf_counter() - t0p,
                    t0p - t_api, self.nbytes)
            return outs
        # Inline _timeline_op with the plan's precomputed labels/byte
        # count (no contextmanager frame, no per-call nbytes walk; the
        # XPlane TraceAnnotation rides only with an active timeline).
        if metrics_on:
            hvd_metrics.record_collective(self.op_label, self.nbytes,
                                          self.ps_label)
            hvd_metrics.record_wire("eager", self.wire_label,
                                    self.wire_nbytes,
                                    tiers=self.wire_tiers,
                                    sched=self.wire_sched)
            t0 = time.perf_counter()
        if profile_on:
            t0p = time.perf_counter()
        try:
            if tl is not None:
                with jax.profiler.TraceAnnotation(
                        f"hvd::{self.op_kind}::{name or self.default_name}"):
                    with tl.op_span(name or self.default_name,
                                    self.op_kind):
                        outs = prog(*staged)
            else:
                outs = prog(*staged)
            if metrics_on:
                hvd_metrics.record_collective_latency(
                    self.op_label, time.perf_counter() - t0)
            if flight_on:
                _flight.record_complete(self.op_label, self.ps_label,
                                        fl_seq, time.perf_counter() - t0f)
        except (ValueError, RuntimeError) as e:
            _translate_dispatch_error(name or self.default_name,
                                      self.op_label, e)
        outs = self._localize(outs)
        if profile_on:
            _profile.record_dispatch(
                self.op_label, time.perf_counter() - t0p,
                t0p - t_api, self.nbytes)
        return outs

    def _localize(self, outs):
        """Per-process local rows of each output (multi-process), with the
        shard order resolved once per plan instead of sorted per call."""
        if not self.multi:
            return list(outs)
        order = self._localize_order
        res = []
        for o in outs:
            shards = o.addressable_shards
            if order is None:
                order = tuple(int(i) for i in np.argsort(
                    [s.index[0].start or 0 for s in shards]))
                self._localize_order = order
            if len(order) == 1:
                res.append(np.asarray(shards[0].data))
            else:
                res.append(np.concatenate(
                    [np.asarray(shards[i].data) for i in order], axis=0))
        return res


def _quantized_wire_tiers(flat_len, n, members):
    """Per-tier split of the flat block-scaled exchange — first leg
    AllToAll (foreign-destination fraction), second leg AllGather (ring
    slice-boundary fraction) — mirroring the static cost model's per-leg
    classification byte-for-byte. None on single-slice layouts (the
    default record_wire split books everything to ici there anyway)."""
    st = basics._state
    world = st.topology.size if st is not None else n
    slices, slice_size = _live_slices(world)
    if slices <= 1:
        return None
    leg = _wire.exchange_leg_bytes(flat_len, n)
    t1 = _wire.split_tiers(leg, _wire.a2a_dcn_fraction(members, slice_size))
    t2 = _wire.split_tiers(leg, _wire.ring_dcn_fraction(members,
                                                        slice_size))
    return {"ici": t1["ici"] + t2["ici"], "dcn": t1["dcn"] + t2["dcn"]}


class _WireDispatchPlan(_DispatchPlan):
    """Dispatch plan for eager allreduces riding the quantized wire tier
    (ops/wire.py). Beyond the base plan it owns the bucket's error-feedback
    residual — fetched from the wire store before the call, stored after —
    and records the exchange's exact on-wire byte estimate (split per
    link tier when a slice hierarchy exists). Keyed (like every plan) on
    the wire dtype, so a per-process-set wire flip routes the next call
    through a fresh plan with a fresh residual."""

    __slots__ = ("wire_name", "ef", "ef_key", "flat_len", "wire_records",
                 "res_len")

    def __init__(self, program, mesh, ps, staged, wire_name, ef, ef_key):
        super().__init__("allreduce", "ALLREDUCE", program, mesh, ps,
                         staged, "grouped_allreduce")
        self.wire_name = wire_name
        self.ef = ef
        self.ef_key = ef_key
        self.flat_len = sum(int(np.prod(s[1:])) for s in self.global_shapes)
        self.res_len = self.flat_len
        n = self.global_shapes[0][0] if self.global_shapes else 1
        # Plan-constant wire accounting: (path, dtype, bytes, compressed,
        # tiers) per record — built once by the subclass hook (the
        # hierarchical plan books one record per decomposed leg).
        self._init_wire_records(n, staged)

    def _init_wire_records(self, n, staged):
        self.wire_label = self.wire_name
        self.wire_nbytes = _wire.exchange_wire_bytes(self.flat_len, n)
        members = self.ps.rank_list() if self.ps.ranks is not None \
            else list(range(n))
        self.wire_records = [
            ("eager", self.wire_name, self.wire_nbytes, True,
             _quantized_wire_tiers(self.flat_len, n, members))]

    def _zero_residual(self):
        return _wire.zero_residual(self.mesh, self.sharding,
                                   self.global_shapes[0][0], self.res_len)

    def dispatch(self, staged, name=None, prog=None, t_api=None):
        # Instrumentation inlined like the base fast path (no
        # contextmanager frame, plan-constant labels/bytes): the wire
        # tier's HOST cost over the fp32 plan is just the residual store
        # round-trip — guarded at 2x by test_perf_guards.
        from horovod_tpu.metrics import instruments as hvd_metrics
        profile_on = _profile.armed
        if profile_on and t_api is None:
            t_api = time.perf_counter()
        if _chaos.armed:
            _chaos.fire("collective.dispatch")
        args = list(staged)
        ef = self.ef
        if ef:
            res = _wire.ef_get(self.ef_key)
            if res is None:
                res = self._zero_residual()
            args.append(res)
        metrics_on = hvd_metrics.enabled()
        flight_on = _flight.armed
        if flight_on:
            fl_seq = _flight.record_dispatch(self.op_label, self.ps_label,
                                             self.nbytes, self.sig, name)
            t0f = time.perf_counter()
        if metrics_on:
            hvd_metrics.record_collective(self.op_label, self.nbytes,
                                          self.ps_label)
            for path, dtype, nbytes, compressed, tiers in self.wire_records:
                hvd_metrics.record_wire(path, dtype, nbytes, compressed,
                                        tiers=tiers)
            t0 = time.perf_counter()
        if profile_on:
            t0p = time.perf_counter()
        tl = basics.timeline()
        try:
            if tl is not None:
                with jax.profiler.TraceAnnotation(
                        f"hvd::{self.op_kind}::{name or self.default_name}"):
                    with tl.op_span(name or self.default_name,
                                    self.op_kind):
                        outs = self.program(*args)
            else:
                outs = self.program(*args)
            if ef:
                # The residual stays a DEVICE-RESIDENT global array
                # between steps (never localized): it feeds straight
                # back into the next key-matched dispatch.
                _wire.ef_put(self.ef_key, outs[-1])
                outs = outs[:-1]
            if metrics_on:
                hvd_metrics.record_collective_latency(
                    self.op_label, time.perf_counter() - t0)
            if flight_on:
                _flight.record_complete(self.op_label, self.ps_label,
                                        fl_seq, time.perf_counter() - t0f)
        except (ValueError, RuntimeError) as e:
            # Never resume error feedback over a failed exchange: the
            # residual's pairing with the result stream is broken (and
            # after an elastic recovery it would be a dead-backend array).
            if ef:
                _wire.ef_pop(self.ef_key)
            _translate_dispatch_error(name or self.default_name,
                                      self.op_label, e)
        except Exception:
            if ef:
                _wire.ef_pop(self.ef_key)
            raise
        outs = self._localize(list(outs))
        if profile_on:
            _profile.record_dispatch(
                self.op_label, time.perf_counter() - t0p,
                t0p - t_api, self.nbytes)
        return outs


class _HierDispatchPlan(_WireDispatchPlan):
    """Dispatch plan for eager allreduces riding the HIERARCHICAL dispatch
    tier: local RS (exact, ICI) -> cross-slice allreduce on the per-tier
    wire (DCN) -> local AG, compiled over the (slice x chips-per-slice)
    mesh. Byte accounting books each decomposed leg to its own link tier
    (wire.hierarchical_wire_bytes — the same integers the static model's
    hierarchical what-if predicts); the error-feedback residual covers
    the CROSS leg's shard only. Keyed on the slice layout and cross wire,
    so an autotuner strategy flip (or an elastic resize through
    clear_program_caches) routes through a fresh plan."""

    __slots__ = ("cross_label", "num_slices")

    @staticmethod
    def _spec_for(mesh):
        from horovod_tpu.common.topology import CROSS_AXIS, LOCAL_AXIS
        return P((CROSS_AXIS, LOCAL_AXIS))

    def __init__(self, program, hier_mesh, ps, staged, hier, ef_key):
        # Slots the _init_wire_records hook needs; assigned before the
        # base __init__ that invokes it.
        self.cross_label = hier["cross"]
        self.num_slices = hier["slices"]
        super().__init__(program, hier_mesh, ps, staged,
                         hier["cross"], hier["ef"], ef_key)

    def _init_wire_records(self, n, staged):
        payload_dtype = str(staged[0].dtype) if staged else "float32"
        width = np.dtype(staged[0].dtype).itemsize if staged else 4
        h = _wire.hierarchical_wire_bytes(
            self.flat_len, n, self.num_slices, width,
            cross_wire=self.cross_label or "")
        self.res_len = h["shard_elems"]
        self.wire_label = self.cross_label or payload_dtype
        self.wire_nbytes = h["ici"] + h["dcn"]
        self.wire_records = [
            ("eager", payload_dtype, h["ici"], False, {"ici": h["ici"]}),
            ("eager", self.cross_label or payload_dtype, h["dcn"],
             self.cross_label is not None, {"dcn": h["dcn"]})]


class _HierAlltoallPlan(_WireDispatchPlan):
    """Dispatch plan for eager equal-splits alltoalls riding the
    HIERARCHICAL dispatch tier: slice-local a2a (ICI) -> cross-slice a2a
    on the per-tier wire (DCN), compiled over the (slice x
    chips-per-slice) mesh. Byte accounting books the local leg all-ICI
    and splits the cross leg by its own ``(S-1)/S`` foreign-slice
    fraction (``wire.hierarchical_a2a_bytes`` — the same integers the
    static model's hierarchical a2a what-if predicts, keeping
    ``cross_check_bytes`` at delta 0). NO error feedback: an alltoall
    moves data without reducing, so there is no accumulated sum for a
    residual to correct — each element pays one bounded round-off on the
    quantized cross leg. Keyed on the slice layout and cross wire, so a
    strategy flip (or an elastic resize through clear_program_caches)
    routes through a fresh plan."""

    __slots__ = ("cross_label", "num_slices")

    @staticmethod
    def _spec_for(mesh):
        from horovod_tpu.common.topology import CROSS_AXIS, LOCAL_AXIS
        return P((CROSS_AXIS, LOCAL_AXIS))

    def __init__(self, program, hier_mesh, ps, staged, hier):
        # Slots the _init_wire_records hook needs; assigned before the
        # base init that precedes it. _WireDispatchPlan.__init__ is
        # bypassed on purpose: its wire/ef plumbing is allreduce-shaped
        # (residual store, exchange_wire_bytes); only its multi-record
        # dispatch() is shared.
        self.cross_label = hier["cross"]
        self.num_slices = hier["slices"]
        _DispatchPlan.__init__(self, "alltoall", "ALLTOALL", program,
                               hier_mesh, ps, staged, "alltoall")
        self.wire_name = hier["cross"]
        self.ef = False
        self.ef_key = None
        self.flat_len = sum(int(np.prod(s[1:])) for s in self.global_shapes)
        self.res_len = 0
        n = self.global_shapes[0][0] if self.global_shapes else 1
        self._init_wire_records(n, staged)

    def _init_wire_records(self, n, staged):
        payload_dtype = str(staged[0].dtype) if staged else "float32"
        width = np.dtype(staged[0].dtype).itemsize if staged else 4
        h = _wire.hierarchical_a2a_bytes(
            self.flat_len, n, self.num_slices, width,
            cross_wire=self.cross_label or "")
        self.cross_label = h["cross_label"]
        self.wire_label = self.cross_label or payload_dtype
        self.wire_nbytes = h["local"] + h["cross"]
        self.wire_sched = "a2a"
        self.wire_records = [
            ("eager", payload_dtype, h["local"], False,
             {"ici": h["local"]}),
            ("eager", self.cross_label or payload_dtype, h["cross"],
             self.cross_label is not None, dict(h["cross_tiers"]))]


@functools.lru_cache(maxsize=4096)
def _hier_verdict(strategy, cross, op, sig, n, slices, ef_cfg):
    """Memoized tail of the hierarchical-dispatch verdict: everything
    derivable from the resolved policy values and the call signature
    (the per-dispatch cost of the armed tier must stay plan-key cheap —
    guarded at 2x the flat plan by test_perf_guards)."""
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        return None
    dtypes = {dt for _, dt in sig}
    if len(dtypes) != 1 or not all(
            jnp.issubdtype(dt, jnp.floating) for dt in dtypes):
        return None
    total = sum(int(np.prod(shape[1:])) if len(shape) >= 1 else 0
                for shape, _ in sig)
    width = np.dtype(next(iter(dtypes))).itemsize
    h = _wire.hierarchical_wire_bytes(total, n, slices, width,
                                      cross_wire=cross)
    label = h["cross_label"]
    return {"strategy": strategy, "cross": label, "slices": slices,
            "ef": bool(ef_cfg) and label is not None}


def _eager_hier_for(ps, op, sig):
    """Hierarchical-dispatch verdict for one eager allreduce: a dict
    (strategy facts the program/plan need) or None for the flat path.

    Eligibility — shared, deliberately, with the static cost model's
    mirror (analysis/cost.py): the per-set strategy registry (autotuner /
    hvd.set_dispatch_strategy) else the HOROVOD_HIERARCHICAL_DISPATCH
    default; global process set only (slice membership of a sub-set is
    undefined); float Sum/Average groups of ONE dtype (the decomposition
    concatenates); and a live slice hierarchy (HOROVOD_MESH_SLICES /
    multi-slice topology) — a 1-slice layout would pay two extra ICI legs
    for no DCN saving (hvdlint HVP113)."""
    st = basics._state
    if st is None or sig is None:
        return None
    cfg = st.config
    hier_cfg = getattr(cfg, "hierarchical_dispatch", False)
    if not hier_cfg and not _wire._strategy_registry:
        return None          # hot-path fast exit: tier disarmed everywhere
    default = "hier_qcross" if hier_cfg else ""
    strategy = _wire.dispatch_strategy_for(_ps_label(ps), default)
    if strategy not in ("hier", "hier_qcross"):
        return None
    if ps.ranks is not None:
        return None
    n = ps.size()
    slices, _ = _live_slices(n)
    if slices <= 1:
        return None
    cross = ""
    if strategy == "hier_qcross":
        cross = _wire.cross_wire_for(_ps_label(ps), cfg)
    return _hier_verdict(strategy, cross, ReduceOp(op), sig, n, slices,
                         bool(cfg.wire_error_feedback))


@functools.lru_cache(maxsize=4096)
def _a2a_hier_verdict(strategy, cross, sig, n, slices):
    """Memoized tail of the hierarchical-ALLTOALL verdict (the a2a twin
    of :func:`_hier_verdict`): single-tensor equal-splits calls whose
    per-rank dim divides the world. The cross wire label survives only
    for float payloads the shared eligibility predicate accepts — below
    one BLOCK per destination slice the exchange padding would inflate
    the wire and the cross leg stays exact."""
    if len(sig) != 1:
        return None
    (shape, dtype), = sig
    if len(shape) < 2 or shape[1] % n != 0:
        return None
    per = int(np.prod(shape[1:]))
    label = _wire.quantized_label(cross) if cross else None
    if label is not None and not (
            jnp.issubdtype(np.dtype(dtype), jnp.floating)
            and _wire.quantized_eligible(per, slices, True, True)):
        label = None
    return {"strategy": strategy, "cross": label, "slices": slices}


def _eager_a2a_hier_for(ps, sig):
    """Hierarchical-dispatch verdict for one eager equal-splits alltoall:
    a dict (strategy facts the program/plan need) or None for the flat
    path — the a2a twin of :func:`_eager_hier_for`, sharing its
    eligibility philosophy (global process set only, live slice
    hierarchy, hvdlint HVP113 on 1-slice layouts) but keyed on the a2a
    strategy registry / ``HOROVOD_HIERARCHICAL_ALLTOALL`` default, with
    the expert cross wire resolved through
    :func:`horovod_tpu.ops.wire.alltoall_cross_wire_for` — NEVER the
    allreduce wire knobs: alltoall payloads are activations and quantize
    only by explicit choice (docs/performance.md)."""
    st = basics._state
    if st is None or sig is None:
        return None
    cfg = st.config
    hier_cfg = getattr(cfg, "hierarchical_alltoall", False)
    if not hier_cfg and not _wire._a2a_strategy_registry:
        return None          # hot-path fast exit: tier disarmed everywhere
    default = "hier_qcross" if hier_cfg else ""
    strategy = _wire.alltoall_strategy_for(_ps_label(ps), default)
    if strategy not in ("hier", "hier_qcross"):
        return None
    if ps.ranks is not None:
        return None
    n = ps.size()
    slices, _ = _live_slices(n)
    if slices <= 1:
        return None
    cross = ""
    if strategy == "hier_qcross":
        cross = _wire.alltoall_cross_wire_for(_ps_label(ps), cfg)
    return _a2a_hier_verdict(strategy, cross, sig, n, slices)


def _eager_wire_for(ps, op, sig, wire_req):
    """Effective QUANTIZED wire dtype for one eager allreduce — ``(label,
    error_feedback)`` with label None for the exact full-precision path.
    The decision honors the one-shot compressor request, then the
    per-process-set registry (autotuner / hvd.set_wire_dtype), then the
    config knob; it quantizes only float Sum/Average groups big enough
    that the exchange's n×BLOCK padding doesn't inflate the wire (below
    one block per destination rank the exact psum moves fewer bytes)."""
    st = basics._state
    if st is None or sig is None:
        return None, False
    cfg = st.config
    req = wire_req or _wire.wire_dtype_for(_ps_label(ps), cfg.wire_dtype)
    label = _wire.quantized_label(req)
    if label is None:
        return None, False
    # REAL floats only — _is_float admits complex (correct for Average
    # validation), but the block quantizer's abs/round math silently
    # drops the imaginary part; complex payloads keep the exact wire,
    # matching the static cost model's float-only gate.
    all_float = all(jnp.issubdtype(dt, jnp.floating) for _, dt in sig)
    total = sum(int(np.prod(shape[1:])) if len(shape) >= 1 else 0
                for shape, _ in sig)
    if not _wire.quantized_eligible(
            total, ps.size(), all_float,
            ReduceOp(op) in (ReduceOp.SUM, ReduceOp.AVERAGE)):
        return None, False
    return label, bool(cfg.wire_error_feedback)


# ----------------------------------------------------------------------------
# Public eager API
# ----------------------------------------------------------------------------

def allreduce(tensor, op=Average, prescale_factor=1.0, postscale_factor=1.0,
              process_set=None, name=None):
    """Allreduce a rank-major stacked tensor; returns the stacked per-rank
    results (every slice equals the reduction).

    reference: hvd.allreduce (torch/mpi_ops.py:294-360; op semantics
    message.h:43-50, pre/postscale operations.cc:1480).
    """
    return grouped_allreduce([tensor], op=op, prescale_factor=prescale_factor,
                             postscale_factor=postscale_factor,
                             process_set=process_set, name=name)[0]


@_interceptable("allreduce")
def grouped_allreduce(tensors, op=Average, prescale_factor=1.0,
                      postscale_factor=1.0, process_set=None, name=None):
    """One fused dispatch for a group of tensors — completes atomically like
    the reference's grouped ops (reference: EnqueueTensorAllreduces
    operations.cc:1480, group_table.h:39). When the effective wire dtype
    for this process set is quantized (int8/fp8 — config knob, per-set
    registry, or a one-shot Compression.int8 request), eligible float
    Sum/Average groups ride the block-scaled exchange with error feedback
    instead of the exact psum (ops/wire.py). When the hierarchical
    dispatch tier is armed over a live slice hierarchy
    (HOROVOD_HIERARCHICAL_DISPATCH / hvd.set_dispatch_strategy), eligible
    groups instead decompose into local RS (ICI) -> cross-slice allreduce
    on the per-tier wire (DCN) -> local AG."""
    mesh, ps = _mesh_for(process_set)
    sig = _plan_sig(tensors)
    wire_req = _wire.consume_wire_request()
    # A one-shot Compression.int8 request is an explicit per-dispatch
    # opt-in to the FLAT quantized exchange — it must never be silently
    # dropped by the hierarchical verdict (exact-cross hier would move
    # full precision on every leg while the caller believes otherwise).
    hier = None if _wire.quantized_label(wire_req) is not None \
        else _eager_hier_for(ps, op, sig)
    if hier is not None:
        wire_name, wire_ef = None, False
    else:
        wire_name, wire_ef = _eager_wire_for(ps, op, sig, wire_req)
    if sig is not None:
        key = ("allreduce", mesh, ps, int(op), float(prescale_factor),
               float(postscale_factor), sig, wire_name, wire_ef,
               None if hier is None
               else (hier["slices"], hier["cross"], hier["ef"]))
        plan = _plan_lookup(key, ps)
        if plan is not None:
            return plan.run(tensors, name)
    n = ps.size()
    if op == Average and any(
            not _is_float(_dtype_of(t)) for t in tensors):
        raise ValueError("Average is not supported for integer tensors; use "
                         "hvd.Sum (matches reference torch/mpi_ops.py checks).")
    active_mask = _join_sync(ps, mesh, {
        "kind": "allreduce", "op": int(ReduceOp(op)),
        "pre": float(prescale_factor), "post": float(postscale_factor),
        "slices": _slice_desc(tensors, mesh, n, "allreduce")})
    tensors = _prepare(tensors, mesh, n, "allreduce")
    shapes, dtypes = _signature(tensors)
    st = basics._get_state()
    if hier is not None and active_mask is None \
            and _plan_eligible(st, active_mask):
        hmesh = _hier_mesh(mesh, hier["slices"])
        prog = _hier_allreduce_program(
            hmesh, n, ReduceOp(op), float(prescale_factor),
            float(postscale_factor), shapes, dtypes, hier["cross"] or "",
            hier["ef"])
        plan = _register_plan(key, _HierDispatchPlan(
            prog, hmesh, ps, tensors, hier, key))
        return plan.dispatch(tensors, name)
    # A hierarchical verdict on a non-plannable control path (join mask,
    # armed join mode, debug order check) falls back to the exact flat
    # program: the 2-level decomposition composes with neither the
    # active-mask math nor a stable residual identity.
    if wire_name is not None and active_mask is None:
        if _plan_eligible(st, active_mask):
            prog = _quantized_allreduce_program(
                mesh, n, ReduceOp(op), float(prescale_factor),
                float(postscale_factor), shapes, dtypes, wire_name, wire_ef)
            plan = _register_plan(key, _WireDispatchPlan(
                prog, mesh, ps, tensors, wire_name, wire_ef, key))
            return plan.dispatch(tensors, name)
        # Non-plannable control path (debug order check, armed join mode):
        # quantize without error feedback — there is no stable per-bucket
        # residual identity to key the store on.
        prog = _quantized_allreduce_program(
            mesh, n, ReduceOp(op), float(prescale_factor),
            float(postscale_factor), shapes, dtypes, wire_name, False)
        flat_len = sum(int(np.prod(s[1:])) for s in shapes)
        with _timeline_op(name or "grouped_allreduce", "ALLREDUCE", tensors,
                          process_set=ps,
                          wire=("eager", wire_name,
                                _wire.exchange_wire_bytes(flat_len, n),
                                True)):
            return _localize(list(prog(*tensors)), mesh)
    prog = _allreduce_program(mesh, n, ReduceOp(op), float(prescale_factor),
                              float(postscale_factor), shapes, dtypes,
                              active_mask)
    if sig is not None and _plan_eligible(st, active_mask):
        donate_prog = _allreduce_program(
            mesh, n, ReduceOp(op), float(prescale_factor),
            float(postscale_factor), shapes, dtypes, active_mask,
            donate=True) if st.config.donate_eager else None
        plan = _register_plan(key, _DispatchPlan(
            "allreduce", "ALLREDUCE", prog, mesh, ps, tensors,
            "grouped_allreduce", donate_program=donate_prog))
        return plan.dispatch(tensors, name)
    with _timeline_op(name or "grouped_allreduce", "ALLREDUCE", tensors,
                      process_set=ps):
        return _localize(list(prog(*tensors)), mesh)


def allgather(tensor, process_set=None, name=None):
    """Gather rank slices; output slice ``[r]`` is the concatenation of every
    rank's data (identical across ranks), shape ``(n, n*m, ...)``.

    reference: hvd.allgather (torch/mpi_ops.py:655-712). Ragged first dims are
    supported via :func:`allgather_ragged`.
    """
    return grouped_allgather([tensor], process_set=process_set, name=name)[0]


@_interceptable("allgather")
def grouped_allgather(tensors, process_set=None, name=None):
    mesh, ps = _mesh_for(process_set)
    sig = _plan_sig(tensors)
    if sig is not None:
        key = ("allgather", mesh, ps, sig)
        plan = _plan_lookup(key, ps)
        if plan is not None:
            return plan.run(tensors, name)
    n = ps.size()
    slices = _slice_desc(tensors, mesh, n, "allgather")
    # Validate BEFORE the join round: an active raising after publishing
    # its descriptor would leave the joined processes' mirrors launching a
    # collective nobody else joins (a hang, not an error).
    for s, _ in slices:
        if len(s) < 1:
            raise TensorShapeMismatchError(
                "allgather requires per-rank tensors of rank>=1 "
                "(stacked input rank>=2)")
    active_mask = _join_sync(ps, mesh, {"kind": "allgather",
                                        "slices": slices})
    tensors = _prepare(tensors, mesh, n, "allgather")
    shapes, dtypes = _signature(tensors)
    # HOROVOD_HIERARCHICAL_ALLGATHER: 2-level gather over the (cross,
    # local) mesh — global set only, and the masked (join) variant stays
    # flat (the static row-drop composes with the 1-D gather).
    topo = basics.topology()
    hier = (basics.config().hierarchical_allgather
            and ps.ranks is None and active_mask is None
            and getattr(topo, "mesh2d", None) is not None)
    prog = _allgather_program(topo.mesh2d if hier else mesh, n, shapes,
                              dtypes, active_mask, hier)
    st = basics._get_state()
    if sig is not None and _plan_eligible(st, active_mask):
        plan = _register_plan(key, _DispatchPlan(
            "allgather", "ALLGATHER", prog, mesh, ps, tensors,
            "grouped_allgather"))
        return plan.dispatch(tensors, name)
    with _timeline_op(name or "grouped_allgather", "ALLGATHER", tensors,
                      process_set=ps):
        return _localize(list(prog(*tensors)), mesh)


@_interceptable("allgather_ragged")
def allgather_ragged(tensors, process_set=None, name=None,
                     return_sizes=False, _mirror=False):
    """Allgather of per-rank tensors with differing first dims.

    ``tensors`` is a list of arrays whose shapes agree on all but the first
    axis — one per rank (single process) or one per **local** rank
    (multi-process). Returns the concatenated array (same value for every
    rank); with ``return_sizes=True`` also the per-block first-dim sizes (in
    active-rank order), so callers can split the concatenation without
    re-negotiating. This is the dynamic-shape path that needs host-side size
    negotiation in the reference (reference: controller.cc:74 allgather
    first-dim exchange, collective_operations.h:137-174): multi-process
    launches exchange the per-rank first dims through the jax.distributed
    control plane (:mod:`horovod_tpu.common.negotiation`) before building the
    padded program, so each distinct size vector compiles once everywhere.
    """
    mesh, ps = _mesh_for(process_set)
    n = ps.size()
    multi, local_pos = _local_mesh_info(mesh)
    n_rows = len(local_pos) if multi else n
    if len(tensors) != n_rows:
        raise TensorShapeMismatchError(
            f"allgather_ragged needs one tensor per "
            f"{'local ' if multi else ''}rank ({n_rows}), got {len(tensors)}")
    tensors = [jnp.asarray(t) for t in tensors]
    # Armed-mode round BEFORE the size negotiation so active and joined
    # processes interleave the control plane identically. A joined
    # process's mirror re-enters this function with zero-row tensors
    # AFTER its loop already consumed the round (_mirror=True): it starts
    # at the size exchange, in lockstep with the actives.
    if not _mirror:
        _join_sync(ps, mesh, {
            "kind": "allgather_ragged",
            "tail": [int(s) for s in tensors[0].shape[1:]],
            "dtype": str(tensors[0].dtype)})
    local_sizes = [int(t.shape[0]) for t in tensors]
    if multi:
        from horovod_tpu.common import negotiation
        sizes = negotiation.exchange_sizes("allgather_ragged", local_sizes,
                                           procs=_mesh_processes(mesh))
    else:
        sizes = local_sizes
    max_size = max(sizes)
    padded = jnp.stack([
        jnp.pad(t, [(0, max_size - s)] + [(0, 0)] * (t.ndim - 1))
        for t, s in zip(tensors, local_sizes)])
    gathered = allgather(padded, process_set=process_set, name=name)
    # Joined ranks' slices were dropped by the masked allgather, so the
    # output rows hold n_active blocks, in active-rank order.
    mask = _active_mask(ps)
    active = range(n) if mask is None else np.nonzero(np.array(mask))[0]
    row0 = np.asarray(gathered[0]).reshape(
        (len(list(active)), max_size) + tuple(tensors[0].shape[1:]))
    out = jnp.concatenate(
        [row0[i, :sizes[r]] for i, r in enumerate(active)], axis=0)
    if return_sizes:
        return out, [sizes[r] for r in active]
    return out


def broadcast(tensor, root_rank, process_set=None, name=None):
    """Broadcast the root rank's slice to all ranks
    (reference: hvd.broadcast torch/mpi_ops.py:843-900)."""
    return grouped_broadcast([tensor], root_rank, process_set=process_set,
                             name=name)[0]


@_interceptable("broadcast")
def grouped_broadcast(tensors, root_rank, process_set=None, name=None):
    mesh, ps = _mesh_for(process_set)
    sig = _plan_sig(tensors)
    if sig is not None:
        key = ("broadcast", mesh, ps, int(root_rank), sig)
        plan = _plan_lookup(key, ps)
        if plan is not None:
            return plan.run(tensors, name)
    n = ps.size()
    if ps.ranks is not None:
        try:
            root = ps.rank_list().index(root_rank)
        except ValueError:
            raise ValueError(
                f"broadcast root_rank {root_rank} is not a member of "
                f"{ps} (ranks {ps.rank_list()})") from None
    else:
        root = root_rank
    if not (0 <= root < n):
        raise ValueError(f"root_rank {root_rank} out of range [0,{n})")
    mask = _join_sync(ps, mesh, {"kind": "broadcast", "root": int(root),
                                 "slices": _slice_desc(tensors, mesh, n,
                                                       "broadcast")})
    if mask is not None and not mask[root]:
        # Reference errors when the broadcast root has already joined
        # (controller.cc join/root checks) — there is no data to send.
        from horovod_tpu.common.exceptions import HorovodInternalError
        raise HorovodInternalError(
            f"broadcast root_rank {root_rank} has joined")
    tensors = _prepare(tensors, mesh, n, "broadcast")
    shapes, dtypes = _signature(tensors)
    prog = _broadcast_program(mesh, n, int(root), shapes, dtypes)
    st = basics._get_state()
    if sig is not None and _plan_eligible(st, mask):
        plan = _register_plan(key, _DispatchPlan(
            "broadcast", "BROADCAST", prog, mesh, ps, tensors,
            "grouped_broadcast"))
        return plan.dispatch(tensors, name)
    with _timeline_op(name or "grouped_broadcast", "BROADCAST", tensors,
                      process_set=ps):
        return _localize(list(prog(*tensors)), mesh)


def reducescatter(tensor, op=Sum, prescale_factor=1.0, postscale_factor=1.0,
                  process_set=None, name=None):
    """Reduce across ranks and scatter the result: input slices ``(m, ...)``
    (m divisible by n), output slices ``(m/n, ...)``.

    reference: hvd.reducescatter (torch/mpi_ops.py:1066-1123,
    EnqueueTensorReducescatters operations.cc:1797).
    """
    return grouped_reducescatter([tensor], op=op, prescale_factor=prescale_factor,
                                 postscale_factor=postscale_factor,
                                 process_set=process_set, name=name)[0]


@_interceptable("reducescatter")
def grouped_reducescatter(tensors, op=Sum, prescale_factor=1.0,
                          postscale_factor=1.0, process_set=None, name=None):
    mesh, ps = _mesh_for(process_set)
    sig = _plan_sig(tensors)
    if sig is not None:
        key = ("reducescatter", mesh, ps, int(op), float(prescale_factor),
               float(postscale_factor), sig)
        plan = _plan_lookup(key, ps)
        if plan is not None:
            return plan.run(tensors, name)
    n = ps.size()
    slices = _slice_desc(tensors, mesh, n, "reducescatter")
    # Validate BEFORE the join round (see grouped_allgather).
    for s, _ in slices:
        if len(s) < 1 or s[0] % n != 0:
            raise TensorShapeMismatchError(
                f"reducescatter: per-rank first dim must be divisible by "
                f"{n}, got {tuple(s)}")
    active_mask = _join_sync(ps, mesh, {
        "kind": "reducescatter", "op": int(ReduceOp(op)),
        "pre": float(prescale_factor), "post": float(postscale_factor),
        "slices": slices})
    tensors = _prepare(tensors, mesh, n, "reducescatter")
    shapes, dtypes = _signature(tensors)
    prog = _reducescatter_program(mesh, n, ReduceOp(op), float(prescale_factor),
                                  float(postscale_factor), shapes, dtypes,
                                  active_mask)
    st = basics._get_state()
    if sig is not None and _plan_eligible(st, active_mask):
        plan = _register_plan(key, _DispatchPlan(
            "reducescatter", "REDUCESCATTER", prog, mesh, ps, tensors,
            "grouped_reducescatter"))
        return plan.dispatch(tensors, name)
    with _timeline_op(name or "grouped_reducescatter", "REDUCESCATTER",
                      tensors, process_set=ps):
        return _localize(list(prog(*tensors)), mesh)


@_interceptable("alltoall")
def alltoall(tensor, splits=None, process_set=None, name=None):
    """All-to-all exchange. Equal splits ride a single XLA AllToAll; uneven
    ``splits`` (per-rank row counts to send to each peer) use the padded path.

    Returns ``(output, received_splits)`` when ``splits`` is given, else output
    — matching the reference (reference: hvd.alltoall torch/mpi_ops.py:928-1014,
    splits negotiation collective_operations.h:199-268).

    Multi-process: ``tensor`` is the local rank-major stack and ``splits`` has
    one row per **local** rank; the full splits matrix is negotiated through
    the jax.distributed control plane, playing the role of the reference's
    cross-rank splits exchange.
    """
    mesh, ps = _mesh_for(process_set)
    n = ps.size()
    sig = _plan_sig((tensor,)) if splits is None else None
    hier = _eager_a2a_hier_for(ps, sig) if sig is not None else None
    if sig is not None:
        # The hierarchy facts join the key: a strategy/cross-wire flip (or
        # a slice-layout change through clear_program_caches) routes the
        # next call through a differently-keyed plan — no desync window.
        key = ("alltoall", mesh, ps, sig,
               None if hier is None else (hier["slices"], hier["cross"]))
        plan = _plan_lookup(key, ps)
        if plan is not None:
            return plan.run([tensor], name)[0]
    if _join_sync(ps, mesh, {"kind": "alltoall"}) is not None:
        from horovod_tpu.common.exceptions import HorovodInternalError
        raise HorovodInternalError(
            "alltoall is not supported while ranks have joined (matches the "
            "reference: JOIN covers allreduce/allgather/broadcast only)")
    t = jnp.asarray(tensor)
    multi, local_pos = _local_mesh_info(mesh)
    n_rows = len(local_pos) if multi else n
    _check_stacked(t, n_rows, "alltoall")
    if splits is None:
        if t.ndim < 2 or t.shape[1] % n != 0:
            raise TensorShapeMismatchError(
                f"alltoall without splits: per-rank first dim must be "
                f"divisible by {n}")
        (tt,) = _prepare([t], mesh, n, "alltoall")
        shapes, dtypes = _signature([tt])
        st = basics._get_state()
        if hier is not None and _plan_eligible(st, None):
            hmesh = _hier_mesh(mesh, hier["slices"])
            prog = _hier_alltoall_program(hmesh, n, shapes, dtypes,
                                          hier["cross"] or "")
            plan = _register_plan(key, _HierAlltoallPlan(
                prog, hmesh, ps, (tt,), hier))
            return plan.dispatch([tt], name)[0]
        # Non-plannable control paths (debug order check, join armed)
        # fall back to the exact flat program, like the allreduce tier.
        prog = _alltoall_program(mesh, n, shapes, dtypes)
        if sig is not None and _plan_eligible(st, None):
            plan = _register_plan(key, _DispatchPlan(
                "alltoall", "ALLTOALL", prog, mesh, ps, (tt,),
                "alltoall"))
            return plan.dispatch([tt], name)[0]
        with _timeline_op(name or "alltoall", "ALLTOALL", (tt,),
                          process_set=ps):
            return _localize([prog(tt)[0]], mesh)[0]

    splits = np.asarray(splits)
    if splits.shape != (n_rows, n):
        raise TensorShapeMismatchError(
            f"splits must be ({n_rows},{n}) [{'local ' if multi else ''}rank,"
            f" peer] row counts, got {splits.shape}")
    if (splits < 0).any():
        raise TensorShapeMismatchError("splits must be non-negative")
    row_sums = splits.sum(axis=1)
    if (row_sums > t.shape[1]).any():
        # The reference rejects splits that don't match the tensor size
        # (collective_operations.h:199-268 splits validation). In the stacked
        # layout rows beyond splits[r].sum() are permitted as padding, but a
        # sum *exceeding* the available rows is always an error.
        bad = int(np.argmax(row_sums > t.shape[1]))
        raise TensorShapeMismatchError(
            f"alltoall splits for rank {bad} sum to {int(row_sums[bad])} "
            f"but each rank only has {t.shape[1]} rows")
    if multi:
        # Host-side splits negotiation (reference:
        # collective_operations.h:199-268): every process learns the full
        # [rank, peer] matrix so it can size and slice its receive side.
        from horovod_tpu.common import negotiation
        per_proc = negotiation.exchange("alltoall_splits", splits.tolist(),
                                        procs=_mesh_processes(mesh))
        full = np.concatenate([np.asarray(s, np.int64) for s in per_proc])
        if full.shape != (n, n):
            raise TensorShapeMismatchError(
                f"negotiated alltoall splits have shape {full.shape}, "
                f"expected ({n},{n}) — mismatched splits across processes")
    else:
        full = splits.astype(np.int64)
    rows_global = list(local_pos) if multi else list(range(n))

    # Pad every (rank, peer) block to the max block size with ONE gather per
    # rank row (an index map built host-side), run the dense AllToAll, then
    # slice the ragged rows back out with one gather each. O(n) device ops
    # total — not the O(n^2) per-block slicing a naive port would do — and
    # the index maps are data, so distinct splits matrices reuse the same
    # compiled programs as long as the padded shape matches.
    block = max(int(full.max()), 1)
    m = int(t.shape[1])
    pack_idx = _alltoall_pack_index(full.tobytes(), n, m,
                                    tuple(rows_global))
    pad_width = [(0, 0), (0, 1)] + [(0, 0)] * (t.ndim - 2)
    t_pad = jnp.pad(t, pad_width)
    dense = jax.vmap(lambda row, idx: row[idx])(t_pad, pack_idx)
    (dense,) = _prepare([dense], mesh, n, "alltoall")
    shapes, dtypes = _signature([dense])
    prog = _alltoall_program(mesh, n, shapes, dtypes)
    with _timeline_op(name or "alltoall", "ALLTOALL", (dense,),
                      process_set=ps):
        exchanged = _localize([prog(dense)[0]], mesh)[0]
    received = full.T  # received[r][p] = rows rank r got from peer p
    rows = []
    for i, g in enumerate(rows_global):
        keep = np.concatenate(
            [p * block + np.arange(int(received[g, p])) for p in range(n)]
        ).astype(np.int64)
        rows.append(exchanged[i][keep])
    return rows, received[np.asarray(rows_global)]


@functools.lru_cache(maxsize=64)
def _alltoall_pack_index(full_bytes, n, m, rows_global):
    """Device-resident pack-index map for the uneven alltoall, cached by
    (splits matrix, tensor rows, local rows): a repeated splits pattern —
    the steady state of MoE dispatch — reuses both the host index build
    (O(n²·block)) and its device upload instead of rebuilding per step
    (the reference negotiates splits once per response, not per call:
    collective_operations.h:199-268)."""
    full = np.frombuffer(full_bytes, np.int64).reshape(n, n)
    block = max(int(full.max()), 1)
    offs = np.concatenate([np.zeros((n, 1), np.int64),
                           np.cumsum(full, axis=1)], axis=1)
    j = np.arange(block, dtype=np.int64)
    # pack_idx[i, p*block + k] = offs[g,p] + k for k < full[g,p], else m
    # (m indexes the zero sentinel row appended by the caller).
    pack = offs[:, :-1, None] + j[None, None, :]          # (n, n, block)
    pack = np.where(j[None, None, :] < full[:, :, None], pack, m)
    return jnp.asarray(pack.reshape(n, n * block)[list(rows_global)])


@_interceptable("barrier")
def barrier(process_set=None, name=None):
    """Block until all ranks reach the barrier
    (reference: hvd.barrier operations.cc EnqueueBarrier, message.h BARRIER)."""
    mesh, ps = _mesh_for(process_set)
    multi, local_pos = _local_mesh_info(mesh)
    rows = len(local_pos) if multi else ps.size()
    _join_sync(ps, mesh, {"kind": "barrier"})
    token = np.zeros((rows, 1), np.int32)
    (token,) = _prepare([token], mesh, ps.size(), "barrier")
    with _timeline_op(name or "barrier", "BARRIER", process_set=ps):
        jax.block_until_ready(_barrier_program(mesh)(token))


def _active_mask(ps):
    """0/1 tuple over the set's ranks excluding joined ranks, or None when
    nobody has joined (the fast path). Joined state is the union of the
    global protocol's (st.joined_ranks) and this set's own armed-mode
    accounting (ps.joined_ranks, reference: per-ProcessSet joined_size)."""
    st = basics._get_state()
    set_joined = getattr(ps, "joined_ranks", set())
    if not st.joined_ranks and not set_joined:
        return None
    joined_union = set(st.joined_ranks) | set_joined
    ranks = ps.rank_list()
    if all(r in joined_union for r in ranks):
        # Every participant of this set joined — there is nobody left to
        # contribute, so the collective is a contract violation (the global
        # set can't reach here: join() resets on world completion).
        from horovod_tpu.common.exceptions import HorovodInternalError
        raise HorovodInternalError(
            f"collective on process set {ranks} after all its ranks joined")
    return tuple(0 if r in joined_union else 1 for r in ranks)


# ----------------------------------------------------------------------------
# Multi-process JOIN (reference: controller.cc:269-327 joined-size
# accounting, torch/mpi_ops_v2.cc:972 DoJoin).
#
# The reference's background controller negotiates EVERY collective, which
# is what lets a joined rank keep answering negotiations and contributing
# zeros until everyone has joined. The TPU hot path deliberately has no
# per-op negotiation (compiled programs replace it), so JOIN across
# processes is an armed MODE (HOROVOD_JOIN_MODE=1): while armed, every
# global-set eager collective opens with one tiny KV "join round" in which
# each process publishes either the descriptor of the op it is dispatching
# or the set of ranks it has joined. A joined process sits inside join()
# mirroring each negotiated descriptor with zero-filled inputs — its chips
# must still launch the XLA program for the device collective to complete —
# while the active ranks' programs carry the negotiated active-mask, giving
# exact reference semantics (Sum-as-zero, Average over n_active, static
# drop for Min/Max/Prod/Adasum, root-joined error). When a round shows
# every rank joined, state resets and join() returns the last rank to join.
# ----------------------------------------------------------------------------

def _join_armed():
    """Whether the multi-process join protocol is on (armed) — every
    global-set eager collective then pays one KV round, joined or not."""
    st = basics._get_state()
    return st.config.join_mode and jax.process_count() > 1


def _exchange_join_round(tag, procs, payload):
    """One raw protocol round on ``tag``: each participant publishes
    ``{"joined": [...], "desc": ...}`` and reads everyone else's.
    Returns ``(joined_union, descs)``."""
    from horovod_tpu.common import negotiation
    payloads = negotiation.exchange(tag, payload, procs=procs)
    joined = set()
    descs = []
    for p in payloads:
        joined.update(int(r) for r in p["joined"])
        if p.get("desc") is not None:
            descs.append(p["desc"])
    return joined, descs


def _join_round(payload):
    """Global-set protocol round; updates st.joined_ranks to the union."""
    joined, descs = _exchange_join_round("join_round", None, payload)
    st = basics._get_state()
    st.joined_ranks.clear()
    st.joined_ranks.update(joined)
    return joined, descs


def _join_round_set(ps, mesh, payload):
    """SET-SCOPED protocol round: only the processes owning devices of
    ``ps``'s mesh participate (reference: joined_size is per ProcessSet,
    controller.cc:269-327 — the complement of the set never pays the
    round). The tag carries the set's rank list so two sets with the same
    owner processes keep distinct descriptor streams. Updates
    ``ps.joined_ranks`` to the union."""
    tag = "join_round_set/" + ",".join(str(r) for r in ps.rank_list())
    joined, descs = _exchange_join_round(tag, _mesh_processes(mesh), payload)
    ps.joined_ranks = set(joined)
    return joined, descs


def _round_mask(joined, descs, desc, ranks, what):
    """Shared active-dispatch epilogue of a join round: verify every
    active peer dispatched the same descriptor, then build the 0/1 active
    mask over ``ranks`` (set positions) — None when nobody has joined."""
    bad = [d for d in descs if d != desc]
    if bad:
        raise TensorShapeMismatchError(
            f"join-mode collective mismatch on {what}: this process "
            f"dispatched {desc}, peer(s) dispatched {bad[:2]} at the same "
            f"round — every process must issue the same collectives in "
            f"the same order")
    if not joined:
        return None
    if len(joined) >= len(ranks):
        from horovod_tpu.common.exceptions import HorovodInternalError
        raise HorovodInternalError(
            f"collective on {what} after all its ranks joined")
    return tuple(0 if r in joined else 1 for r in ranks)


def _set_local_ranks(ps, mesh):
    """Global ranks of this process's devices WITHIN the set's mesh
    (submesh device order == rank_list order, topology.build_submesh)."""
    _, local_pos = _local_mesh_info(mesh)
    ranks = ps.rank_list()
    return [ranks[i] for i in local_pos]


def _join_sync(ps, mesh, desc):
    """Pre-dispatch hook for every eager collective: fence in-flight
    fused ASYNC work (so sync and async device collectives submit in the
    same order on every process — see FusionRuntime.fence), then the
    armed-mode join round (or the plain local mask when not armed). Must
    run BEFORE any other cross-process interaction of the op
    (``_prepare``'s order check, size negotiations) so active and
    mirroring processes interleave their control-plane exchanges in the
    same order."""
    st = basics._get_state()
    if st.fusion is not None:
        st.fusion.fence()
    if not _join_armed():
        return _active_mask(ps)
    if ps.ranks is not None:
        multi, _ = _local_mesh_info(mesh)
        if not multi:
            return _active_mask(ps)
        # Set-scoped armed round among the set's owner processes only
        # (the complement keeps training untouched).
        mine = sorted(set(ps.joined_ranks) & set(_set_local_ranks(ps, mesh)))
        joined, descs = _join_round_set(ps, mesh,
                                        {"joined": mine, "desc": desc})
        return _round_mask(joined, descs, desc, ps.rank_list(),
                           f"process set {ps.rank_list()}")
    _, local_pos = _local_mesh_info(mesh)
    mine = sorted(st.joined_ranks.intersection(local_pos))
    joined, descs = _join_round({"joined": mine, "desc": desc})
    return _round_mask(joined, descs, desc, list(range(ps.size())),
                       "the global set")


def _slice_desc(tensors, mesh=None, n=None, what=None):
    """JSON-able per-tensor (slice-shape, dtype) signature, leading
    (local-rank) axis excluded. With ``mesh``/``n``/``what`` the stacked
    leading axis is validated HERE — i.e. before the join round — so a
    malformed input raises before any descriptor is published (an active
    raising after publishing would leave joined mirrors launching a
    collective nobody joins)."""
    rows = _expected_rows(mesh, n) if mesh is not None else None
    out = []
    for t in tensors:
        if not hasattr(t, "ndim"):
            t = np.asarray(t)
        if rows is not None:
            _check_stacked(t, rows, what)
        out.append([[int(s) for s in t.shape[1:]], str(_dtype_of(t))])
    return out


def _mirror_dispatch(desc, joined, process_set=None):
    """Run on a JOINED process: launch the XLA program the active ranks
    negotiated, feeding zero-filled local rows (the mask makes the math
    exact; the launch itself is what the device collective needs).
    ``process_set`` scopes the mirror to a sub-set's mesh (set-scoped
    armed join); default is the global set."""
    mesh, ps = _mesh_for(process_set)
    n = ps.size()
    _, local_pos = _local_mesh_info(mesh)
    rows = len(local_pos)
    # Mask positions follow the SET's rank order (global set: identity).
    mask = tuple(0 if r in joined else 1 for r in ps.rank_list())
    kind = desc["kind"]
    if kind == "alltoall":
        from horovod_tpu.common.exceptions import HorovodInternalError
        raise HorovodInternalError(
            "alltoall is not supported while ranks have joined (matches "
            "the reference: JOIN covers allreduce/allgather/broadcast "
            "only)")
    if kind == "allgather_ragged":
        # Mirror the active sequence exactly: the size negotiation (zero
        # rows from joined ranks), then the inner public allgather — whose
        # own join round lines up with the actives' inner round.
        tail = tuple(desc["tail"])
        zeros = [jnp.zeros((0,) + tail, desc["dtype"])
                 for _ in range(rows)]
        allgather_ragged(zeros, process_set=process_set, _mirror=True)
        return
    if kind == "barrier":
        token = np.zeros((rows, 1), np.int32)
        (token,) = _prepare([token], mesh, n, "barrier")
        with _timeline_op("join_mirror_barrier", "JOIN"):
            jax.block_until_ready(_barrier_program(mesh)(token))
        return
    zeros = [np.zeros([rows] + list(s), np.dtype(d))
             for s, d in desc["slices"]]
    tensors = _prepare(zeros, mesh, n, kind)
    shapes, dtypes = _signature(tensors)
    if kind == "allreduce":
        prog = _allreduce_program(mesh, n, ReduceOp(desc["op"]),
                                  float(desc["pre"]), float(desc["post"]),
                                  shapes, dtypes, mask)
    elif kind == "reducescatter":
        prog = _reducescatter_program(mesh, n, ReduceOp(desc["op"]),
                                      float(desc["pre"]),
                                      float(desc["post"]), shapes, dtypes,
                                      mask)
    elif kind == "allgather":
        prog = _allgather_program(mesh, n, shapes, dtypes, mask)
    elif kind == "broadcast":
        if not mask[int(desc["root"])]:
            # The actives raise this after the same round and never launch
            # a program — raise symmetrically instead of hanging in a
            # mirror launch nobody joins.
            from horovod_tpu.common.exceptions import HorovodInternalError
            raise HorovodInternalError(
                f"broadcast root_rank {desc['root']} has joined")
        prog = _broadcast_program(mesh, n, int(desc["root"]), shapes,
                                  dtypes)
    else:
        from horovod_tpu.common.exceptions import HorovodInternalError
        raise HorovodInternalError(f"join mirror: unknown op kind {kind!r}")
    with _timeline_op(f"join_mirror_{kind}", "JOIN"):
        jax.block_until_ready(prog(*tensors))


def _join_multiprocess(st, rank):
    """join() under HOROVOD_JOIN_MODE: publish this process's ranks as
    joined and service the protocol loop — mirroring every collective the
    still-active ranks dispatch — until the world has joined. Returns the
    highest rank of the final round's newly-joined set (all processes
    compute the same value from the same round sequence)."""
    mesh = global_process_set.mesh
    _, local_pos = _local_mesh_info(mesh)
    my_ranks = sorted(local_pos)
    if rank is not None:
        raise ValueError(
            "multi-process join() takes no rank argument: each process "
            "joins all the ranks (chips) it owns — call join() from the "
            "process whose data ran out")
    n = basics.size()
    # Every process participates in every round (actives via _join_sync),
    # so st.joined_ranks here is the union as of the LAST completed round —
    # the same value every looping process holds as its previous-round
    # union. Snapshot it BEFORE adding my ranks so the final round's
    # newly-joined set (which determines the returned last rank) is
    # computed identically everywhere, including by the last joiner.
    prev = set(st.joined_ranks)
    st.joined_ranks.update(my_ranks)
    while True:
        joined, descs = _join_round({"joined": my_ranks, "desc": None})
        if descs:
            if any(d != descs[0] for d in descs[1:]):
                raise TensorShapeMismatchError(
                    f"join-mode collective mismatch among active ranks: "
                    f"{descs[:3]}")
            # The round rewrote st.joined_ranks to the union; the mirror's
            # own nested rounds (ragged) need my ranks marked joined.
            st.joined_ranks.update(my_ranks)
            _mirror_dispatch(descs[0], joined)
            prev = joined
            continue
        if len(joined) >= n:
            newly = joined - prev
            st.joined_ranks.clear()
            return max(newly) if newly else n - 1
        prev = joined


def _join_multiprocess_set(ps):
    """join(process_set=ps) under HOROVOD_JOIN_MODE: publish this
    process's ranks WITHIN the set as joined and service the set-scoped
    protocol loop — mirroring every collective the set's still-active
    ranks dispatch — until the whole set has joined. Processes outside
    the set never participate (reference: per-ProcessSet joined_size,
    controller.cc:269-327). Returns the highest GLOBAL rank of the final
    round's newly-joined set (like the global join(); NOT the set-local
    index — index into rank_list() to convert).

    Contract: while any process is inside ``join(process_set=ps)``, the
    set's other owner processes may only dispatch ``ps``-scoped
    collectives until the set join completes (the joining process cannot
    answer other meshes' control rounds while it loops here) — the same
    same-order SPMD contract every armed-mode exchange carries.
    """
    mesh = ps.mesh
    my_ranks = sorted(_set_local_ranks(ps, mesh))
    if not my_ranks:
        raise ValueError(
            f"join(process_set=...): this process owns no ranks of "
            f"{ps.rank_list()}")
    ranks = ps.rank_list()
    n = len(ranks)
    prev = set(ps.joined_ranks)
    ps.joined_ranks = prev | set(my_ranks)
    while True:
        joined, descs = _join_round_set(ps, mesh,
                                        {"joined": my_ranks, "desc": None})
        if descs:
            if any(d != descs[0] for d in descs[1:]):
                raise TensorShapeMismatchError(
                    f"join-mode collective mismatch among active ranks of "
                    f"process set {ranks}: {descs[:3]}")
            _mirror_dispatch(descs[0], joined, process_set=ps)
            prev = joined
            continue
        if len(joined) >= n:
            newly = joined - prev
            ps.joined_ranks = set()
            return max(newly) if newly else ranks[-1]
        prev = joined


def join(rank=None, process_set=None):
    """Signal that ``rank`` (default: every rank this controller owns) has
    exhausted its uneven workload.

    reference semantics (torch/mpi_ops.py DoJoin, controller.cc:269-327,
    joined_size accounting): a joined rank contributes nothing to subsequent
    collectives — Sum treats it as zeros, Average divides by the active
    count, Min/Max/Product/Adasum exclude it — until every rank has joined,
    at which point the join completes and returns the id of the last rank to
    join (and the join state resets).

    Multi-process semantics: set ``HOROVOD_JOIN_MODE=1`` on every process.
    While armed, each global-set eager collective opens with one small KV
    round (the control-plane cost the reference pays on every collective
    through its background controller); a process whose data ran out calls
    ``join()``, which joins ALL the ranks (chips) it owns and services the
    protocol loop — mirroring the still-active ranks' collectives with
    zero contributions — until every rank has joined. Without the mode
    flag, calling join() under a multi-process launch raises rather than
    corrupting state (a process cannot silently drop out of SPMD
    dispatch). alltoall raises while ranks are joined (reference: JOIN
    covers allreduce/allgather/broadcast).

    ``process_set``: join only within that set (reference: joined_size is
    per ProcessSet, controller.cc:269-327). The set's OTHER owner
    processes keep dispatching set-scoped collectives with this process's
    ranks masked out; processes outside the set are untouched and keep
    training. The join loop services set-scoped rounds only — see
    :func:`_join_multiprocess_set` for the ordering contract.
    """
    st = basics._get_state()
    if process_set is not None and process_set.ranks is not None:
        if rank is not None:
            raise ValueError(
                "join(process_set=...) takes no rank argument: the process "
                "joins all the ranks it owns within the set")
        multi, _ = _local_mesh_info(process_set.mesh)
        if multi:
            if not st.config.join_mode:
                raise NotImplementedError(
                    "hvd.join(process_set=...) across processes requires "
                    "HOROVOD_JOIN_MODE=1 on every owner process of the set")
            return _join_multiprocess_set(process_set)
        # Single owner process: all the set's ranks are ours — the join
        # completes immediately (nothing to mirror, nobody else to wait
        # for) and the set's joined state resets.
        process_set.joined_ranks = set()
        return process_set.rank_list()[-1]
    if jax.process_count() > 1:
        if st.config.join_mode:
            return _join_multiprocess(st, rank)
        # Deliberately NOT HorovodInternalError: that is the retryable
        # collective-failure type the elastic @run wrapper restores-and-
        # retries, which would loop forever on this deterministic usage
        # error.
        raise NotImplementedError(
            "hvd.join() across processes requires HOROVOD_JOIN_MODE=1 on "
            "every process (it arms a per-collective negotiation round). "
            "Without it, multi-process eager dispatch is SPMD and cannot "
            "drop one process from subsequent collectives — pad uneven "
            "batches or use the elastic API.")
    if rank is None:
        st.joined_ranks.update(range(basics.size()))
    else:
        if not (0 <= rank < basics.size()):
            raise ValueError(f"join: rank {rank} out of range")
        st.joined_ranks.add(rank)
    if len(st.joined_ranks) >= basics.size():
        st.joined_ranks.clear()
        barrier()
        return basics.size() - 1
    return -1


# ----------------------------------------------------------------------------
# Async handles (reference: handle_manager.h + mpi_ops.py:1245-1283)
# ----------------------------------------------------------------------------

class Handle:
    """In-flight collective result. JAX dispatch is already asynchronous, so
    the handle just wraps the pending device arrays."""

    __slots__ = ("_outputs", "name")

    def __init__(self, outputs, name=None):
        self._outputs = outputs
        self.name = name

    def poll(self):
        # Leaves without is_ready() are concrete host values (numpy etc.),
        # which are by definition complete; jax.Arrays report readiness.
        return all(
            o.is_ready() if hasattr(o, "is_ready") else True
            for o in jax.tree_util.tree_leaves(self._outputs))

    def synchronize(self):
        jax.block_until_ready(self._outputs)
        return self._outputs


@_interceptable("allreduce_async")
def allreduce_async(tensor, op=Average, prescale_factor=1.0,
                    postscale_factor=1.0, process_set=None, name=None):
    """Async allreduce through the tensor-fusion runtime: small tensors
    submitted back-to-back are batched into one fused collective
    (reference: every async allreduce rides the fusion buffer + cycle loop,
    operations.cc:747-853). Process-set ops bypass fusion (the runtime fuses
    per the global mesh only, like the reference fuses per process set)."""
    if (process_set is not None and process_set.ranks is not None) \
            or _join_armed():
        # Armed join mode: the fusion runtime's deferred flush cannot open
        # the per-collective join round at enqueue time (the op set isn't
        # final until flush), so async falls back to an immediate sync
        # dispatch — correctness over overlap while the mode is on.
        return Handle(allreduce(tensor, op=op, prescale_factor=prescale_factor,
                                postscale_factor=postscale_factor,
                                process_set=process_set, name=name), name)
    from horovod_tpu.ops.fusion import get_runtime
    t = tensor if hasattr(tensor, "ndim") else np.asarray(tensor)
    _check_stacked(t, _expected_rows(global_process_set.mesh, basics.size()),
                   "allreduce_async")
    if op == Average and not _is_float(_dtype_of(t)):
        raise ValueError("Average is not supported for integer tensors; use "
                         "hvd.Sum (matches reference torch/mpi_ops.py checks).")
    rt = get_runtime()
    req = _wire.consume_wire_request()
    if req and _wire.quantized_label(req) is not None and \
            _wire.quantized_label(getattr(rt, "wire_dtype", None)) is None:
        # Compression.int8 on the async path while the fusion runtime's own
        # wire is full precision: honor the request with a sync quantized
        # dispatch (correctness over overlap — the runtime quantizes whole
        # buckets only when its own wire knob is quantized, and a per-call
        # request cannot retroactively re-key an open bucket).
        _wire.request_wire_once(req)
        return Handle(allreduce(t, op=op, prescale_factor=prescale_factor,
                                postscale_factor=postscale_factor,
                                name=name), name)
    return rt.enqueue_allreduce(t, op, prescale_factor,
                                postscale_factor, name)


@_interceptable("allreduce_async")
def grouped_allreduce_async(tensors, op=Average, prescale_factor=1.0,
                            postscale_factor=1.0, process_set=None, name=None):
    """Async grouped allreduce through the fusion runtime: the group
    completes atomically and same-signature groups ride ONE fused bucket
    (reference: grouped enqueue + GroupTable, operations.cc:1480,
    group_table.h). Process-set groups bypass fusion like allreduce_async;
    so does armed join mode (see allreduce_async)."""
    if (process_set is not None and process_set.ranks is not None) \
            or _join_armed():
        out = grouped_allreduce(tensors, op=op,
                                prescale_factor=prescale_factor,
                                postscale_factor=postscale_factor,
                                process_set=process_set, name=name)
        return Handle(out, name)
    from horovod_tpu.ops.fusion import get_runtime
    ts = [t if hasattr(t, "ndim") else np.asarray(t) for t in tensors]
    rows = _expected_rows(global_process_set.mesh, basics.size())
    for t in ts:
        _check_stacked(t, rows, "grouped_allreduce_async")
        if op == Average and not _is_float(_dtype_of(t)):
            raise ValueError(
                "Average is not supported for integer tensors; use hvd.Sum "
                "(matches reference torch/mpi_ops.py checks).")
    rt = get_runtime()
    req = _wire.consume_wire_request()
    if req and _wire.quantized_label(req) is not None and \
            _wire.quantized_label(getattr(rt, "wire_dtype", None)) is None:
        # Same one-shot discipline as allreduce_async: the request must be
        # consumed HERE (not leak to the next unrelated eager dispatch),
        # and when the fusion runtime's own wire is full precision it is
        # honored with a sync quantized grouped dispatch.
        _wire.request_wire_once(req)
        return Handle(grouped_allreduce(
            ts, op=op, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, name=name), name)
    return rt.enqueue_grouped_allreduce(
        ts, op, prescale_factor, postscale_factor, name)


@_interceptable("allgather_async")
def allgather_async(tensor, process_set=None, name=None):
    return Handle(allgather(tensor, process_set=process_set, name=name), name)


@_interceptable("broadcast_async")
def broadcast_async(tensor, root_rank, process_set=None, name=None):
    return Handle(broadcast(tensor, root_rank, process_set=process_set,
                            name=name), name)


@_interceptable("alltoall_async")
def alltoall_async(tensor, splits=None, process_set=None, name=None):
    return Handle(alltoall(tensor, splits=splits, process_set=process_set,
                           name=name), name)


@_interceptable("reducescatter_async")
def reducescatter_async(tensor, op=Sum, process_set=None, name=None):
    return Handle(reducescatter(tensor, op=op, process_set=process_set,
                                name=name), name)


def poll(handle):
    return handle.poll()


def synchronize(handle):
    return handle.synchronize()


# ----------------------------------------------------------------------------
# Object collectives (reference: torch/functions.py broadcast_object /
# allgather_object — pickle to a byte tensor, exchange, unpickle).
# ----------------------------------------------------------------------------

def broadcast_object(obj, root_rank=0, process_set=None, name=None):
    import cloudpickle  # available via baked-in deps
    mesh, ps = _mesh_for(process_set)
    n = ps.size()
    payload = cloudpickle.dumps(obj)
    buf = np.frombuffer(payload, dtype=np.uint8)
    n_rows = _expected_rows(mesh, n)
    # Pad (or truncate — non-root payloads are discarded anyway) all ranks
    # to the root's length (length broadcast first).
    ln = int(broadcast(jnp.full((n_rows, 1), len(buf), jnp.int32), root_rank,
                       process_set=process_set)[0, 0])
    row = jnp.pad(jnp.asarray(buf), (0, max(0, ln - len(buf))))[:ln]
    stacked = jnp.tile(row[None], (n_rows, 1))
    out = broadcast(stacked, root_rank, process_set=process_set, name=name)
    data = bytes(np.asarray(out[0, :ln], np.uint8))
    return cloudpickle.loads(data)


def allgather_object_single(obj, process_set=None, name=None):
    """Frontend convenience: gather ONE object for this caller — the object
    stands for each rank this process owns (all of them single-controller,
    the local chips multi-process). Shared by the torch/tf/mxnet
    ``allgather_object`` wrappers."""
    mesh, ps = _mesh_for(process_set)
    n_rows = _expected_rows(mesh, ps.size())
    return allgather_object([obj] * n_rows, process_set=process_set,
                            name=name)


def allgather_object(objs, process_set=None, name=None):
    """Gather every rank's object(s); returns the full per-rank list on
    every caller. ``objs``: one object per rank (single process) or per
    local chip (multi-process); the global split sizes come back from the
    ragged allgather's negotiation."""
    import cloudpickle
    mesh, ps = _mesh_for(process_set)
    n = ps.size()
    n_rows = _expected_rows(mesh, n)
    if not isinstance(objs, (list, tuple)) or len(objs) != n_rows:
        raise ValueError(
            f"allgather_object expects a list of {n_rows} objects "
            f"(one per {'local chip' if n_rows != n else 'rank'})")
    bufs = [np.frombuffer(cloudpickle.dumps(o), dtype=np.uint8) for o in objs]
    gathered, sizes = allgather_ragged([jnp.asarray(b) for b in bufs],
                                       process_set=process_set, name=name,
                                       return_sizes=True)
    out, off = [], 0
    arr = np.asarray(gathered, np.uint8)
    for s in sizes:
        out.append(cloudpickle.loads(bytes(arr[off:off + s])))
        off += s
    return out
