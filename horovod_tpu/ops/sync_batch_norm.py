"""Cross-replica synchronized batch normalization.

Reference (horovod/torch/sync_batch_norm.py:218 LoC /
tensorflow/sync_batch_norm.py): batch-norm statistics (mean, var, count) are
allreduced across workers so small per-worker batches normalize with global
statistics.

TPU-native design: a flax ``nn.Module`` computing mean/mean-of-squares locally
and ``psum``-ing them over the data-parallel mesh axis — two tiny collectives
XLA fuses into the step. Used inside a shard_mapped train step with
``axis_name`` equal to the DP axis.
"""

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from horovod_tpu.common.topology import HVD_AXIS


class SyncBatchNorm(nn.Module):
    """Drop-in BatchNorm with cross-replica statistics.

    Attributes mirror flax BatchNorm; ``axis_name`` is the mesh axis to
    synchronize over (None = local-only, i.e. plain BatchNorm).
    """
    use_running_average: bool = False
    axis_name: str = HVD_AXIS
    momentum: float = 0.99
    epsilon: float = 1e-5
    dtype: jnp.dtype = None
    use_bias: bool = True
    use_scale: bool = True
    scale_init: nn.initializers.Initializer = nn.initializers.ones
    bias_init: nn.initializers.Initializer = nn.initializers.zeros

    @nn.compact
    def __call__(self, x, use_running_average=None):
        use_running_average = nn.merge_param(
            "use_running_average", self.use_running_average,
            use_running_average)
        features = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros(features, jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones(features, jnp.float32))

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            reduce_axes = tuple(range(x.ndim - 1))
            xf = x.astype(jnp.float32)
            local_mean = jnp.mean(xf, axis=reduce_axes)
            local_sq = jnp.mean(jnp.square(xf), axis=reduce_axes)
            if self.axis_name is not None and not self.is_initializing():
                # One fused psum over [mean, mean(x^2)] — the reference
                # allreduces the stat pair the same way
                # (sync_batch_norm.py _sync_batch_norm_forward).
                stats = jnp.stack([local_mean, local_sq])
                stats = lax.pmean(stats, self.axis_name)
                mean, sq = stats[0], stats[1]
            else:
                mean, sq = local_mean, local_sq
            var = jnp.maximum(sq - jnp.square(mean), 0.0)
            if not self.is_initializing():
                ra_mean.value = (self.momentum * ra_mean.value
                                 + (1 - self.momentum) * mean)
                ra_var.value = (self.momentum * ra_var.value
                                + (1 - self.momentum) * var)

        y = (x.astype(jnp.float32) - mean) * lax.rsqrt(var + self.epsilon)
        if self.use_scale:
            y = y * self.param("scale", self.scale_init, (features,),
                               jnp.float32)
        if self.use_bias:
            y = y + self.param("bias", self.bias_init, (features,),
                               jnp.float32)
        return y.astype(self.dtype or x.dtype)
