"""Gradient compression for the wire.

Reference (horovod/torch/compression.py / tensorflow/compression.py, 74 LoC
each): ``Compression.none`` and ``Compression.fp16`` — cast gradients to fp16
before the allreduce, cast back after.

TPU addition: ``Compression.bf16`` — bfloat16 is the TPU-native wire dtype
(same exponent range as fp32, so no loss-scale bookkeeping is needed, and ICI
moves half the bytes).
"""

import jax.numpy as jnp


class Compressor:
    """Interface: compress returns (compressed, ctx); decompress restores."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """reference: compression.py NoneCompressor."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = None

    @classmethod
    def compress(cls, tensor):
        if jnp.issubdtype(tensor.dtype, jnp.floating) and \
                tensor.dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), tensor.dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class FP16Compressor(_CastCompressor):
    """reference: compression.py FP16Compressor."""
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = jnp.bfloat16


class Compression:
    """reference: compression.py Compression namespace."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
