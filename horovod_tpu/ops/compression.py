"""Gradient compression for the wire.

Reference (horovod/torch/compression.py / tensorflow/compression.py, 74 LoC
each): ``Compression.none`` and ``Compression.fp16`` — cast gradients to fp16
before the allreduce, cast back after.

TPU addition: ``Compression.bf16`` — bfloat16 is the TPU-native wire dtype
(same exponent range as fp32, so no loss-scale bookkeeping is needed, and ICI
moves half the bytes).
"""

import jax.numpy as jnp


class Compressor:
    """Interface: compress returns (compressed, ctx); decompress restores."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """reference: compression.py NoneCompressor."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = None

    @classmethod
    def compress(cls, tensor):
        if jnp.issubdtype(tensor.dtype, jnp.floating) and \
                tensor.dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), tensor.dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class FP16Compressor(_CastCompressor):
    """reference: compression.py FP16Compressor."""
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = jnp.bfloat16


class Int8Compressor(Compressor):
    """int8 on the wire (TPU addition beyond the reference's fp16 cast).

    Unlike the cast compressors, int8 cannot ride an ordinary psum (summing
    n int8s overflows and per-rank scales differ), so compress/decompress
    are routing markers into the quantized wire tier
    (:mod:`horovod_tpu.ops.wire`): the quantization itself happens INSIDE
    the collective, fused into its reduce-scatter→all-gather phases
    (EQuARX-style, arXiv:2506.17615 — int8 both legs, fp32 accumulation,
    per-block scales, error feedback on the eager/fused paths). All three
    dispatch paths honor it: the fused jit tree (DistributedOptimizer /
    ``fused_allreduce_tree``) detects the compressor and rides
    ``strategies.scaled_allreduce_int8``; ``compress()`` arms a one-shot
    wire request that the next EAGER allreduce dispatch consumes (the
    compress→allreduce→decompress frontend pattern); the eager fusion
    runtime quantizes whole buckets under ``HOROVOD_WIRE_DTYPE=int8``.
    Lossy: each wire leg adds error ≤ its block's max/254, compensated
    next round by the error-feedback residual where the path keeps one.
    Combinations the exchange can't express (explicit process sets,
    non-Sum/Average ops, sub-block payloads) fall back to the exact
    collective.
    """

    @staticmethod
    def compress(tensor):
        from horovod_tpu.ops import wire
        wire.request_wire_once("int8")
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


def _powersgd(rank=4, min_compression_rate=2.0, ef_dtype=None):
    """Construct the stateful PowerSGD marker (low-rank factor exchange
    with error feedback; honored by DistributedOptimizer only — see
    horovod_tpu/optim/powersgd.py). ``ef_dtype`` keeps the error-feedback
    residual in a wider dtype than the gradients (e.g. fp32 under bf16
    training)."""
    from horovod_tpu.optim.powersgd import PowerSGDCompressor
    return PowerSGDCompressor(rank=rank,
                              min_compression_rate=min_compression_rate,
                              ef_dtype=ef_dtype)


class Compression:
    """reference: compression.py Compression namespace."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
    powersgd = staticmethod(_powersgd)
