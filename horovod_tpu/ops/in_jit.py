"""Collectives for use *inside* jitted/sharded programs.

This is the API users call inside their own ``shard_map``/``pjit`` training
steps — the TPU-native analog of the reference's in-graph TF ops
(reference: horovod/tensorflow/mpi_ops.py:58-170) and of its XLA CustomCall
path (reference: horovod/tensorflow/xla_mpi_ops.cc): on TPU *every* op is
already inside XLA, so "the XLA path" is simply ``jax.lax`` collectives over a
named mesh axis, fused and scheduled by the compiler.

Process-set semantics (reference's per-set communicators,
horovod/common/process_set.cc) are implemented SPMD-style: all ranks execute
the op, and subset reductions use identity-masked full-axis collectives
(Sum/Average ride one ``psum`` with non-members contributing the identity) or
an ``all_gather`` + static local select for the non-linear ops. Non-member
ranks receive a well-defined value they are expected to ignore, mirroring how
non-member processes simply don't call the op in the reference.
"""

import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu.common.topology import HVD_AXIS
from horovod_tpu.ops.collective_ops import (Adasum, Average, Max, Min, Product,
                                            ReduceOp, Sum)


def _ranks(process_set):
    if process_set is None or getattr(process_set, "ranks", None) is None:
        return None
    return list(process_set.ranks)


def _member_mask(ranks, axis_name):
    idx = lax.axis_index(axis_name)
    return jnp.isin(idx, jnp.asarray(np.array(ranks)))


def size(axis_name=HVD_AXIS):
    return lax.axis_size(axis_name)


def rank(axis_name=HVD_AXIS):
    return lax.axis_index(axis_name)


def _gather_select(x, ranks, axis_name):
    """all_gather the full axis, select the process set's slices (static)."""
    g = lax.all_gather(x, axis_name)  # (world, ...)
    return g[jnp.asarray(np.array(ranks))]  # (set_size, ...)


def _pos_in_set(ranks, axis_name):
    """This rank's index within the set (0 for non-members)."""
    idx = lax.axis_index(axis_name)
    r = jnp.asarray(np.array(ranks))
    return jnp.sum(jnp.where(r == idx, jnp.arange(len(ranks)), 0))


def allreduce(x, op=Average, axis_name=HVD_AXIS, process_set=None,
              prescale_factor=1.0, postscale_factor=1.0):
    ranks = _ranks(process_set)
    op = ReduceOp(op)
    if prescale_factor != 1.0:
        x = x * jnp.asarray(prescale_factor, x.dtype)
    if ranks is None:
        n = lax.axis_size(axis_name)
        if op in (Sum, Average):
            y = lax.psum(x, axis_name)
            if op == Average:
                y = y / jnp.asarray(n, y.dtype)
        elif op == Min:
            y = lax.pmin(x, axis_name)
        elif op == Max:
            y = lax.pmax(x, axis_name)
        elif op == Product:
            g = lax.all_gather(x, axis_name)
            y = jnp.prod(g, axis=0)
        elif op == Adasum:
            from horovod_tpu.ops.adasum import adasum_tree
            g = lax.all_gather(x, axis_name)
            y = adasum_tree([g[i] for i in range(n)])
        else:
            raise ValueError(f"unknown op {op}")
    else:
        n = len(ranks)
        if op in (Sum, Average):
            mask = _member_mask(ranks, axis_name)
            y = lax.psum(jnp.where(mask, x, jnp.zeros_like(x)), axis_name)
            if op == Average:
                y = y / jnp.asarray(n, y.dtype)
        elif op in (Min, Max, Product):
            g = _gather_select(x, ranks, axis_name)
            reducer = {Min: jnp.min, Max: jnp.max, Product: jnp.prod}[op]
            y = reducer(g, axis=0)
        elif op == Adasum:
            from horovod_tpu.ops.adasum import adasum_tree
            g = _gather_select(x, ranks, axis_name)
            y = adasum_tree([g[i] for i in range(n)])
        else:
            raise ValueError(f"unknown op {op}")
    if postscale_factor != 1.0:
        y = y * jnp.asarray(postscale_factor, y.dtype)
    return y


def allgather(x, axis_name=HVD_AXIS, process_set=None, axis=0, tiled=True):
    ranks = _ranks(process_set)
    if ranks is None:
        return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)
    g = _gather_select(x, ranks, axis_name)  # (set, ...)
    g = jnp.moveaxis(g, 0, axis)
    if tiled:
        shape = list(g.shape)
        shape[axis] = shape[axis] * shape[axis + 1]
        del shape[axis + 1]
        # (set, m, ...) -> (set*m, ...) along `axis`
        g = g.reshape(shape)
    return g


def broadcast(x, root_rank, axis_name=HVD_AXIS, process_set=None):
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
    if jnp.issubdtype(x.dtype, jnp.bool_):
        return lax.psum(masked.astype(jnp.int32), axis_name).astype(x.dtype)
    return lax.psum(masked, axis_name)


def reducescatter(x, op=Sum, axis_name=HVD_AXIS, process_set=None,
                  scatter_axis=0):
    op = ReduceOp(op)
    if op not in (Sum, Average):
        raise ValueError("reducescatter supports Sum/Average")
    ranks = _ranks(process_set)
    if ranks is None:
        n = lax.axis_size(axis_name)
        y = lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis,
                             tiled=True)
    else:
        n = len(ranks)
        if x.shape[scatter_axis] % n != 0:
            raise ValueError(
                f"reducescatter: axis {scatter_axis} size "
                f"{x.shape[scatter_axis]} not divisible by set size {n}")
        mask = _member_mask(ranks, axis_name)
        full = lax.psum(jnp.where(mask, x, jnp.zeros_like(x)), axis_name)
        chunk = x.shape[scatter_axis] // n
        pos = _pos_in_set(ranks, axis_name)
        y = lax.dynamic_slice_in_dim(full, pos * chunk, chunk, axis=scatter_axis)
    if op == Average:
        y = y / jnp.asarray(n, y.dtype)
    return y


def alltoall(x, axis_name=HVD_AXIS, process_set=None, split_axis=0,
             concat_axis=0):
    ranks = _ranks(process_set)
    if ranks is None:
        return lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)
    n = len(ranks)
    if x.shape[split_axis] % n != 0:
        raise ValueError(
            f"alltoall: axis {split_axis} size {x.shape[split_axis]} not "
            f"divisible by set size {n}")
    chunk = x.shape[split_axis] // n
    g = _gather_select(x, ranks, axis_name)  # (set, ..., m, ...)
    pos = _pos_in_set(ranks, axis_name)
    parts = [lax.dynamic_slice_in_dim(g[i], pos * chunk, chunk, axis=split_axis)
             for i in range(n)]
    return jnp.concatenate(parts, axis=concat_axis)


def ppermute(x, perm, axis_name=HVD_AXIS):
    """Point-to-point ring shifts — the primitive ring attention builds on."""
    return lax.ppermute(x, axis_name, perm)


def mark_varying(tree, axis_name=HVD_AXIS):
    """Lift every leaf to device-varying over ``axis_name`` (no-op for leaves
    already varying). Needed when mixing replicated values (e.g. an initial
    carry built from constants) with per-rank values inside shard_map scans
    and conds under JAX's varying-manual-axes checking."""
    import jax as _jax

    def mv(x):
        vma = getattr(_jax.typeof(x), "vma", ())
        if axis_name in vma:
            return x
        return lax.pcast(x, axis_name, to="varying")

    return _jax.tree_util.tree_map(mv, tree)


def mark_varying_like(tree, ref, axis_name=HVD_AXIS):
    """Lift every leaf of ``tree`` to device-varying over ``axis_name`` AND
    every axis ``ref`` (a data operand) is already varying over. Use for
    scan/loop carries whose steady-state type combines constants with data
    that may itself be sharded over MORE mesh axes (e.g. a ring-attention
    accumulator on a dp x pp x sp mesh is varying over all three)."""
    import jax as _jax

    axes = set(getattr(_jax.typeof(ref), "vma", ())) | {axis_name}
    for ax in axes:
        tree = mark_varying(tree, ax)
    return tree
