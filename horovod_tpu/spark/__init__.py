"""Spark cluster integration (reference: horovod/spark/).

``run()`` launches the training function as a Spark job with ranks assigned
from partition/host placement (reference: spark/runner.py:200). The
estimator layer (``TpuEstimator``) implements the Store→Parquet→train→model
pipeline of the reference's Spark ML estimators (spark/common/estimator.py)
with a pandas/pyarrow data path, so it also runs without a Spark cluster —
pyspark is only required for the distributed job backend.
"""

from horovod_tpu.spark.estimator import TpuEstimator, TpuModel
from horovod_tpu.spark.keras import KerasEstimator, KerasModel
from horovod_tpu.spark.lightning import LightningEstimator, LightningModel
from horovod_tpu.spark.runner import run, run_elastic, spark_available
from horovod_tpu.spark.store import (DBFSLocalStore, FilesystemStore,
                                     HDFSStore, LocalStore, Store)
from horovod_tpu.spark.task import assign_ranks
from horovod_tpu.spark.torch import TorchEstimator, TorchModel

__all__ = ["run", "run_elastic", "spark_available", "Store", "LocalStore",
           "FilesystemStore", "HDFSStore", "DBFSLocalStore",
           "TpuEstimator", "TpuModel", "KerasEstimator",
           "KerasModel", "TorchEstimator", "TorchModel",
           "LightningEstimator", "LightningModel", "assign_ranks"]
