"""LightningEstimator: Spark-ML estimator for PyTorch-Lightning modules.

Reference: horovod/spark/lightning/estimator.py:1-703 (TorchEstimator
variant driving a ``LightningModule`` through a ``pl.Trainer`` on the
workers), remote.py:40-342 (the remote trainer: logger/checkpoint wiring,
rank-0 store sync, resume, trainer.fit over a datamodule) and
datamodule.py (store-backed ``LightningDataModule``).

TPU-image adaptation: pytorch-lightning is not baked into this image, so
every Lightning touch point is lazy and the estimator fails fast with a
clear gate when it is absent (``TorchEstimator`` covers plain torch
modules without the dependency). The subsystem is exercised in CI against
a faithful API stub (tests/test_integrations.py) — the same way the
reference exercises its estimator against petastorm-free mocks.

What ``fit`` wires, mirroring the reference remote trainer:

- the module's ``configure_optimizers`` result is wrapped in
  ``horovod_tpu.torch.DistributedOptimizer`` (reference: remote.py wires
  hvd into the Lightning loop; _EstimatorParams optimizer handling,
  estimator.py:195-227);
- rank-0 parameter broadcast before training via an ``on_fit_start``
  callback (reference: remote.py broadcasts before trainer.fit);
- a ``ModelCheckpoint`` pointed at the Store's staged run directory —
  the user's own ModelCheckpoint is re-pointed if supplied, else a
  default one is appended (reference: remote.py:168-182 "Lightning
  requires to add checkpoint callbacks for all ranks");
- a rank-0 store-sync callback pushing checkpoints/logs each epoch
  (reference: remote.py:186-190 _SyncCallback);
- optional ``EarlyStopping`` (reference: estimator exposes user callbacks
  incl. early stopping, estimator.py:204+);
- per-epoch ``callback_metrics`` harvested back to the driver as
  ``model.history`` (reference: remote.py returns serialized metrics);
- resume from the staged checkpoint via ``trainer.fit(ckpt_path=...)``
  (reference: remote.py ckpt resume path).
"""

import os

import numpy as np

from horovod_tpu.spark.estimator import SparkParamsMixin
from horovod_tpu.spark.store import LocalStore
from horovod_tpu.spark.torch import TorchModel


def _lightning():
    try:
        import pytorch_lightning as pl
        return pl
    except ImportError as e:
        raise ImportError(
            "LightningEstimator requires pytorch_lightning; this image does "
            "not ship it — use TorchEstimator for plain torch modules") from e


def _wrap_configure_optimizers(module, backward_passes_per_step):
    """Intercept ``configure_optimizers`` so every returned torch optimizer
    is wrapped in the distributed optimizer (gradients averaged across
    ranks). Handles the Lightning return shapes: a single optimizer, a
    list, an (optimizers, schedulers) tuple, or a config dict."""
    import torch

    from horovod_tpu.torch.optimizer import DistributedOptimizer

    if getattr(module, "_hvd_optimizers_wrapped", False):
        return  # a second fit() must not stack another wrapper
    module._hvd_optimizers_wrapped = True
    module._hvd_wrapped_opts = []
    orig = module.configure_optimizers

    def _wrap_one(opt, single):
        if not isinstance(opt, torch.optim.Optimizer):
            return opt
        if hasattr(opt, "_allreduce_grad_async"):
            # Already distributed: re-wrapping would stack two dynamic
            # subclasses whose super(self.__class__) calls recurse.
            return opt
        dist = DistributedOptimizer(
            opt,
            named_parameters=module.named_parameters() if single else None,
            backward_passes_per_step=backward_passes_per_step)
        module._hvd_wrapped_opts.append(dist)
        return dist

    def wrapped(*args, **kwargs):
        # Retire the previous fit's wrappers: their gradient hooks are
        # still registered on the SAME parameters and would double-fire.
        for old in module._hvd_wrapped_opts:
            old._remove_hooks()
        module._hvd_wrapped_opts = []
        cfg = orig(*args, **kwargs)
        if isinstance(cfg, (list, tuple)) and len(cfg) == 2 \
                and isinstance(cfg[0], (list, tuple)):
            opts, scheds = cfg
            return [_wrap_one(o, len(opts) == 1) for o in opts], scheds
        if isinstance(cfg, (list, tuple)):
            return [_wrap_one(o, len(cfg) == 1) for o in cfg]
        if isinstance(cfg, dict) and "optimizer" in cfg:
            return {**cfg, "optimizer": _wrap_one(cfg["optimizer"], True)}
        return _wrap_one(cfg, True)

    module.configure_optimizers = wrapped


def make_datamodule(pl, X, y, val_X=None, val_y=None, batch_size=32,
                    shuffle=True, seed=0, num_workers=0):
    """Store-materialized arrays → ``pl.LightningDataModule`` with
    sharded train/val loaders (reference: datamodule.py
    PetastormDataModule — per-worker reader shards; here the shard is a
    strided row slice, matching ParquetBatchReader's shard contract).
    Sharding is per PROCESS (cross_rank/cross_size): a single controller
    owns all its chips' ranks and the torch frontend reduces across the
    full world, so each process feeds its own row slice."""
    import torch
    import torch.utils.data as tud

    import horovod_tpu.torch as hvd_torch

    rank, size = hvd_torch.cross_rank(), hvd_torch.cross_size()

    def _shard(a):
        return np.ascontiguousarray(a[rank::size])

    class _DataModule(pl.LightningDataModule):
        def __init__(self):
            super().__init__()
            self._train = None
            self._val = None

        def setup(self, stage=None):
            g = np.random.default_rng(seed)
            order = g.permutation(len(X)) if shuffle else np.arange(len(X))
            self._train = tud.TensorDataset(
                torch.as_tensor(_shard(X[order])),
                torch.as_tensor(_shard(y[order])))
            if val_X is not None and len(val_X):
                self._val = tud.TensorDataset(
                    torch.as_tensor(_shard(val_X)),
                    torch.as_tensor(_shard(val_y)))

        def train_dataloader(self):
            if self._train is None:
                self.setup()
            gen = torch.Generator()
            gen.manual_seed(seed)  # epoch order honors the estimator seed
            return tud.DataLoader(self._train, batch_size=batch_size,
                                  shuffle=shuffle, drop_last=True,
                                  generator=gen if shuffle else None,
                                  num_workers=num_workers)

        def val_dataloader(self):
            if self._train is None:
                self.setup()
            if self._val is None:
                return []
            return tud.DataLoader(self._val, batch_size=batch_size,
                                  shuffle=False, num_workers=num_workers)

    return _DataModule()


class LightningEstimator(SparkParamsMixin):
    """Train a ``LightningModule`` from a DataFrame
    (reference: spark/lightning/estimator.py:195-360 — params mirrored
    where meaningful on TPU; petastorm/num_gpus/mp-start plumbing is
    designed out, the data path is the Store's Parquet pipeline).

    Args:
        model: ``pl.LightningModule`` defining ``training_step`` (and
            optionally ``validation_step``) + ``configure_optimizers``.
        feature_cols / label_cols: DataFrame columns.
        validation: None, a float fraction (tail split after a seeded
            shuffle), or a column name whose truthy rows form the
            validation set (reference: EstimatorParams.validation).
        callbacks: extra ``pl.Callback`` objects (a user ModelCheckpoint
            is re-pointed at the store's staged run dir, reference:
            remote.py:168-178).
        checkpoint_callback: append a default ModelCheckpoint when the
            user supplied none (reference: remote.py:179-182).
        early_stopping: patience (int) for an EarlyStopping on
            ``early_stopping_monitor`` (default ``val_loss``), or None.
        gradient_clip_val / logger / trainer_args: passed to
            ``pl.Trainer`` (reference: estimator.py logger/trainer_args
            params).
        terminate_on_nan: maps to ``Trainer(detect_anomaly=...)``
            (reference: estimator.py:215 terminate_on_nan).
        batch_size, epochs, store, run_id, shuffle, seed, verbose,
        backward_passes_per_step: as in TorchEstimator.
    """

    def __init__(self, model, feature_cols, label_cols, batch_size=32,
                 epochs=1, store=None, run_id=None, shuffle=True, seed=0,
                 verbose=0, validation=None, callbacks=None,
                 checkpoint_callback=True, early_stopping=None,
                 early_stopping_monitor="val_loss", gradient_clip_val=None,
                 terminate_on_nan=False, logger=None, trainer_args=None,
                 backward_passes_per_step=1, num_dataloader_workers=0):
        _lightning()  # fail fast with the clear gating error
        self.model = model
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.store = store or LocalStore("./tpu_estimator")
        self.run_id = run_id
        self.shuffle = shuffle
        self.seed = seed
        self.verbose = verbose
        self.validation = validation
        self.callbacks = list(callbacks or [])
        self.checkpoint_callback = checkpoint_callback
        self.early_stopping = early_stopping
        self.early_stopping_monitor = early_stopping_monitor
        self.gradient_clip_val = gradient_clip_val
        self.terminate_on_nan = terminate_on_nan
        self.logger = logger
        self.trainer_args = dict(trainer_args or {})
        self.backward_passes_per_step = backward_passes_per_step
        self.num_dataloader_workers = num_dataloader_workers

    # -- data -------------------------------------------------------------

    def _split_validation(self, df):
        """(train_X, train_y, val_X, val_y) honoring the ``validation``
        param (fraction or indicator column, reference:
        EstimatorParams.validation semantics)."""
        from horovod_tpu.spark.estimator import materialize_dataframe
        if isinstance(self.validation, str):
            # Indicator column: ride the store-backed path (durability
            # write + chunked read-back — never driver-toPandas a Spark
            # frame) with the indicator appended as a trailing feature,
            # then split on it.
            feats = [c for c in self.feature_cols if c != self.validation]
            X_all, y = materialize_dataframe(
                self.store, df, feats + [self.validation], self.label_cols)
            val_mask = X_all[..., -1].astype(bool)
            X = X_all[..., :-1]
            return (X[~val_mask], y[~val_mask], X[val_mask], y[val_mask])
        X, y = materialize_dataframe(self.store, df, self.feature_cols,
                                     self.label_cols)
        if not self.validation:
            return X, y, None, None
        frac = float(self.validation)
        order = np.random.default_rng(self.seed).permutation(len(X))
        n_val = max(1, int(len(X) * frac))
        tr, va = order[:-n_val], order[-n_val:]
        return X[tr], y[tr], X[va], y[va]

    # -- training ---------------------------------------------------------

    def fit(self, df):
        pl = _lightning()

        import horovod_tpu.torch as hvd_torch

        if not hvd_torch.is_initialized():
            hvd_torch.init()

        X, y, val_X, val_y = self._split_validation(df)
        run_id = self.run_id or self.store.new_run_id()
        from horovod_tpu.spark.store import stage_checkpoints
        local_dir, sync_ckpt = stage_checkpoints(self.store, run_id)

        module = self.model
        _wrap_configure_optimizers(module, self.backward_passes_per_step)

        # --- callback wiring (reference: remote.py:160-190) --------------
        from pytorch_lightning.callbacks import EarlyStopping, ModelCheckpoint

        callbacks = list(self.callbacks)
        ckpt_cb = None
        for cb in callbacks:
            if isinstance(cb, ModelCheckpoint):
                # Re-point the user's checkpoint callback at the staged
                # run dir (reference: remote.py:168-175 rewrites dirpath).
                cb.dirpath = local_dir
                ckpt_cb = cb
                break
        if ckpt_cb is None and self.checkpoint_callback:
            ckpt_cb = ModelCheckpoint(dirpath=local_dir, filename="model")
            callbacks.append(ckpt_cb)
        if self.early_stopping:
            callbacks.append(EarlyStopping(
                monitor=self.early_stopping_monitor,
                patience=int(self.early_stopping)))

            class _SyncShouldStop(pl.Callback):
                """Reconcile the stop decision across ranks: each rank's
                val shard yields a different monitored metric, and with
                no horovod-aware Trainer strategy PL cannot reconcile
                ``should_stop`` itself (reference strategy:
                reduce_boolean_decision) — a divergent stop would leave
                the continuing ranks blocked in their next allreduce.
                Any rank voting stop stops everyone."""

                def on_train_epoch_end(self, trainer, pl_module):
                    votes = hvd_torch.allgather_object(
                        bool(trainer.should_stop))
                    trainer.should_stop = any(votes)

            callbacks.append(_SyncShouldStop())

        class _BroadcastCallback(pl.Callback):
            """Rank-0 state broadcast before the first step (reference:
            remote.py broadcasts model/optimizer state pre-fit)."""

            def on_fit_start(self, trainer, pl_module):
                hvd_torch.broadcast_parameters(pl_module.state_dict(),
                                               root_rank=0)

        class _MetricsCallback(pl.Callback):
            """Per-epoch callback_metrics → driver-side history
            (reference: remote.py serializes logged metrics back)."""

            def __init__(self):
                self.history = []

            def on_train_epoch_end(self, trainer, pl_module):
                self.history.append({
                    k: float(v)
                    for k, v in dict(trainer.callback_metrics).items()})

        class _StoreSyncCallback(pl.Callback):
            """Rank-0 pushes staged checkpoints to the Store each epoch
            (reference: remote.py:186-190 _SyncCallback)."""

            def on_train_epoch_end(self, trainer, pl_module):
                if hvd_torch.rank() == 0:
                    sync_ckpt()

        metrics_cb = _MetricsCallback()
        callbacks += [_BroadcastCallback(), metrics_cb,
                      _StoreSyncCallback()]

        dm = make_datamodule(pl, X, y, val_X, val_y,
                             batch_size=self.batch_size,
                             shuffle=self.shuffle, seed=self.seed,
                             num_workers=self.num_dataloader_workers)

        trainer_kwargs = dict(max_epochs=self.epochs, callbacks=callbacks,
                              logger=self.logger or False,
                              enable_checkpointing=bool(
                                  self.checkpoint_callback or ckpt_cb),
                              detect_anomaly=self.terminate_on_nan)
        if self.gradient_clip_val is not None:
            trainer_kwargs["gradient_clip_val"] = self.gradient_clip_val
        trainer_kwargs.update(self.trainer_args)
        trainer = pl.Trainer(**trainer_kwargs)

        # Resume from the staged checkpoint when this run_id already has
        # one (reference: remote.py resume; TorchEstimator._has_checkpoint).
        # The configured callback's filename is probed first so custom
        # filenames resume too.
        ckpt_path = None
        if ckpt_cb is not None:
            names = [f"{getattr(ckpt_cb, 'filename', None) or 'model'}.ckpt",
                     "model.ckpt", "last.ckpt"]
            for name in dict.fromkeys(names):
                p = os.path.join(local_dir, name)
                if os.path.exists(p):
                    ckpt_path = p
                    break

        trainer.fit(module, datamodule=dm, ckpt_path=ckpt_path)
        if hvd_torch.rank() == 0:
            # Rank-0 only, like the per-epoch _StoreSyncCallback: every
            # rank concurrently pushing its staged dir to a remote store
            # would race (last writer wins with a possibly non-rank-0
            # replica).
            sync_ckpt()

        return LightningModel(module, self.feature_cols, self.label_cols,
                              history=metrics_cb.history, run_id=run_id)


class LightningModel(TorchModel):
    """Result of ``LightningEstimator.fit``: ``transform(df)`` appends
    ``<label>__output`` prediction columns via the module's forward
    (reference: spark/lightning/estimator.py TorchModel/transform path).
    ``history`` carries the per-epoch logged metrics (val metrics
    included when a validation split/column was configured)."""

    def transform(self, df):
        # TorchModel.transform already runs the forward under no_grad;
        # only the train/eval mode needs handling — and it is restored,
        # so a follow-up fit() doesn't silently train in eval mode.
        was_training = self.model.training
        self.model.eval()
        try:
            return super().transform(df)
        finally:
            if was_training:
                self.model.train()
