"""LightningEstimator: Spark-ML estimator for PyTorch-Lightning modules.

Reference: horovod/spark/lightning/estimator.py (TorchEstimator variant that
drives a ``LightningModule`` through a Trainer in the remote workers).

Gated on a pytorch-lightning install (not part of the baked TPU image): when
absent, ``fit`` raises with a pointer to :class:`TorchEstimator`, whose
training loop covers the same torch models without the Lightning dependency.
"""

from horovod_tpu.spark.torch import TorchEstimator, TorchModel  # noqa: F401


def _lightning():
    try:
        import pytorch_lightning as pl
        return pl
    except ImportError as e:
        raise ImportError(
            "LightningEstimator requires pytorch_lightning; this image does "
            "not ship it — use TorchEstimator for plain torch modules") from e


class LightningEstimator(TorchEstimator):
    """Train a ``LightningModule`` from a DataFrame. The module must define
    ``training_step`` and ``configure_optimizers``; its optimizer is wrapped
    in the distributed optimizer like the reference wires Horovod into the
    Lightning Trainer (reference: spark/lightning/estimator.py)."""

    def __init__(self, model, feature_cols, label_cols, **kwargs):
        _lightning()  # fail fast with the clear gating error

        def _opt_factory(params):
            del params
            return model.configure_optimizers()

        def _loss(outputs, labels):
            del outputs, labels
            raise NotImplementedError  # training_step computes the loss

        super().__init__(model, _opt_factory, _loss, feature_cols,
                         label_cols, **kwargs)

    def fit(self, df):
        pl = _lightning()
        import torch.utils.data as tud

        import horovod_tpu.torch as hvd_torch

        if not hvd_torch.is_initialized():
            hvd_torch.init()
        X, y = self._materialize(df)
        import torch
        ds = tud.TensorDataset(torch.as_tensor(X), torch.as_tensor(y))
        loader = tud.DataLoader(ds, batch_size=self.batch_size,
                                shuffle=self.shuffle)
        trainer = pl.Trainer(max_epochs=self.epochs, logger=False,
                             enable_checkpointing=False)
        trainer.fit(self.model, loader)
        return TorchModel(self.model, self.feature_cols, self.label_cols,
                          run_id=self.run_id)
