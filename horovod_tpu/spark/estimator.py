"""Estimator layer: fit a flax model on a DataFrame, get back a model for
inference — the Spark ML Estimator workflow.

Reference: horovod/spark/common/estimator.py (fit → Store-backed Parquet →
distributed training → Model for transform) + spark/keras/estimator.py:91.
The data path is pandas/pyarrow Parquet (no petastorm), so the estimator
also works without a Spark cluster; with pyspark installed, Spark DataFrames
are accepted and converted.
"""

import os

import numpy as np

from horovod_tpu.spark.store import LocalStore


def _to_pandas(df):
    if hasattr(df, "toPandas"):  # pyspark DataFrame
        return df.toPandas()
    return df


def features_from_dataframe(pdf, feature_cols):
    """Feature matrix with the estimator family's canonical shape rule: one
    trailing singleton axis from a single vector-valued column is squeezed.
    Used by BOTH fit (via :func:`materialize_dataframe`) and every model's
    ``transform`` so the two always feed the model the same shape."""
    X = np.stack([np.asarray(pdf[c].tolist(), np.float32)
                  for c in feature_cols], axis=-1)
    if X.ndim > 2 and X.shape[-1] == 1:
        X = X[..., 0]
    return X


def materialize_dataframe(store, df, feature_cols, label_cols):
    """DataFrame → Parquet in the store → (X, y) numpy arrays — the shared
    data path of every estimator (the reference writes Parquet for petastorm
    readers; we read it back with pyarrow — same durability contract,
    TPU-friendly dense batches)."""
    pdf = _to_pandas(df)
    path = store.get_train_data_path()
    store.make_dirs(os.path.dirname(path) or ".")
    # Written for durability (resume / remote trainers); the in-memory
    # frame is already the exact data, so no read-back round trip.
    pdf.to_parquet(path + ".parquet")
    X = features_from_dataframe(pdf, feature_cols)
    y = np.stack([np.asarray(pdf[c].tolist()) for c in label_cols], axis=-1)
    if y.shape[-1] == 1:
        y = y[..., 0]
    return X, y


class TpuEstimator:
    """Train a flax model from a DataFrame (reference: KerasEstimator
    spark/keras/estimator.py:91 — params mirrored where meaningful).

    Args:
        model: flax ``nn.Module``.
        optimizer: optax transform (wrapped in DistributedOptimizer inside).
        loss: ``loss(logits, labels) -> scalar``.
        feature_cols / label_cols: DataFrame column names.
        batch_size, epochs: training schedule.
        store: artifact Store (default: LocalStore under ./tpu_estimator).
        run_id: resume a previous run's checkpoint when it exists
            (reference: EstimatorParams._has_checkpoint resume).
    """

    def __init__(self, model, optimizer, loss, feature_cols, label_cols,
                 batch_size=32, epochs=1, store=None, run_id=None,
                 shuffle=True, seed=0, verbose=0):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.store = store or LocalStore("./tpu_estimator")
        self.run_id = run_id
        self.shuffle = shuffle
        self.seed = seed
        self.verbose = verbose

    # -- data -------------------------------------------------------------

    def _materialize(self, df):
        return materialize_dataframe(self.store, df, self.feature_cols,
                                     self.label_cols)

    # -- training ---------------------------------------------------------

    def fit(self, df):
        """Train and return a :class:`TpuModel`
        (reference: estimator.py fit :26)."""
        import jax
        import jax.numpy as jnp

        import horovod_tpu as hvd
        from horovod_tpu.checkpoint import CheckpointManager
        from horovod_tpu.optim import DistributedOptimizer
        from horovod_tpu.parallel import TrainState, make_train_step

        if not hvd.is_initialized():
            hvd.init()
        mesh = hvd.global_process_set.mesh
        n = hvd.size()

        X, y = self._materialize(df)
        run_id = self.run_id or self.store.new_run_id()
        ckpt_dir = self.store.get_checkpoint_path(run_id)
        self.store.make_dirs(ckpt_dir)

        params = self.model.init(jax.random.PRNGKey(self.seed),
                                 jnp.asarray(X[:1]))
        opt = DistributedOptimizer(self.optimizer)
        state = TrainState.create(params, opt)

        mgr = CheckpointManager(os.path.abspath(ckpt_dir))
        if mgr.has_checkpoint():
            state = mgr.restore(template=state, mesh=mesh)

        def loss_fn(params, batch):
            bx, by = batch
            logits = self.model.apply(params, bx)
            return self.loss(logits, by)

        step = make_train_step(loss_fn, opt, mesh)

        # global batches: n shards of batch_size each
        global_bs = self.batch_size * n
        rng = np.random.default_rng(self.seed)
        history = []
        start_step = int(jax.device_get(state.step))
        for epoch in range(self.epochs):
            order = rng.permutation(len(X)) if self.shuffle \
                else np.arange(len(X))
            losses = []
            for i in range(0, len(order) - global_bs + 1, global_bs):
                idx = order[i:i + global_bs]
                state, loss = step(state, (jnp.asarray(X[idx]),
                                           jnp.asarray(y[idx])))
                losses.append(float(jax.device_get(loss)))
            history.append(float(np.mean(losses)) if losses else float("nan"))
            mgr.save(start_step + epoch + 1, state)
        mgr.close()

        return TpuModel(model=self.model, params=state.params,
                        feature_cols=self.feature_cols,
                        label_cols=self.label_cols, run_id=run_id,
                        history=history, store=self.store)


class TpuModel:
    """Trained model returned by fit; ``transform(df)`` appends predictions
    (reference: spark Model.transform → inference UDF,
    spark/common/estimator.py)."""

    def __init__(self, model, params, feature_cols, label_cols, run_id,
                 history, store):
        self.model = model
        self.params = params
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.run_id = run_id
        self.history = history
        self.store = store

    def predict(self, X):
        import jax
        import jax.numpy as jnp
        return np.asarray(jax.jit(self.model.apply)(
            self.params, jnp.asarray(np.asarray(X, np.float32))))

    def transform(self, df):
        pdf = _to_pandas(df).copy()
        X = features_from_dataframe(pdf, self.feature_cols)
        preds = self.predict(X)
        for j, col in enumerate(self.label_cols):
            pdf[f"{col}__output"] = list(
                preds[..., j] if preds.ndim > 1 and
                preds.shape[-1] > j else preds.reshape(len(pdf), -1)[:, 0])
        return pdf
