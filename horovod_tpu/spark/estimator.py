"""Estimator layer: fit a flax model on a DataFrame, get back a model for
inference — the Spark ML Estimator workflow.

Reference: horovod/spark/common/estimator.py (fit → Store-backed Parquet →
distributed training → Model for transform) + spark/keras/estimator.py:91.
The data path is pandas/pyarrow Parquet (no petastorm), so the estimator
also works without a Spark cluster; with pyspark installed, Spark DataFrames
are accepted and converted.
"""


import numpy as np

from horovod_tpu.spark.store import LocalStore


def _to_pandas(df):
    if hasattr(df, "toPandas"):  # pyspark DataFrame
        return df.toPandas()
    return df


def _assemble(cols_to_arrays, cols, dtype=None):
    """Column dict → dense matrix with the estimator family's canonical
    shape rule: one trailing singleton axis from a single vector-valued
    column is squeezed. Shared by fit's streaming batches and every model's
    ``transform`` so both always feed the model the same shape."""
    arrs = []
    for c in cols:
        a = np.asarray(cols_to_arrays[c])
        if a.dtype == object:
            a = np.stack([np.asarray(v) for v in a])
        arrs.append(a.astype(dtype) if dtype is not None else a)
    X = np.stack(arrs, axis=-1)
    if X.ndim > 2 and X.shape[-1] == 1:
        X = X[..., 0]
    return X


def features_from_dataframe(pdf, feature_cols):
    return _assemble({c: pdf[c].tolist() for c in feature_cols},
                     feature_cols, np.float32)


def batch_features_labels(batch, feature_cols, label_cols):
    """One streamed reader batch (column dict) → (X, y)."""
    X = _assemble(batch, feature_cols, np.float32)
    y = _assemble(batch, label_cols)
    if y.ndim > 1 and y.shape[-1] == 1:
        y = y[..., 0]
    return X, y


def write_dataframe_dataset(store, df, path=None):
    """DataFrame → partitioned Parquet dataset in the store; returns the
    dataset path. A Spark DataFrame is written BY THE EXECUTORS
    (``df.write.parquet``) — the driver never materializes it (reference:
    Store-backed Parquet for petastorm readers, store.py:38-540); a pandas
    frame is written in bounded row-group chunks. A string is taken as an
    already-written dataset path (fit directly on existing Parquet)."""
    if isinstance(df, str):
        return df
    path = path or store.get_train_data_path()
    if hasattr(df, "write"):  # pyspark: distributed write, no toPandas
        # Full-URI path (HDFSStore) so executors hit the store's namenode,
        # not fs.defaultFS.
        df.write.mode("overwrite").parquet(path)
        return path
    import pyarrow as pa
    import pyarrow.parquet as pq
    store.delete(path)
    store.make_dirs(path)
    chunk = 65536
    fs = getattr(store, "filesystem", None)
    strip = getattr(store, "strip_uri", lambda p: p)
    for part, s in enumerate(range(0, len(df), chunk)):
        table = pa.Table.from_pandas(df.iloc[s:s + chunk])
        pq.write_table(table, f"{strip(path)}/part-{part:05d}.parquet",
                       filesystem=fs)
    return path


def dataset_reader(store, path, columns, batch_size, shuffle=False, seed=0,
                   drop_last=True):
    """ParquetBatchReader bound to the store's filesystem (URIs stripped to
    the form pyarrow fs handles expect)."""
    from horovod_tpu.data.parquet import ParquetBatchReader
    strip = getattr(store, "strip_uri", lambda p: p)
    return ParquetBatchReader(
        strip(path), columns=list(columns), batch_size=batch_size,
        shuffle=shuffle, seed=seed, drop_last=drop_last,
        filesystem=getattr(store, "filesystem", None))


def materialize_dataframe(store, df, feature_cols, label_cols):
    """DataFrame → Parquet dataset in the store → (X, y) numpy arrays.

    Kept for the small-data estimators (Keras/Torch frontends). A pandas
    frame is used directly after the durability write (no read-back round
    trip); a Spark frame or dataset path is read back through the chunked
    reader so the driver never ``toPandas()``'s it — only the final dense
    (X, y) is driver-resident. For bounded-memory training use
    :class:`TpuEstimator`'s streaming fit."""
    path = write_dataframe_dataset(store, df)
    if not isinstance(df, str) and not hasattr(df, "write"):  # pandas
        X = _assemble({c: df[c].tolist() for c in feature_cols},
                      feature_cols, np.float32)
        y = _assemble({c: df[c].tolist() for c in label_cols}, label_cols)
        if y.ndim > 1 and y.shape[-1] == 1:
            y = y[..., 0]
        return X, y
    reader = dataset_reader(store, path,
                            list(feature_cols) + list(label_cols),
                            batch_size=65536, drop_last=False)
    Xs, ys = [], []
    for batch in reader.batches():
        X, y = batch_features_labels(batch, feature_cols, label_cols)
        Xs.append(X)
        ys.append(y)
    return np.concatenate(Xs), np.concatenate(ys)


class SparkParamsMixin:
    """Spark-ML-style ``getFoo()``/``setFoo(v)`` accessors over plain
    constructor attributes (reference: estimators subclass pyspark
    ``Params`` with per-param getters/setters, spark/common/params.py).
    ``setX`` returns ``self`` for chaining, like pyspark."""

    @staticmethod
    def _camel_to_attr(name):
        import re
        return re.sub("(?<!^)(?=[A-Z])", "_", name).lower()

    def __getattr__(self, name):
        if (name.startswith("get") or name.startswith("set")) \
                and len(name) > 3 and name[3].isupper():
            attr = self._camel_to_attr(name[3:])
            if attr in self.__dict__:
                if name.startswith("get"):
                    return lambda: getattr(self, attr)

                def _setter(value):
                    setattr(self, attr, value)
                    return self

                return _setter
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")


class TpuEstimator(SparkParamsMixin):
    """Train a flax model from a DataFrame (reference: KerasEstimator
    spark/keras/estimator.py:91 — params mirrored where meaningful).

    Args:
        model: flax ``nn.Module``.
        optimizer: optax transform (wrapped in DistributedOptimizer inside).
        loss: ``loss(logits, labels) -> scalar``.
        feature_cols / label_cols: DataFrame column names.
        batch_size, epochs: training schedule.
        store: artifact Store (default: LocalStore under ./tpu_estimator).
        run_id: resume a previous run's checkpoint when it exists
            (reference: EstimatorParams._has_checkpoint resume).
    """

    def __init__(self, model, optimizer, loss, feature_cols, label_cols,
                 batch_size=32, epochs=1, store=None, run_id=None,
                 shuffle=True, seed=0, verbose=0):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.store = store or LocalStore("./tpu_estimator")
        self.run_id = run_id
        self.shuffle = shuffle
        self.seed = seed
        self.verbose = verbose

    # -- data -------------------------------------------------------------

    def _reader(self, path, global_bs):
        return dataset_reader(self.store, path,
                              self.feature_cols + self.label_cols,
                              batch_size=global_bs, shuffle=self.shuffle,
                              seed=self.seed)

    # -- training ---------------------------------------------------------

    def fit(self, df):
        """Train and return a :class:`TpuModel`
        (reference: estimator.py fit :26).

        ``df`` may be a Spark DataFrame (written to Parquet by the
        executors), a pandas DataFrame (written in chunks), or a string
        path to an existing partitioned Parquet dataset. Training streams
        batches through :class:`~horovod_tpu.data.parquet.ParquetBatchReader`
        — the driver never holds the full dataset (the petastorm-reader
        contract, reference: spark/common/store.py:38-540)."""
        import jax
        import jax.numpy as jnp

        import horovod_tpu as hvd
        from horovod_tpu.checkpoint import CheckpointManager
        from horovod_tpu.optim import DistributedOptimizer
        from horovod_tpu.parallel import TrainState, make_train_step

        if not hvd.is_initialized():
            hvd.init()
        mesh = hvd.global_process_set.mesh
        n = hvd.size()

        data_path = write_dataframe_dataset(self.store, df)
        run_id = self.run_id or self.store.new_run_id()

        # global batches: n shards of batch_size each
        global_bs = self.batch_size * n
        reader = self._reader(data_path, global_bs)
        if len(reader) < global_bs:
            raise ValueError(
                f"dataset at {data_path} has fewer than one global batch "
                f"({global_bs} rows)")
        # Shape probe: schema/head only, no buffer read or shuffle.
        X0, _ = batch_features_labels(reader.head(1), self.feature_cols,
                                      self.label_cols)

        params = self.model.init(jax.random.PRNGKey(self.seed),
                                 jnp.asarray(X0[:1]))
        opt = DistributedOptimizer(self.optimizer)
        state = TrainState.create(params, opt)

        # Orbax writes to local disk; a remote store (HDFS) stages through
        # a local dir and syncs per epoch (pull on resume, push after save)
        # — same durability contract as the reference's HDFSStore
        # checkpoints (store.py:402-540).
        from horovod_tpu.spark.store import stage_checkpoints
        local_ckpt, sync_ckpt = stage_checkpoints(self.store, run_id)
        mgr = CheckpointManager(local_ckpt)
        if mgr.has_checkpoint():
            state = mgr.restore(template=state, mesh=mesh)

        def loss_fn(params, batch):
            bx, by = batch
            logits = self.model.apply(params, bx)
            return self.loss(logits, by)

        step = make_train_step(loss_fn, opt, mesh)

        history = []
        start_step = int(jax.device_get(state.step))
        for epoch in range(self.epochs):
            losses = []
            for batch in reader.batches(epoch=epoch):
                bx, by = batch_features_labels(batch, self.feature_cols,
                                               self.label_cols)
                state, loss = step(state, (jnp.asarray(bx),
                                           jnp.asarray(by)))
                losses.append(float(jax.device_get(loss)))
            history.append(float(np.mean(losses)) if losses else float("nan"))
            mgr.save(start_step + epoch + 1, state)
            sync_ckpt()
        mgr.close()

        return TpuModel(model=self.model, params=state.params,
                        feature_cols=self.feature_cols,
                        label_cols=self.label_cols, run_id=run_id,
                        history=history, store=self.store)


class TpuModel:
    """Trained model returned by fit; ``transform(df)`` appends predictions
    (reference: spark Model.transform → inference UDF,
    spark/common/estimator.py)."""

    def __init__(self, model, params, feature_cols, label_cols, run_id,
                 history, store):
        self.model = model
        self.params = params
        self.feature_cols = feature_cols
        self.label_cols = label_cols
        self.run_id = run_id
        self.history = history
        self.store = store

    def predict(self, X):
        import jax
        import jax.numpy as jnp
        return np.asarray(jax.jit(self.model.apply)(
            self.params, jnp.asarray(np.asarray(X, np.float32))))

    def transform(self, df):
        pdf = _to_pandas(df).copy()
        X = features_from_dataframe(pdf, self.feature_cols)
        preds = self.predict(X)
        for j, col in enumerate(self.label_cols):
            pdf[f"{col}__output"] = list(
                preds[..., j] if preds.ndim > 1 and
                preds.shape[-1] > j else preds.reshape(len(pdf), -1)[:, 0])
        return pdf
