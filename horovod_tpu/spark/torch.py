"""TorchEstimator: the Spark-ML-style estimator for PyTorch models.

Reference: horovod/spark/torch/estimator.py:92 (TorchEstimator) — fit(df)
materializes the DataFrame through the Store, trains with the distributed
optimizer wrapper, checkpoints per epoch, and returns a Model whose
``transform`` appends predictions.

The torch training loop runs through this framework's torch frontend
(horovod_tpu/torch): gradients are averaged across ranks by
``hvd.torch.DistributedOptimizer`` exactly as the reference wires
``hvd.DistributedOptimizer`` into the remote trainer
(reference: horovod/spark/torch/remote.py).
"""

import os

import numpy as np

from horovod_tpu.spark.estimator import (SparkParamsMixin,
                                         _to_pandas, features_from_dataframe,
                                         materialize_dataframe)
from horovod_tpu.spark.store import LocalStore


class TorchEstimator(SparkParamsMixin):
    """Train a ``torch.nn.Module`` from a DataFrame
    (reference: spark/torch/estimator.py:92; params mirrored where they are
    meaningful on TPU).

    Args:
        model: torch.nn.Module.
        optimizer: factory ``(params) -> torch.optim.Optimizer`` (a
            constructed optimizer binds to parameters, so a factory is the
            faithful analog of the reference's optimizer re-construction in
            the remote trainer).
        loss: ``loss(outputs, labels) -> scalar tensor``.
        feature_cols / label_cols: DataFrame columns.
        batch_size, epochs, store, run_id: as in TpuEstimator.
    """

    def __init__(self, model, optimizer, loss, feature_cols, label_cols,
                 batch_size=32, epochs=1, store=None, run_id=None,
                 shuffle=True, seed=0, verbose=0, backward_passes_per_step=1):
        self.model = model
        self.optimizer_factory = optimizer
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.store = store or LocalStore("./tpu_estimator")
        self.run_id = run_id
        self.shuffle = shuffle
        self.seed = seed
        self.verbose = verbose
        self.backward_passes_per_step = backward_passes_per_step

    def _materialize(self, df):
        return materialize_dataframe(self.store, df, self.feature_cols,
                                     self.label_cols)

    def fit(self, df):
        import torch

        import horovod_tpu.torch as hvd_torch
        from horovod_tpu.torch.optimizer import DistributedOptimizer

        if not hvd_torch.is_initialized():
            hvd_torch.init()

        X, y = self._materialize(df)
        run_id = self.run_id or self.store.new_run_id()
        # Local staging (remote stores pull existing checkpoints first and
        # push after each save): torch.load/save only touch local paths.
        from horovod_tpu.spark.store import stage_checkpoints
        local_dir, sync_ckpt = stage_checkpoints(self.store, run_id)
        ckpt_file = os.path.join(local_dir, "model.pt")

        model = self.model
        opt = DistributedOptimizer(
            self.optimizer_factory(model.parameters()),
            named_parameters=model.named_parameters(),
            backward_passes_per_step=self.backward_passes_per_step)

        start_epoch = 0
        if os.path.exists(ckpt_file):  # resume (reference: _has_checkpoint)
            ckpt = torch.load(ckpt_file, weights_only=False)
            model.load_state_dict(ckpt["model"])
            start_epoch = ckpt.get("epoch", 0)

        # Parameter broadcast from rank 0 (reference: remote.py broadcasts
        # model state before training).
        hvd_torch.broadcast_parameters(model.state_dict(), root_rank=0)

        rng = np.random.default_rng(self.seed)
        history = []
        xt = torch.as_tensor(X)
        yt = torch.as_tensor(y)
        for epoch in range(start_epoch, self.epochs):
            order = rng.permutation(len(X)) if self.shuffle \
                else np.arange(len(X))
            losses = []
            for s in range(0, len(order) - self.batch_size + 1,
                           self.batch_size):
                idx = order[s:s + self.batch_size]
                opt.zero_grad()
                out = model(xt[idx])
                loss = self.loss(out, yt[idx])
                loss.backward()
                opt.step()
                losses.append(float(loss.detach()))
            epoch_loss = float(np.mean(losses)) if losses else float("nan")
            history.append(epoch_loss)
            torch.save({"model": model.state_dict(), "epoch": epoch + 1},
                       ckpt_file)
            sync_ckpt()
            if self.verbose:
                print(f"[TorchEstimator] epoch {epoch}: loss={epoch_loss}")
        return TorchModel(model, self.feature_cols, self.label_cols,
                          history=history, run_id=run_id)


class TorchModel:
    """Inference-side result of ``TorchEstimator.fit`` (reference:
    spark/torch/estimator.py TorchModel → transform appends predictions)."""

    def __init__(self, model, feature_cols, label_cols, history=None,
                 run_id=None):
        self.model = model
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.history = history or []
        self.run_id = run_id

    def transform(self, df):
        import torch

        pdf = _to_pandas(df).copy()
        X = features_from_dataframe(pdf, self.feature_cols)
        with torch.no_grad():
            out = self.model(torch.as_tensor(X)).numpy()
        out = np.asarray(out)
        if out.ndim == 1:
            out = out[:, None]
        if out.shape[1] != len(self.label_cols):
            raise ValueError(
                f"model produced {out.shape[1]} output column(s) but "
                f"{len(self.label_cols)} label_cols were requested: "
                f"{self.label_cols}")
        for i, c in enumerate(self.label_cols):
            pdf[f"{c}__output"] = list(out[:, i])
        return pdf
