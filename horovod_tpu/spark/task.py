"""Rank assignment from Spark task placement — pure logic, no pyspark.

Reference: horovod/spark/runner.py:161-198 — the driver collects each task's
(partition index, host), then assigns Horovod ranks host-major so local
ranks are contiguous on a host, mirroring hosts.py get_host_assignments.
"""

import collections


def assign_ranks(task_hosts):
    """``task_hosts``: list of (task_index, host). Returns
    {task_index: dict(rank, local_rank, cross_rank, size, local_size,
    cross_size)}.

    Host order follows first appearance (by lowest task index); within a
    host, tasks are ordered by task index — deterministic and stable across
    retries, like the reference's sorted registration order.
    """
    by_host = collections.OrderedDict()
    for idx, host in sorted(task_hosts):
        by_host.setdefault(host, []).append(idx)

    size = len(task_hosts)
    cross_size = len(by_host)
    local_sizes = {h: len(idxs) for h, idxs in by_host.items()}

    out = {}
    rank = 0
    for cross_rank, (host, idxs) in enumerate(by_host.items()):
        for local_rank, idx in enumerate(idxs):
            out[idx] = dict(rank=rank, local_rank=local_rank,
                            cross_rank=cross_rank, size=size,
                            local_size=local_sizes[host],
                            cross_size=cross_size, host=host)
            rank += 1
    return out
