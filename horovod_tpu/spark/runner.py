"""Run horovod_tpu training as a Spark job.

Reference: horovod/spark/runner.py (run :200 — parallelize num_proc tasks,
collect (partition, host) registrations, assign ranks, execute the pickled
function on every task, gather per-rank results).
"""

import importlib.util
import os
import socket

import cloudpickle

from horovod_tpu.spark.task import assign_ranks


def spark_available():
    return importlib.util.find_spec("pyspark") is not None


def run(fn, args=(), kwargs=None, num_proc=None, extra_env=None,
        verbose=True):
    """Run ``fn`` on ``num_proc`` Spark tasks with horovod_tpu env wired;
    returns the list of per-rank results (reference: spark/runner.py:200-310).

    Requires an active SparkSession (pyspark). Each task is one worker
    process owning its executor-local chips.
    """
    if not spark_available():
        raise RuntimeError(
            "horovod_tpu.spark.run requires pyspark; install it or use "
            "horovod_tpu.run / hvdrun directly")
    from pyspark.sql import SparkSession

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    num_proc = num_proc or max(sc.defaultParallelism, 1)
    kwargs = dict(kwargs or {})

    driver_addr = socket.gethostbyname(socket.gethostname())
    from horovod_tpu.runner.http_kv import KVStoreServer
    from horovod_tpu.runner.secret import SECRET_ENV, make_secret_key
    os.environ.setdefault(SECRET_ENV, make_secret_key())
    kv = KVStoreServer()
    kv_port = kv.start()
    coordinator_port = _free_port()
    payload = cloudpickle.dumps((fn, tuple(args), kwargs))
    secret_key = os.environ.get(SECRET_ENV)
    base_env = dict(extra_env or {})

    def _task(_it):
        # Placement discovery and execution MUST happen inside the same
        # Spark job: scheduling a second job can place partitions on
        # different hosts, leaving env ranks that contradict physical
        # placement. Barrier mode runs all tasks concurrently (like the
        # reference's long-running task services, spark/runner.py:49-130)
        # and allGather gives every task the full (partition, host) map.
        import json as _json
        from pyspark import BarrierTaskContext
        ctx = BarrierTaskContext.get()
        idx = ctx.partitionId()
        gathered = ctx.allGather(
            _json.dumps([idx, socket.gethostname()]))
        placement = [tuple(_json.loads(s)) for s in gathered]
        info = assign_ranks(placement)[idx]
        env = dict(base_env)
        env.update({
            "HOROVOD_RANK": str(info["rank"]),
            "HOROVOD_LOCAL_RANK": str(info["local_rank"]),
            "HOROVOD_CROSS_RANK": str(info["cross_rank"]),
            "HOROVOD_SIZE": str(info["size"]),
            "HOROVOD_LOCAL_SIZE": str(info["local_size"]),
            "HOROVOD_CROSS_SIZE": str(info["cross_size"]),
            "HOROVOD_COORDINATOR_ADDR": driver_addr,
            "HOROVOD_COORDINATOR_PORT": str(coordinator_port),
            "HOROVOD_KV_ADDR": driver_addr,
            "HOROVOD_KV_PORT": str(kv_port),
        })
        if secret_key:
            env[SECRET_ENV] = secret_key
        os.environ.update(env)
        f, a, kw = cloudpickle.loads(payload)
        yield (info["rank"], f(*a, **kw))

    try:
        results = sc.parallelize(range(num_proc), num_proc) \
            .barrier().mapPartitions(_task).collect()
    finally:
        kv.stop()
    return [r for _, r in sorted(results)]


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def run_elastic(fn, args=(), kwargs=None, num_proc=None, min_np=1,
                max_np=None, reset_limit=3, extra_env=None, verbose=True):
    """Elastic run over Spark (reference: spark/runner.py:312 run_elastic).

    Spark owns task placement, so elasticity is job-level here: on worker
    failure the barrier job is retried with whatever parallelism the cluster
    currently offers, clamped to ``[min_np, max_np]``, up to ``reset_limit``
    resets — the role the reference's elastic driver plays over its
    long-running Spark task services. ``fn`` should follow the elastic
    contract (durable checkpoints / TpuState) so retries resume rather than
    restart.
    """
    if not spark_available():
        raise RuntimeError(
            "horovod_tpu.spark.run_elastic requires pyspark; install it or "
            "use horovod_tpu.runner.api.run_elastic directly")
    from pyspark.sql import SparkSession

    sc = SparkSession.builder.getOrCreate().sparkContext
    resets = 0
    last_err = None
    # reset_limit=None means unlimited, matching runner.api.run_elastic and
    # the elastic driver.
    while reset_limit is None or resets <= reset_limit:
        avail = num_proc or max(sc.defaultParallelism, 1)
        np_now = max(min_np, min(avail, max_np or avail))
        try:
            return run(fn, args=args, kwargs=kwargs, num_proc=np_now,
                       extra_env=extra_env, verbose=verbose)
        except Exception as e:
            # Only Spark/Py4J job failures are transient (executor loss,
            # stage abort); deterministic user-code errors fail fast rather
            # than re-running the whole job reset_limit times.
            if not _is_spark_failure(e):
                raise
            last_err = e
            resets += 1
    raise RuntimeError(
        f"spark elastic run failed after {resets} resets") from last_err


def _is_spark_failure(e):
    """True only for cluster-side failures worth an elastic reset (executor
    loss, preemption, barrier desync). Deterministic user-code errors — which
    Spark also surfaces as Py4JJavaError stage failures, with the Python
    traceback embedded — fail fast instead of burning reset_limit re-runs."""
    text = f"{type(e).__name__}: {e}"
    transient = ("ExecutorLostFailure", "Executor lost", "TaskKilled",
                 "task preempted", "Connection reset", "Connection refused",
                 "SparkContext was shut down", "BarrierJobSlotsNumberCheck",
                 "Could not recover from a failed barrier")
    if any(s in text for s in transient):
        return True
    # A stage failure carrying a Python traceback is user code raising
    # deterministically on the worker — not retryable.
    if "Traceback (most recent call last)" in text:
        return False
    mod = type(e).__module__ or ""
    # Remaining py4j/pyspark-native failures without an embedded user error
    # (driver/JVM-side flakiness) stay retryable.
    return mod.startswith("py4j") or mod.startswith("pyspark")
