"""KerasEstimator: the Spark-ML-style estimator for Keras models.

Reference: horovod/spark/keras/estimator.py:91 (KerasEstimator → Store-backed
Parquet → remote Keras training with hvd.DistributedOptimizer + callbacks →
KerasModel for transform).

Gated on a Keras/TensorFlow install (not part of the baked TPU image): the
class is always importable for API parity, and raises a clear error at
``fit`` time when Keras is unavailable — the same pattern the reference uses
for optional framework support.
"""

import json
import os

import numpy as np

from horovod_tpu.spark.estimator import (SparkParamsMixin, _to_pandas,
                                         features_from_dataframe,
                                         materialize_dataframe)
from horovod_tpu.spark.store import LocalStore


def _keras():
    try:
        import keras
        return keras
    except ImportError:
        try:
            from tensorflow import keras
            return keras
        except ImportError as e:
            raise ImportError(
                "KerasEstimator requires keras (or tensorflow.keras); this "
                "image ships neither — use TpuEstimator (flax) or "
                "TorchEstimator instead") from e


class KerasEstimator(SparkParamsMixin):
    """Train a compiled-or-compilable Keras model from a DataFrame
    (reference: spark/keras/estimator.py:91)."""

    def __init__(self, model, optimizer, loss, feature_cols, label_cols,
                 batch_size=32, epochs=1, store=None, run_id=None,
                 shuffle=True, seed=0, verbose=0, custom_objects=None,
                 checkpoint_callback=None, backend_env=None,
                 data_module=None):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.store = store or LocalStore("./tpu_estimator")
        self.run_id = run_id
        self.shuffle = shuffle
        self.seed = seed
        self.verbose = verbose
        # reference-parity params (spark/keras/estimator.py:91 Params)
        self.custom_objects = custom_objects
        self.checkpoint_callback = checkpoint_callback
        self.backend_env = dict(backend_env or {})
        self.data_module = data_module

    def fit(self, df):
        keras = _keras()
        import horovod_tpu.keras as hvd_keras

        if not hvd_keras.is_initialized():
            hvd_keras.init()

        X, y = materialize_dataframe(self.store, df, self.feature_cols,
                                     self.label_cols)

        run_id = self.run_id or self.store.new_run_id()
        # Local staging (remote stores pull existing checkpoints first and
        # push after save): model.save/open only ever touch local paths.
        from horovod_tpu.spark.store import stage_checkpoints
        local_dir, sync_ckpt = stage_checkpoints(self.store, run_id)
        ckpt_file = os.path.join(local_dir, "model.keras")
        meta_file = os.path.join(local_dir, "fit_state.json")

        model = self.model
        initial_epoch = 0
        if os.path.exists(ckpt_file):  # resume: train only remaining epochs
            model = hvd_keras.load_model(ckpt_file)
            if os.path.exists(meta_file):
                with open(meta_file) as f:
                    initial_epoch = int(json.load(f).get("epoch", 0))
        else:
            opt = hvd_keras.DistributedOptimizer(self.optimizer)
            model.compile(optimizer=opt, loss=self.loss)

        callbacks = [
            hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0),
            hvd_keras.callbacks.MetricAverageCallback(),
        ]
        history = model.fit(X, y, batch_size=self.batch_size,
                            epochs=self.epochs, shuffle=self.shuffle,
                            initial_epoch=initial_epoch,
                            verbose=self.verbose, callbacks=callbacks)
        model.save(ckpt_file)
        with open(meta_file, "w") as f:
            json.dump({"epoch": self.epochs}, f)
        sync_ckpt()
        return KerasModel(model, self.feature_cols, self.label_cols,
                          history=history.history, run_id=run_id)


class KerasModel:
    """Result of ``KerasEstimator.fit`` (reference: KerasModel.transform)."""

    def __init__(self, model, feature_cols, label_cols, history=None,
                 run_id=None):
        self.model = model
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.history = history or {}
        self.run_id = run_id

    def transform(self, df):
        pdf = _to_pandas(df).copy()
        X = features_from_dataframe(pdf, self.feature_cols)
        out = np.asarray(self.model.predict(X, verbose=0))
        if out.ndim == 1:
            out = out[:, None]
        if out.shape[1] != len(self.label_cols):
            raise ValueError(
                f"model produced {out.shape[1]} output column(s) but "
                f"{len(self.label_cols)} label_cols were requested: "
                f"{self.label_cols}")
        for i, c in enumerate(self.label_cols):
            pdf[f"{c}__output"] = list(out[:, i])
        return pdf
