"""Training artifact stores (reference: horovod/spark/common/store.py:38-540:
Store/LocalStore/HDFSStore/DBFSLocalStore — per-run directories for training
data, checkpoints, and logs, plus (de)serialization helpers)."""

import os
import shutil
import uuid


class Store:
    """Abstract per-run artifact layout."""

    def get_train_data_path(self, idx=None):
        raise NotImplementedError

    def get_val_data_path(self, idx=None):
        raise NotImplementedError

    def get_checkpoint_path(self, run_id):
        raise NotImplementedError

    def get_logs_path(self, run_id):
        raise NotImplementedError

    def exists(self, path):
        raise NotImplementedError

    def new_run_id(self):
        return f"run_{uuid.uuid4().hex[:12]}"

    @staticmethod
    def create(prefix_path, **kwargs):
        """Factory mirroring Store.create (reference: store.py:84-96):
        hdfs:// → :class:`HDFSStore`, dbfs:/ → :class:`DBFSLocalStore`,
        anything else → :class:`LocalStore`."""
        if HDFSStore.matches(prefix_path):
            return HDFSStore(prefix_path, **kwargs)
        if DBFSLocalStore.matches(prefix_path):
            return DBFSLocalStore(prefix_path, **kwargs)
        return LocalStore(prefix_path, **kwargs)


class FilesystemStore(Store):
    """Store on a (possibly network-mounted) filesystem path
    (reference: FilesystemStore store.py:110-320)."""

    def __init__(self, prefix_path, train_path=None, val_path=None,
                 checkpoint_path=None, logs_path=None):
        self.prefix_path = prefix_path
        self._train_path = train_path or os.path.join(
            prefix_path, "intermediate_train_data")
        self._val_path = val_path or os.path.join(
            prefix_path, "intermediate_val_data")
        self._checkpoint_base = checkpoint_path or os.path.join(
            prefix_path, "checkpoints")
        self._logs_base = logs_path or os.path.join(prefix_path, "logs")
        # Created lazily (make_dirs at first write): merely CONSTRUCTING an
        # estimator with the default store must not litter the CWD.

    def get_train_data_path(self, idx=None):
        return self._train_path if idx is None else \
            f"{self._train_path}.{idx}"

    def get_val_data_path(self, idx=None):
        return self._val_path if idx is None else f"{self._val_path}.{idx}"

    def get_checkpoint_path(self, run_id):
        return os.path.join(self._checkpoint_base, run_id)

    def get_logs_path(self, run_id):
        return os.path.join(self._logs_base, run_id)

    def exists(self, path):
        return os.path.exists(path)

    def make_dirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.unlink(path)


    @property
    def filesystem(self):
        """``pyarrow.fs.FileSystem`` for dataset readers
        (:class:`horovod_tpu.data.parquet.ParquetBatchReader`)."""
        from pyarrow import fs
        return fs.LocalFileSystem()

    @property
    def is_local(self):
        """Whether paths are directly usable with local-filesystem APIs
        (os/open/orbax). Remote stores stage through a local dir instead."""
        return True


class LocalStore(FilesystemStore):
    """Local-disk store (reference: LocalStore store.py:322-360)."""


class DBFSLocalStore(FilesystemStore):
    """Databricks DBFS store through the local FUSE mount: ``dbfs:/path``
    resolves to ``/dbfs/path`` (reference: DBFSLocalStore store.py:362-400)."""

    @classmethod
    def matches(cls, path):
        return path.startswith("dbfs:/") or path.startswith("/dbfs")

    def __init__(self, prefix_path, **kwargs):
        if prefix_path.startswith("dbfs:/"):
            prefix_path = "/dbfs/" + prefix_path[len("dbfs:/"):].lstrip("/")
        super().__init__(prefix_path, **kwargs)


class HDFSStore(Store):
    """HDFS-backed store via ``pyarrow.fs.HadoopFileSystem`` (reference:
    HDFSStore store.py:402-540 — per-run train/val/checkpoint/log dirs on
    HDFS, no driver-side materialization: Spark executors write the Parquet,
    workers stream it back through the same filesystem handle).

    Requires libhdfs (``ARROW_LIBHDFS_DIR``)/a Hadoop client on the
    machine; constructing the store without one raises pyarrow's error.
    """

    FS_PREFIX = "hdfs://"

    @classmethod
    def matches(cls, path):
        return path.startswith(cls.FS_PREFIX)

    def __init__(self, prefix_path, host=None, port=None, user=None,
                 kerb_ticket=None):
        rest = prefix_path[len(self.FS_PREFIX):] \
            if prefix_path.startswith(self.FS_PREFIX) else prefix_path
        netloc, _, self._path = rest.partition("/")
        self._path = "/" + self._path
        if netloc and host is None:
            host, _, p = netloc.partition(":")
            port = int(p) if p else port
        from pyarrow import fs
        self._fs = fs.HadoopFileSystem(
            host=host or "default", port=port or 0, user=user,
            kerb_ticket=kerb_ticket)
        self._netloc = netloc
        self.prefix_path = prefix_path
        self._train_path = self._join("intermediate_train_data")
        self._val_path = self._join("intermediate_val_data")

    def _join(self, *parts):
        # Full URIs (authority included) so consumers that resolve paths
        # through THEIR OWN filesystem config — Spark's df.write.parquet,
        # pyarrow URI inference — land on this store's namenode, not
        # whatever fs.defaultFS happens to be.
        return f"{self.FS_PREFIX}{self._netloc}" + "/".join(
            [self._path.rstrip("/")] + list(parts))

    def strip_uri(self, path):
        """hdfs://netloc/p -> /p (the form pyarrow fs handles expect)."""
        if path.startswith(self.FS_PREFIX):
            rest = path[len(self.FS_PREFIX):]
            return "/" + rest.partition("/")[2]
        return path

    @property
    def filesystem(self):
        return self._fs

    @property
    def is_local(self):
        return False

    def get_train_data_path(self, idx=None):
        return self._train_path if idx is None else \
            f"{self._train_path}.{idx}"

    def get_val_data_path(self, idx=None):
        return self._val_path if idx is None else f"{self._val_path}.{idx}"

    def get_checkpoint_path(self, run_id):
        return self._join("checkpoints", run_id)

    def get_logs_path(self, run_id):
        return self._join("logs", run_id)

    def exists(self, path):
        from pyarrow import fs
        return self._fs.get_file_info(
            self.strip_uri(path)).type != fs.FileType.NotFound

    def make_dirs(self, path):
        self._fs.create_dir(self.strip_uri(path), recursive=True)

    def delete(self, path):
        from pyarrow import fs
        path = self.strip_uri(path)
        info = self._fs.get_file_info(path)
        if info.type == fs.FileType.Directory:
            self._fs.delete_dir(path)
        elif info.type != fs.FileType.NotFound:
            self._fs.delete_file(path)

    def download_dir(self, remote_path, local_path):
        """Copy a store directory tree to local disk (checkpoint pull)."""
        from pyarrow import fs
        fs.copy_files(self.strip_uri(remote_path), local_path,
                      source_filesystem=self._fs,
                      destination_filesystem=fs.LocalFileSystem())

    def upload_dir(self, local_path, remote_path):
        """Copy a local directory tree into the store (checkpoint push)."""
        from pyarrow import fs
        self.make_dirs(remote_path)
        fs.copy_files(local_path, self.strip_uri(remote_path),
                      source_filesystem=fs.LocalFileSystem(),
                      destination_filesystem=self._fs)


def split_protocol(path):
    """Split ``"hdfs://host/p"`` → ``("hdfs", "host/p")``; bare paths give a
    ``None`` protocol (reference: fsspec.core.split_protocol, used throughout
    store.py)."""
    if "://" in path:
        protocol, rest = path.split("://", 1)
        return protocol, rest
    return None, path


def is_databricks():
    """True inside a Databricks runtime (reference:
    spark/common/util.py is_databricks — env probe)."""
    return "DATABRICKS_RUNTIME_VERSION" in os.environ


def host_hash():
    """Stable per-host identifier used to key per-host artifact caches
    (reference: spark/common/util.py host_hash via runner host_hash)."""
    import hashlib
    import socket
    return hashlib.md5(socket.gethostname().encode()).hexdigest()[:12]


# Reference-parity alias: the reference renamed its filesystem base class.
AbstractFilesystemStore = FilesystemStore


def stage_checkpoints(store, run_id):
    """Local checkpoint staging for a run: returns ``(local_dir, sync)``.

    Estimators do file I/O (orbax, model.save, torch.save) against LOCAL
    paths only; for a remote store (HDFS/DBFS) this stages through a temp
    dir — existing remote checkpoints are pulled down first (the remote dir
    is the source of truth: a stale local leftover from an earlier crash
    must never shadow, then clobber, newer remote state) and ``sync()``
    pushes the dir back after each save. For local stores ``sync`` is a
    no-op and the store path is used directly. Reference durability
    contract: store.py:402-540 HDFSStore checkpoints.
    """
    import tempfile

    ckpt_dir = store.get_checkpoint_path(run_id)
    store.make_dirs(ckpt_dir)
    if getattr(store, "is_local", True):
        return os.path.abspath(ckpt_dir), (lambda: None)
    local = os.path.join(tempfile.gettempdir(), f"hvd_est_ckpt_{run_id}")
    if os.path.isdir(local):
        shutil.rmtree(local)
    os.makedirs(local, exist_ok=True)
    if store.exists(ckpt_dir):
        store.download_dir(ckpt_dir, local)
    return local, (lambda: store.upload_dir(local, ckpt_dir))
