"""Training artifact stores (reference: horovod/spark/common/store.py:38-540:
Store/LocalStore/HDFSStore/DBFSLocalStore — per-run directories for training
data, checkpoints, and logs, plus (de)serialization helpers)."""

import os
import shutil
import uuid


class Store:
    """Abstract per-run artifact layout."""

    def get_train_data_path(self, idx=None):
        raise NotImplementedError

    def get_val_data_path(self, idx=None):
        raise NotImplementedError

    def get_checkpoint_path(self, run_id):
        raise NotImplementedError

    def get_logs_path(self, run_id):
        raise NotImplementedError

    def exists(self, path):
        raise NotImplementedError

    def new_run_id(self):
        return f"run_{uuid.uuid4().hex[:12]}"

    @staticmethod
    def create(prefix_path):
        """Factory mirroring Store.create (reference: store.py:84-96) —
        filesystem paths only; hdfs:// and dbfs:/ need their own client and
        raise a clear error here."""
        if prefix_path.startswith(("hdfs://", "dbfs:/")):
            raise ValueError(
                f"{prefix_path}: remote stores require the corresponding "
                "filesystem client; mount the path locally or subclass "
                "FilesystemStore")
        return LocalStore(prefix_path)


class FilesystemStore(Store):
    """Store on a (possibly network-mounted) filesystem path
    (reference: FilesystemStore store.py:110-320)."""

    def __init__(self, prefix_path, train_path=None, val_path=None,
                 checkpoint_path=None, logs_path=None):
        self.prefix_path = prefix_path
        self._train_path = train_path or os.path.join(
            prefix_path, "intermediate_train_data")
        self._val_path = val_path or os.path.join(
            prefix_path, "intermediate_val_data")
        self._checkpoint_base = checkpoint_path or os.path.join(
            prefix_path, "checkpoints")
        self._logs_base = logs_path or os.path.join(prefix_path, "logs")
        os.makedirs(prefix_path, exist_ok=True)

    def get_train_data_path(self, idx=None):
        return self._train_path if idx is None else \
            f"{self._train_path}.{idx}"

    def get_val_data_path(self, idx=None):
        return self._val_path if idx is None else f"{self._val_path}.{idx}"

    def get_checkpoint_path(self, run_id):
        return os.path.join(self._checkpoint_base, run_id)

    def get_logs_path(self, run_id):
        return os.path.join(self._logs_base, run_id)

    def exists(self, path):
        return os.path.exists(path)

    def make_dirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.unlink(path)


class LocalStore(FilesystemStore):
    """Local-disk store (reference: LocalStore store.py:322-360)."""
