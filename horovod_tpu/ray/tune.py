"""Ray Tune integration: distributed trials over TPU hosts.

The reference documents pairing Horovod with Ray Tune through a
"distributed trainable" — each Tune trial is itself a multi-worker
training job (reference: docs/hyperparameter_search.rst; the creator
function itself ships in Ray, ``ray.tune.integration.horovod``). This is
the TPU-native analog built on :class:`horovod_tpu.ray.RayExecutor`: one
trial = one executor fan-out, with the trial config forwarded to every
worker.

Gated like the rest of the package: importing works without ray,
constructing a trainable requires it.
"""

from horovod_tpu.ray.strategy import ray_available


def tune_trainable(train_fn, num_workers=1, num_hosts=None,
                   num_workers_per_host=None, cpus_per_worker=1,
                   tpus_per_worker=0, executor_env=None):
    """Wrap ``train_fn(config) -> result`` as a Ray Tune trainable whose
    every trial runs ``train_fn`` across a :class:`RayExecutor` fan-out.

    ``train_fn`` runs on EVERY worker of the trial with the trial's
    ``config`` dict; call :func:`horovod_tpu.init` inside as usual. The
    rank-0 return value is reported to Tune as the trial result (dict
    results are reported as-is; other values under ``{"result": ...}``).

    Use Tune's ``tune.with_resources``/``PlacementGroupFactory`` knobs for
    scheduling beyond the executor's own placement. Reference shape:
    ``DistributedTrainableCreator(fn, num_slots=...)``
    (docs/hyperparameter_search.rst).
    """
    if not ray_available():
        raise RuntimeError(
            "horovod_tpu.ray.tune requires ray; pip install 'ray[tune]'")
    from horovod_tpu.ray import RayExecutor

    def trainable(config):
        executor = RayExecutor(
            # exactly one of num_workers / num_hosts may be set
            # (placement_bundles validates)
            num_workers=None if num_hosts is not None else num_workers,
            num_hosts=num_hosts,
            num_workers_per_host=num_workers_per_host or 1,
            cpus_per_worker=cpus_per_worker,
            tpus_per_worker=tpus_per_worker, env_vars=executor_env)
        try:
            # start() inside the try: a partially-started executor (e.g.
            # placement-group timeout) must still release its placement
            # group / KV server, or failing trials leak cluster resources.
            executor.start()
            results = executor.run(train_fn, args=(config,))
        finally:
            executor.shutdown()
        out = results[0]
        return out if isinstance(out, dict) else {"result": out}

    trainable.__name__ = getattr(train_fn, "__name__", "hvd_trainable")
    return trainable
