"""Elastic horovod_tpu on Ray.

Reference: horovod/ray/elastic_v2.py — ``RayHostDiscovery`` (:40-72) derives
the available hosts/slots from live Ray cluster state, plugged into the
elastic driver in place of a discovery script; the elastic adapter then
spawns/retires workers as nodes come and go.
"""

from horovod_tpu.runner.elastic.discovery import HostDiscovery


class RayHostDiscovery(HostDiscovery):
    """Host discovery over ``ray.nodes()``
    (reference: elastic_v2.py:40-72 RayHostDiscovery).

    Args:
        use_tpu: count ``TPU`` resources as slots (else CPUs).
        cpus_per_slot / tpus_per_slot: resource units consumed per worker
            slot on a host.
    """

    def __init__(self, use_tpu=False, cpus_per_slot=1, tpus_per_slot=1):
        self.use_tpu = use_tpu
        self.cpus_per_slot = max(1, int(cpus_per_slot))
        self.tpus_per_slot = max(1, int(tpus_per_slot))

    def find_available_hosts_and_slots(self):
        import ray

        hosts = {}
        for node in ray.nodes():
            if not node.get("Alive", False):
                continue
            resources = node.get("Resources", {}) or {}
            hostname = node.get("NodeManagerHostname") \
                or node.get("NodeManagerAddress")
            if not hostname:
                continue
            if self.use_tpu:
                slots = int(resources.get("TPU", 0)) // self.tpus_per_slot
            else:
                slots = int(resources.get("CPU", 0)) // self.cpus_per_slot
            if slots > 0:
                hosts[hostname] = slots
        return hosts


def run_elastic(fn, args=(), kwargs=None, min_np=1, max_np=None,
                reset_limit=None, use_tpu=False, cpus_per_slot=1,
                tpus_per_slot=1, env_vars=None, start_timeout=600):
    """Run an elastic job with hosts discovered from the Ray cluster
    (reference: horovod/ray/elastic.py run_elastic / ElasticRayExecutor).

    ``fn`` should follow the elastic contract (horovod_tpu.elastic.TpuState
    commit/restore); workers are (re)launched over ssh onto whatever nodes
    Ray reports alive, via the same elastic driver the CLI uses.
    ``env_vars`` are forwarded into every worker's environment.
    """
    from horovod_tpu.runner import launch as launch_mod
    from horovod_tpu.runner.api import (_TASK_CMD, _elastic_harvester,
                                        _validate_elastic_results)
    from horovod_tpu.runner.elastic.driver import run_elastic_driver
    import cloudpickle

    discovery = RayHostDiscovery(use_tpu=use_tpu,
                                 cpus_per_slot=cpus_per_slot,
                                 tpus_per_slot=tpus_per_slot)

    argv = ["--min-np", str(min_np)]
    if max_np:
        argv += ["--max-np", str(max_np)]
    if reset_limit is not None:
        argv += ["--reset-limit", str(reset_limit)]
    argv += ["--start-timeout", str(start_timeout)]
    # The driver requires a discovery source; pass a placeholder script and
    # substitute the Ray discovery object below.
    argv += ["--host-discovery-script", "ray://cluster"]
    argv += _TASK_CMD
    parsed = launch_mod.parse_args(argv)

    payload = cloudpickle.dumps((fn, tuple(args), dict(kwargs or {})))
    harvested = {}
    expected = {}
    rc = run_elastic_driver(
        parsed, harvest=_elastic_harvester(harvested, expected),
        kv_preload={("func", "pickle"): payload},
        discovery_override=discovery, extra_env=dict(env_vars or {}))
    if rc != 0:
        raise RuntimeError(f"ray elastic run failed with exit code {rc}")
    return _validate_elastic_results(harvested, expected)


class ElasticRayExecutor:
    """Executor-object API over :func:`run_elastic` (reference:
    horovod/ray/elastic.py ElasticRayExecutor:150 — create_settings /
    start / run lifecycle). Kept for source compatibility with reference
    scripts; new code can call :func:`run_elastic` directly.
    """

    @staticmethod
    def create_settings(min_num_proc=1, max_num_proc=None, reset_limit=None,
                        elastic_timeout=600, timeout_s=30,
                        ssh_identity_file=None, nics=None, min_np=None,
                        max_np=None, **kwargs):
        """Build the settings dict consumed by __init__ (reference:
        elastic.py:188-246; min_np/max_np are the deprecated spellings)."""
        import warnings
        if min_np is not None:
            min_num_proc = min_np
            warnings.warn("min_np is deprecated, use min_num_proc",
                          DeprecationWarning)
        if max_np is not None:
            max_num_proc = max_np
            warnings.warn("max_np is deprecated, use max_num_proc",
                          DeprecationWarning)
        return {"min_np": min_num_proc, "max_np": max_num_proc,
                "reset_limit": reset_limit,
                "start_timeout": elastic_timeout}

    def __init__(self, settings, use_gpu=False, use_tpu=None,
                 cpus_per_slot=1, gpus_per_slot=None, tpus_per_slot=1,
                 env_vars=None, override_discovery=True):
        if use_tpu is None:
            # reference scripts say use_gpu; on this build that means the
            # accelerator resource, i.e. TPU slots.
            use_tpu = use_gpu
        self._settings = dict(settings)
        self._use_tpu = use_tpu
        self._cpus_per_slot = cpus_per_slot
        self._tpus_per_slot = tpus_per_slot or gpus_per_slot or 1
        self._env_vars = dict(env_vars or {})
        self._started = False

    def start(self):
        """Validate Ray is up (workers spawn lazily inside :meth:`run`)."""
        from horovod_tpu.ray.strategy import ray_available
        if not ray_available():
            raise RuntimeError("ray is not initialized; call ray.init()")
        self._started = True

    def run(self, worker_fn, callbacks=None):
        """Run ``worker_fn`` elastically; returns per-rank results
        (reference: elastic.py:320-360). ``callbacks`` accepted for API
        compatibility and invoked with the result list."""
        if not self._started:
            self.start()
        results = run_elastic(
            worker_fn, min_np=self._settings.get("min_np", 1),
            max_np=self._settings.get("max_np"),
            reset_limit=self._settings.get("reset_limit"),
            use_tpu=self._use_tpu, cpus_per_slot=self._cpus_per_slot,
            tpus_per_slot=self._tpus_per_slot, env_vars=self._env_vars,
            start_timeout=self._settings.get("start_timeout", 600))
        for cb in (callbacks or []):
            cb(results)
        return results
