"""Placement and env-contract logic for the Ray executor — pure functions,
unit-testable without a ray installation.

Reference: horovod/ray/strategy.py (ColocatedStrategy/PGStrategy bundle
construction) and runner.py env plumbing.
"""

import importlib.util


def ray_available():
    return importlib.util.find_spec("ray") is not None


def placement_bundles(num_hosts=None, num_workers_per_host=None,
                      num_workers=None, cpus_per_worker=1,
                      tpus_per_worker=0):
    """Placement-group bundles: one bundle per *worker process* (= per host
    in the TPU model, each owning its chips).

    Two API shapes, matching the reference (runner.py:168): explicit
    ``num_hosts × num_workers_per_host`` or flat ``num_workers``. Returns
    (bundles, strategy_string). Both use STRICT_SPREAD: the env contract
    gives every worker LOCAL_RANK=0 / sole ownership of its node's chips,
    so colocating two workers on one node (the reference's PACK default,
    valid for one-process-per-GPU) would double-grab devices here.
    """
    if (num_hosts is None) == (num_workers is None):
        raise ValueError(
            "specify exactly one of num_hosts(+num_workers_per_host) or "
            "num_workers (matches reference RayExecutor arg validation)")
    resources = {"CPU": cpus_per_worker}
    if tpus_per_worker:
        resources["TPU"] = tpus_per_worker
    if num_hosts is not None:
        per_host = num_workers_per_host or 1
        bundle = {k: v * per_host for k, v in resources.items()}
        return [dict(bundle) for _ in range(num_hosts)], "STRICT_SPREAD"
    return [dict(resources) for _ in range(num_workers)], "STRICT_SPREAD"


def worker_env(cross_rank, cross_size, local_size, coordinator_addr,
               coordinator_port, kv_port, base_env=None):
    """The rank/coordinator env contract for one worker
    (reference: runner.py Coordinator.establish_rendezvous +
    gloo_run.py:66-78 rank env)."""
    env = dict(base_env or {})
    env.update({
        "HOROVOD_CROSS_RANK": str(cross_rank),
        "HOROVOD_CROSS_SIZE": str(cross_size),
        "HOROVOD_LOCAL_SIZE": str(local_size),
        "HOROVOD_SIZE": str(cross_size * local_size),
        "HOROVOD_RANK": str(cross_rank * local_size),
        "HOROVOD_LOCAL_RANK": "0",
        "HOROVOD_COORDINATOR_ADDR": coordinator_addr,
        "HOROVOD_COORDINATOR_PORT": str(coordinator_port),
        "HOROVOD_KV_ADDR": coordinator_addr,
        "HOROVOD_KV_PORT": str(kv_port),
    })
    import os

    from horovod_tpu.runner.secret import SECRET_ENV
    if os.environ.get(SECRET_ENV):
        env[SECRET_ENV] = os.environ[SECRET_ENV]
    return env
