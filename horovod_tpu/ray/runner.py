"""RayExecutor — run horovod_tpu training on a Ray cluster.

Reference: horovod/ray/runner.py (RayExecutor :168-430: create_settings,
start/run/run_remote/execute/shutdown; worker actors hold the training env
and a BaseHorovodWorker.execute). TPU model: one actor per host process;
each actor's worker bootstraps ``jax.distributed`` with the env contract and
owns all chips Ray scheduled onto its node.
"""

import os
import socket

import cloudpickle

from horovod_tpu.ray.strategy import (placement_bundles, ray_available,
                                      worker_env)


class _Settings:
    """Mini settings object (reference: RayExecutor.create_settings
    runner.py:211-240)."""

    def __init__(self, timeout_s=30, placement_group_timeout_s=100,
                 nics=None):
        self.timeout_s = timeout_s
        self.placement_group_timeout_s = placement_group_timeout_s
        self.nics = nics


class RayExecutor:
    """Job class for horovod_tpu + Ray (reference: runner.py:168).

    Example::

        ex = RayExecutor(num_workers=2, cpus_per_worker=2)
        ex.start()
        results = ex.run(train_fn, args=(cfg,))
        ex.shutdown()
    """

    @classmethod
    def create_settings(cls, timeout_s=30, placement_group_timeout_s=100,
                        nics=None, **_compat):
        return _Settings(timeout_s, placement_group_timeout_s, nics)

    def __init__(self, settings=None, num_workers=None, num_hosts=None,
                 num_workers_per_host=1, cpus_per_worker=1,
                 tpus_per_worker=0, use_current_placement_group=True,
                 env_vars=None):
        if not ray_available():
            raise RuntimeError(
                "RayExecutor requires ray (`pip install ray`); it is not "
                "bundled with horovod_tpu")
        self.settings = settings or self.create_settings()
        self.bundles, self.strategy = placement_bundles(
            num_hosts=num_hosts, num_workers_per_host=num_workers_per_host,
            num_workers=num_workers, cpus_per_worker=cpus_per_worker,
            tpus_per_worker=tpus_per_worker)
        self.num_workers = len(self.bundles)
        self.local_size = (num_workers_per_host if num_hosts is not None
                           else 1)
        self.cpus_per_worker = cpus_per_worker
        self.tpus_per_worker = tpus_per_worker
        self.use_current_placement_group = use_current_placement_group
        self.env_vars = dict(env_vars or {})
        self.workers = []
        self.placement_group = None
        self._kv = None

    # -- lifecycle ---------------------------------------------------------

    def start(self, extra_env_vars=None):
        """Create the placement group and worker actors, establish the
        rendezvous env (reference: runner.py start/_start_executables)."""
        import ray

        from horovod_tpu.runner.http_kv import KVStoreServer

        env = {**self.env_vars, **(extra_env_vars or {})}

        pg = None
        if self.use_current_placement_group:
            pg = ray.util.get_current_placement_group()
        if pg is None:
            pg = ray.util.placement_group(self.bundles,
                                          strategy=self.strategy)
            ray.get(pg.ready(),
                    timeout=self.settings.placement_group_timeout_s)
            self.placement_group = pg

        import os

        from horovod_tpu.runner.secret import SECRET_ENV, make_secret_key
        os.environ.setdefault(SECRET_ENV, make_secret_key())
        self._kv = KVStoreServer()
        kv_port = self._kv.start()
        coordinator_addr = socket.gethostbyname(socket.gethostname())
        coordinator_port = _free_port()

        worker_cls = _make_worker_cls(self.cpus_per_worker,
                                      self.tpus_per_worker)
        self.workers = []
        for i in range(self.num_workers):
            wenv = worker_env(i, self.num_workers, self.local_size,
                              coordinator_addr, coordinator_port, kv_port,
                              base_env=env)
            actor = worker_cls.options(
                scheduling_strategy=ray.util.scheduling_strategies.
                PlacementGroupSchedulingStrategy(
                    placement_group=pg, placement_group_bundle_index=i)
            ).remote(wenv)
            self.workers.append(actor)
        ray.get([w.ready.remote() for w in self.workers],
                timeout=self.settings.timeout_s)

    def run(self, fn, args=None, kwargs=None):
        """Run ``fn`` on every worker; returns the list of results ordered by
        rank (reference: runner.py run :355)."""
        import ray
        payload = cloudpickle.dumps((fn, tuple(args or ()),
                                     dict(kwargs or {})))
        return ray.get([w.execute_pickled.remote(payload)
                        for w in self.workers])

    def run_remote(self, fn, args=None, kwargs=None):
        """Async variant returning object refs (reference: runner.py
        run_remote :377)."""
        payload = cloudpickle.dumps((fn, tuple(args or ()),
                                     dict(kwargs or {})))
        return [w.execute_pickled.remote(payload) for w in self.workers]

    def execute(self, fn):
        """Run ``fn(executable)`` on each worker's persistent state
        (reference: runner.py execute :340)."""
        import ray
        return ray.get([w.execute_fn.remote(cloudpickle.dumps(fn))
                        for w in self.workers])

    def execute_single(self, fn):
        import ray
        return ray.get(
            self.workers[0].execute_fn.remote(cloudpickle.dumps(fn)))

    def shutdown(self):
        """Kill actors and release the placement group
        (reference: runner.py shutdown :425)."""
        import ray
        for w in self.workers:
            ray.kill(w)
        self.workers = []
        if self.placement_group is not None:
            ray.util.remove_placement_group(self.placement_group)
            self.placement_group = None
        if self._kv is not None:
            self._kv.stop()
            self._kv = None


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _make_worker_cls(cpus_per_worker, tpus_per_worker):
    """Define the worker actor lazily (ray must be importable)."""
    import ray

    @ray.remote(num_cpus=cpus_per_worker,
                resources=({"TPU": tpus_per_worker} if tpus_per_worker
                           else None))
    class _HorovodWorker:
        def __init__(self, env):
            os.environ.update(env)

        def ready(self):
            return True

        def execute_pickled(self, payload):
            fn, args, kwargs = cloudpickle.loads(payload)
            return fn(*args, **kwargs)

        def execute_fn(self, pickled_fn):
            fn = cloudpickle.loads(pickled_fn)
            return fn(self)

    return _HorovodWorker
