"""Ray cluster integration (reference: horovod/ray/).

``RayExecutor`` places one worker actor per host (each owns that host's TPU
chips), wires the rank/coordinator env contract, and runs the training
function — mirroring horovod/ray/runner.py:168-430 with the TPU process
model. Gated: importing this package works without ray; constructing an
executor requires it.
"""

from horovod_tpu.ray.elastic import (ElasticRayExecutor, RayHostDiscovery,
                                     run_elastic)
from horovod_tpu.ray.worker import BaseHorovodWorker
from horovod_tpu.ray.runner import RayExecutor
from horovod_tpu.ray.strategy import (placement_bundles, ray_available,
                                      worker_env)
from horovod_tpu.ray.tune import tune_trainable

__all__ = ["RayExecutor", "RayHostDiscovery", "run_elastic",
           "placement_bundles", "worker_env", "ray_available",
           "tune_trainable"]
