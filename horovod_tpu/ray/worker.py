"""Ray worker actor base (reference: horovod/ray/worker.py BaseHorovodWorker:8
— an actor that pins the HOROVOD_* env contract, can start a long-lived
executable object, and executes pickled functions in place).

Used by :class:`horovod_tpu.ray.RayExecutor`'s actor pool; exposed publicly
so advanced users can subclass it for custom per-worker setup, as with the
reference.
"""

import os
import socket


class BaseHorovodWorker:
    executable = None

    def __init__(self, world_rank=0, world_size=1):
        os.environ["HOROVOD_HOSTNAME"] = self.hostname()
        os.environ["HOROVOD_RANK"] = str(world_rank)
        os.environ["HOROVOD_SIZE"] = str(world_size)

    def node_id(self):
        import ray
        return ray.get_runtime_context().get_node_id()

    def hostname(self):
        return socket.gethostname()

    def get_gpu_ids(self):
        """CUDA ids for API compatibility — empty on the TPU build."""
        return []

    def update_env_vars(self, env_vars):
        os.environ.update({k: str(v) for k, v in env_vars.items()})

    def env_vars(self):
        return dict(os.environ)

    def start_executable(self, executable_cls=None, executable_args=None,
                         executable_kwargs=None):
        """Instantiate a long-lived object whose methods :meth:`execute` can
        target (reference: worker.py:37-55)."""
        executable_args = executable_args or []
        executable_kwargs = executable_kwargs or {}
        if executable_cls:
            self.executable = executable_cls(*executable_args,
                                             **executable_kwargs)

    def execute(self, func):
        """Run ``func(self.executable)`` in the worker process."""
        return func(self.executable)
