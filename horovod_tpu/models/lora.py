"""LoRA: low-rank adaptation for parameter-efficient fine-tuning.

Beyond-parity capability (the reference has no model-level tooling;
SURVEY.md §5.7 — this framework carries the model zoo, so it carries the
fine-tuning story too): Hu et al. 2021, "LoRA: Low-Rank Adaptation of
Large Language Models". Frozen base weights ``W`` are adapted as
``W + (alpha/r) * B @ A`` with trainable rank-``r`` factors.

TPU-first, MODEL-AGNOSTIC design: instead of wrapping layer modules (a
per-architecture surgery), the adapters live as a separate small pytree
and are MERGED FUNCTIONALLY into the parameter tree before each
``model.apply`` — XLA fuses the rank-r matmul + add into the step, so
any zoo model (GPT, LLaMA, BERT, T5, ViT, ...) works unchanged. The
distributed win is structural: only the adapter gradients cross the
wire, so the fused allreduce moves ``r*(n+m)`` elements per adapted
``(n, m)`` kernel instead of ``n*m`` — the same economics PowerSGD
approximates, exact here by construction.

    lora = lora_init(params, rank=8, rng=key)           # adapters only
    step = make_train_step(adapter_loss_fn(loss_fn, params, lora),
                           DistributedOptimizer(optax.adamw(1e-4)), mesh)
    ...                                                 # train adapters
    export = lora_merge(params, trained_lora)           # standalone tree
"""

import re

import jax
import jax.numpy as jnp


def _joined(path):
    """THE slash-join convention for parameter paths — defined once."""
    return "/".join(str(getattr(p, "key", p)) for p in path)


def _kernel_leaves(params, targets):
    """``(joined_path, leaf)`` pairs of 2-D ``kernel`` leaves matching
    the ``targets`` regex (e.g. ``layer_0/attn/qkv/kernel``)."""
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        joined = _joined(path)
        if getattr(leaf, "ndim", 0) == 2 \
                and joined.rsplit("/", 1)[-1] == "kernel" \
                and re.search(targets, joined):
            out.append((joined, leaf))
    return out


def lora_init(params, rank=8, alpha=None, targets=r".", rng=None,
              dtype=None):
    """Build the adapter pytree: for every 2-D ``kernel (n_in, n_out)``
    whose path matches ``targets``, a gaussian-init ``a (n_in, r)`` and
    a ZERO-init ``b (r, n_out)`` — so the adapted model starts EXACTLY
    at the base model (Hu et al. §4.1). Returns ``{"rank", "alpha",
    "adapters": {path: {"a", "b"}}}``; paths are the slash-joined
    locations inside ``params``."""
    if rank < 1:
        raise ValueError(f"LoRA rank must be >= 1, got {rank}")
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    alpha = float(alpha) if alpha is not None else float(rank)
    selected = _kernel_leaves(params, targets)
    if not selected:
        raise ValueError(
            f"lora_init: no 2-D 'kernel' leaves match targets={targets!r}")
    adapters = {}
    for i, (path, w) in enumerate(selected):
        n_in, n_out = w.shape
        r = min(rank, n_in, n_out)
        dt = dtype or w.dtype
        a = jax.random.normal(jax.random.fold_in(rng, i),
                              (n_in, r), jnp.float32) * (1.0 / max(n_in, 1)
                                                         ** 0.5)
        adapters[path] = {"a": a.astype(dt),
                          "b": jnp.zeros((r, n_out), dt)}
    return {"rank": rank, "alpha": alpha, "adapters": adapters}


def _delta(ad, alpha, rank):
    scale = alpha / max(1, min(rank, ad["a"].shape[1]))
    return (ad["a"] @ ad["b"]) * scale


def lora_apply(params, lora):
    """Merge the adapters into a NEW parameter tree for ``model.apply``:
    ``W + (alpha/r) * A @ B`` at every adapted path, everything else
    shared by reference. Run INSIDE the jitted step — XLA fuses the
    rank-r work; base params stay untouched (frozen)."""
    adapters = lora["adapters"]
    alpha, rank = lora["alpha"], lora["rank"]

    def merge(path, leaf):
        ad = adapters.get(_joined(path))
        if ad is None:
            return leaf
        return (leaf + _delta(ad, alpha, rank).astype(leaf.dtype))

    return jax.tree_util.tree_map_with_path(merge, params)


def lora_merge(params, lora):
    """Export: fold the adapters permanently into a standalone parameter
    tree (same structure as ``params``) for serving without the LoRA
    machinery."""
    return lora_apply(params, lora)


def lora_wire_numbers(params, lora):
    """(adapter_bytes, full_bytes) per allreduce — what LoRA fine-tuning
    moves on the wire vs full fine-tuning (fp32 accounting)."""
    adapter = sum(ad["a"].size + ad["b"].size
                  for ad in lora["adapters"].values()) * 4
    full = sum(l.size for l in jax.tree_util.tree_leaves(params)) * 4
    return adapter, full


def adapter_loss_fn(loss_fn, base_params, lora):
    """The LoRA fine-tuning adapter for the standard training machinery:
    given the model's ``loss_fn(params, batch)``, return
    ``adapter_loss(adapters, batch)`` that merges the adapters into the
    FROZEN ``base_params`` (a closure constant — gradients cannot reach
    it by construction) before calling through.

    Use with the ordinary step builders — LoRA is just a smaller
    parameter tree to them, which is exactly the distributed win (the
    fused allreduce moves adapter-sized buckets)::

        lora = lora_init(params, rank=8, rng=key)
        opt = DistributedOptimizer(optax.adamw(1e-4))
        step = make_train_step(adapter_loss_fn(loss_fn, params, lora),
                               opt, mesh)
        state = TrainState.create(lora["adapters"], opt)
        ...
        trained = {**lora, "adapters": state.params}
        export = lora_merge(params, trained)

    The base tree is captured as a jit closure constant here — fine for
    small/medium bases; for a LARGE base model use
    :func:`adapter_loss_fn_via_extra`, which threads the base through
    ``TrainState.extra`` as a real operand (no constant capture, compile
    cache keys stay small).
    """
    rank, alpha = lora["rank"], lora["alpha"]

    def adapter_loss(adapters, batch):
        merged = lora_apply(
            base_params,
            {"rank": rank, "alpha": alpha, "adapters": adapters})
        return loss_fn(merged, batch)

    return adapter_loss


def adapter_loss_fn_via_extra(loss_fn, lora):
    """Large-base variant of :func:`adapter_loss_fn`: the frozen base
    tree rides ``TrainState.extra`` as an explicit (non-differentiated)
    operand instead of a jit closure constant::

        step = make_train_step(adapter_loss_fn_via_extra(loss_fn, lora),
                               opt, mesh, has_aux=True)
        state = TrainState.create(lora["adapters"], opt, extra=params)

    The returned ``adapter_loss(adapters, batch, base) -> (loss, base)``
    passes the base back unchanged (the has_aux extra contract).
    """
    rank, alpha = lora["rank"], lora["alpha"]

    def adapter_loss(adapters, batch, base):
        merged = lora_apply(
            base, {"rank": rank, "alpha": alpha, "adapters": adapters})
        return loss_fn(merged, batch), base

    return adapter_loss
