"""T5-style encoder-decoder: relative position biases, RMSNorm, GEGLU,
cross-attention.

Completes the transformer triad in the zoo (BERT = encoder-only,
GPT/LLaMA = decoder-only): the reference framework has no model zoo
(SURVEY.md intro), so models here exist to exercise the distributed
machinery — this one adds cross-attention (``parallel/tp.py``
``TPCrossAttention``) and additive attention biases to the covered
surface. Follows the T5 1.1 recipe: no absolute positions (bucketed
relative position biases on self-attention, shared across layers), RMSNorm
pre-norm, gated-gelu MLP, bias-free projections, untied fp32 LM head.

TPU-first choices as elsewhere: bf16 activations with fp32 params/logits,
fused projections (QKV / gate+up / KV), static shapes. The relative bias
is computed once per stack from a static bucket matrix (host-side numpy)
and one embedding lookup — no per-layer recompute.
"""

import dataclasses
import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu.parallel.tp import (TPCrossAttention, TPSelfAttention,
                                     TPSwiGLUMlp, axis_size_or_1)


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    hidden_size: int = 512
    num_layers: int = 8            # per stack (encoder AND decoder)
    num_heads: int = 8
    intermediate_size: int = 1024
    num_buckets: int = 32
    max_distance: int = 128
    max_decode_len: int = 512       # KV-cache capacity for cached decoding
    rms_eps: float = 1e-6
    dtype: Any = jnp.float32
    tp_axis: Optional[str] = "tp"
    # jax.checkpoint each block's backward (see GPTConfig.remat)
    remat: bool = False

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, intermediate_size=128, num_buckets=8,
                    max_distance=16, max_decode_len=32)
        base.update(kw)
        return T5Config(**base)


def relative_position_buckets_causal_jnp(query_pos, key_positions,
                                         num_buckets, max_distance):
    """Traced causal bucketing for ONE query position against a vector of
    key positions (the decode path: ``query_pos`` is the cache cursor).
    Matches :func:`relative_position_buckets`'s bidirectional=False
    branch; future keys (key > query) land in bucket 0 — they are masked
    by the cache-validity check anyway."""
    rel = jnp.maximum(query_pos - key_positions, 0)        # distance back
    max_exact = num_buckets // 2
    large = max_exact + (
        jnp.log(jnp.maximum(rel, 1).astype(jnp.float32) / max_exact)
        / np.log(max_distance / max_exact) * (num_buckets - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return jnp.where(rel < max_exact, rel, large)


def relative_position_buckets(query_len, key_len, num_buckets, max_distance,
                              bidirectional):
    """T5's bucketed relative positions, host-side: (Lq, Lk) int32.

    Half the buckets cover exact small offsets, the other half log-spaced
    offsets out to ``max_distance``; the encoder (bidirectional) splits
    buckets again by sign. (Reference recipe from the T5 paper — computed
    with numpy at trace time since shapes are static.)
    """
    rel = np.arange(key_len)[None, :] - np.arange(query_len)[:, None]
    if bidirectional:
        num_buckets //= 2
        bucket_offset = (rel > 0).astype(np.int32) * num_buckets
        rel = np.abs(rel)
    else:
        bucket_offset = np.zeros_like(rel)
        rel = np.maximum(-rel, 0)      # decoder attends to the past only
    max_exact = num_buckets // 2
    is_small = rel < max_exact
    # log-spaced buckets for larger distances
    large = max_exact + (
        np.log(np.maximum(rel, 1) / max_exact)
        / np.log(max_distance / max_exact) * (num_buckets - max_exact)
    ).astype(np.int32)
    large = np.minimum(large, num_buckets - 1)
    return (bucket_offset + np.where(is_small, rel, large)).astype(np.int32)


class T5RelativeBias(nn.Module):
    """Learned per-head bias over relative-position buckets, one table per
    stack (T5 shares it across layers). Heads are sharded over tp: the
    table stays replicated (small) and the local head slice is taken by
    tp index, matching TPSelfAttention's head-blocked layout."""
    config: T5Config
    bidirectional: bool

    def setup(self):
        c = self.config
        self.table = self.param("rel_bias", nn.initializers.normal(0.1),
                                (c.num_buckets, c.num_heads), jnp.float32)

    def _local_heads(self, bias):
        c = self.config
        n = axis_size_or_1(c.tp_axis)
        if n > 1:
            local = c.num_heads // n
            bias = lax.dynamic_slice_in_dim(
                bias, lax.axis_index(c.tp_axis) * local, local, axis=0)
        return bias

    def __call__(self, query_len, key_len):
        c = self.config
        buckets = relative_position_buckets(
            query_len, key_len, c.num_buckets, c.max_distance,
            self.bidirectional)
        bias = jnp.asarray(self.table, c.dtype)[jnp.asarray(buckets)]
        return self._local_heads(
            jnp.transpose(bias, (2, 0, 1)))            # (heads, Lq, Lk)

    def decode_bias(self, pos, cache_len):
        """Bias row for ONE query at traced position ``pos`` against cache
        slots 0..cache_len-1 (causal stacks only): (local_heads, 1, L)."""
        c = self.config
        buckets = relative_position_buckets_causal_jnp(
            pos, jnp.arange(cache_len), c.num_buckets, c.max_distance)
        bias = jnp.asarray(self.table, c.dtype)[buckets]   # (L, heads)
        return self._local_heads(jnp.transpose(bias)[:, None, :])


class T5Block(nn.Module):
    """Pre-RMSNorm block: self-attention (+ relative bias), optional
    cross-attention (decoder), GEGLU MLP; bias-free. ``decode`` turns the
    self-attention into KV-cache single-token mode (``bias`` is then this
    step's relative-position row over the cache); pass ``cross_kv`` (from
    a one-time ``project_kv_only`` pass over the static encoder memory)
    so decode steps skip the cross K/V projection too."""
    config: T5Config
    causal: bool
    cross: bool
    decode: bool = False

    def _cross_module(self):
        c = self.config
        return TPCrossAttention(c.num_heads, c.hidden_size, dtype=c.dtype,
                                axis_name=c.tp_axis, use_bias=False,
                                name="cross")

    @nn.compact
    def __call__(self, x, bias, memory=None, memory_mask=None, mask=None,
                 cross_kv=None, project_kv_only=False):
        c = self.config
        if project_kv_only:
            return self._cross_module()(None, memory, project_only=True)
        a = TPSelfAttention(
            c.num_heads, c.hidden_size, dtype=c.dtype, axis_name=c.tp_axis,
            causal=self.causal, use_bias=False, decode=self.decode,
            cache_len=c.max_decode_len, name="attention")(
                nn.RMSNorm(epsilon=c.rms_eps, dtype=c.dtype,
                           name="ln_attn")(x), mask, bias)
        x = x + a
        if self.cross:
            a = self._cross_module()(
                nn.RMSNorm(epsilon=c.rms_eps, dtype=c.dtype,
                           name="ln_cross")(x), memory, memory_mask,
                cached_kv=cross_kv)
            x = x + a
        h = TPSwiGLUMlp(c.intermediate_size, c.hidden_size, dtype=c.dtype,
                        axis_name=c.tp_axis, activation="gelu",
                        name="mlp")(
                            nn.RMSNorm(epsilon=c.rms_eps, dtype=c.dtype,
                                       name="ln_mlp")(x))
        return x + h


class T5Encoder(nn.Module):
    """Encoder stack. ``embed``: a shared token embedding passed by the
    parent :class:`T5` (T5 shares ONE embedding between encoder and
    decoder); standalone use creates its own."""
    config: T5Config
    embed: Optional[nn.Module] = None

    @nn.compact
    def __call__(self, input_ids, mask=None):
        c = self.config
        emb = self.embed if self.embed is not None else nn.Embed(
            c.vocab_size, c.hidden_size, dtype=c.dtype, name="tok_emb")
        x = emb(input_ids)
        L = input_ids.shape[1]
        bias = T5RelativeBias(c, bidirectional=True, name="rel_bias")(L, L)
        block_cls = nn.remat(T5Block) if c.remat else T5Block
        for i in range(c.num_layers):
            x = block_cls(c, causal=False, cross=False,
                          name=f"layer_{i}")(x, bias, mask=mask)
        return nn.RMSNorm(epsilon=c.rms_eps, dtype=c.dtype,
                          name="ln_f")(x)


class T5Decoder(nn.Module):
    """Decoder stack (see :class:`T5Encoder` for ``embed`` sharing).
    ``decode=True`` feeds ONE token per call at traced position ``pos``
    through the per-layer KV caches."""
    config: T5Config
    embed: Optional[nn.Module] = None
    decode: bool = False

    @nn.compact
    def __call__(self, input_ids, memory, memory_mask=None, pos=None,
                 cross_kv=None, project_kv_only=False):
        c = self.config

        def block(i):
            # remat in training only — the decode/prime paths have no
            # backward and mutate the cache collection
            cls = (nn.remat(T5Block) if c.remat and not self.decode
                   and not project_kv_only else T5Block)
            return cls(c, causal=True, cross=True, decode=self.decode,
                       name=f"layer_{i}")

        if project_kv_only:
            # One fused K/V projection of the static memory per layer —
            # the decode loop primes these once and feeds them back via
            # ``cross_kv``.
            return tuple(block(i)(None, None, memory=memory,
                                  project_kv_only=True)
                         for i in range(c.num_layers))
        if self.decode and pos is None:
            raise ValueError("decode mode requires pos (the token's "
                             "position)")
        emb = self.embed if self.embed is not None else nn.Embed(
            c.vocab_size, c.hidden_size, dtype=c.dtype, name="tok_emb")
        x = emb(input_ids)
        rel = T5RelativeBias(c, bidirectional=False, name="rel_bias")
        if self.decode:
            bias = rel.decode_bias(pos, c.max_decode_len)
        else:
            L = input_ids.shape[1]
            bias = rel(L, L)
        for i in range(c.num_layers):
            x = block(i)(x, bias, memory=memory, memory_mask=memory_mask,
                         cross_kv=None if cross_kv is None else cross_kv[i])
        x = nn.RMSNorm(epsilon=c.rms_eps, dtype=c.dtype, name="ln_f")(x)
        return nn.Dense(c.vocab_size, use_bias=False, dtype=jnp.float32,
                        name="lm_head")(x)


class T5(nn.Module):
    """Encoder-decoder LM: ``(src_ids, tgt_ids) -> (B, Lt, V)`` logits.

    ``src_mask``: (B, Ls) True on valid source tokens — masks encoder
    self-attention AND decoder cross-attention. One token embedding is
    SHARED between the two stacks (the T5 recipe; only the LM head is
    untied, per T5 1.1): its params live under ``shared`` in the tree.
    """
    config: T5Config
    decode_mode: bool = False   # KV-cache single-token decoding

    def setup(self):
        c = self.config
        self.shared = nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype)
        self.encoder = T5Encoder(c, embed=self.shared)
        self.decoder = T5Decoder(c, embed=self.shared,
                                 decode=self.decode_mode)

    def encode(self, src_ids, src_mask=None):
        return self.encoder(src_ids, src_mask)

    def decode(self, tgt_ids, memory, memory_mask=None, pos=None,
               cross_kv=None):
        return self.decoder(tgt_ids, memory, memory_mask=memory_mask,
                            pos=pos, cross_kv=cross_kv)

    def project_cross_kv(self, memory):
        """Per-layer fused cross-attention K/V of the (static) encoder
        memory — prime once, pass to :meth:`decode` as ``cross_kv``."""
        return self.decoder(None, memory, project_kv_only=True)

    def __call__(self, src_ids, tgt_ids, src_mask=None):
        return self.decode(tgt_ids, self.encode(src_ids, src_mask),
                           memory_mask=src_mask)


@functools.partial(jax.jit, static_argnums=(0, 3, 4, 6, 7, 9, 10))
def _t5_greedy(model, params, src_ids, max_len, bos_id, src_mask,
               eos_id=None, temperature=0.0, rng=None, top_k=0, top_p=1.0):
    # Module-level jit: flax modules hash by their dataclass config, so
    # repeated decode calls with the same (config, max_len, bos_id, shapes)
    # reuse one compiled program. encode/decode run as methods of the FULL
    # model so the shared token embedding resolves.
    from horovod_tpu.models.generate import _absorb_eos
    memory = model.apply({"params": params}, src_ids, src_mask,
                         method=T5.encode)
    B = src_ids.shape[0]
    buf = jnp.full((B, max_len), bos_id, jnp.int32)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def step(carry, t):
        buf, done, rng = carry
        logits = model.apply({"params": params}, buf, memory,
                             memory_mask=src_mask, method=T5.decode)
        from horovod_tpu.models.generate import sample_or_argmax
        nxt, rng = sample_or_argmax(logits[:, t - 1], rng, temperature,
                                    top_k, top_p)
        nxt, done = _absorb_eos(nxt, done, eos_id)
        return (lax.dynamic_update_slice(buf, nxt[:, None], (0, t)),
                done, rng), None

    (buf, _, _), _ = lax.scan(step, (buf, jnp.zeros((B,), bool), rng),
                              jnp.arange(1, max_len))
    return buf


@functools.partial(jax.jit, static_argnums=(0, 3, 4, 6, 7, 9, 10))
def _t5_greedy_cached(decoder_model, state, src_ids, max_len, bos_id,
                      src_mask, eos_id=None, temperature=0.0, rng=None,
                      top_k=0, top_p=1.0):
    """KV-cache greedy decode: encoder once, then ONE token per step
    through the decoder's per-layer self-attention caches, with the
    cross-attention K/V primed from the static memory exactly once —
    O(1) projection work per generated token."""
    from horovod_tpu.models.generate import _absorb_eos
    params, cache = state
    memory = decoder_model.apply({"params": params}, src_ids, src_mask,
                                 method=T5.encode)
    # Prime the per-layer cross-attention K/V ONCE — the memory is static,
    # so each decode step skips its projection entirely.
    cross_kv = decoder_model.apply({"params": params}, memory,
                                   method=T5.project_cross_kv)
    B = src_ids.shape[0]
    buf = jnp.full((B, max_len), bos_id, jnp.int32)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def step(carry, t):
        buf, cache, done, rng = carry
        tok = lax.dynamic_slice_in_dim(buf, t - 1, 1, axis=1)
        logits, upd = decoder_model.apply(
            {"params": params, "cache": cache}, tok, memory,
            memory_mask=src_mask, pos=t - 1, cross_kv=cross_kv,
            method=T5.decode, mutable=["cache"])
        from horovod_tpu.models.generate import sample_or_argmax
        nxt, rng = sample_or_argmax(logits[:, 0], rng, temperature, top_k,
                                    top_p)
        nxt, done = _absorb_eos(nxt, done, eos_id)
        buf = lax.dynamic_update_slice(buf, nxt[:, None], (0, t))
        return (buf, upd["cache"], done, rng), None

    (buf, _, _, _), _ = lax.scan(
        step, (buf, cache, jnp.zeros((B,), bool), rng),
        jnp.arange(1, max_len))
    return buf


@functools.partial(jax.jit, static_argnums=(0, 3, 4, 5, 7, 8))
def _t5_beam(model, params, src_ids, max_len, num_beams, bos_id, src_mask,
             eos_id=None, length_penalty=0.0):
    from horovod_tpu.models.generate import (beam_expand, beam_finalize,
                                             beam_init_scores,
                                             beam_step_eos)
    memory = model.apply({"params": params}, src_ids, src_mask,
                         method=T5.encode)
    B, k = src_ids.shape[0], num_beams
    mem_k = jnp.repeat(memory, k, axis=0)
    mask_k = None if src_mask is None else jnp.repeat(src_mask, k, axis=0)
    bufs = jnp.full((B, k, max_len), bos_id, jnp.int32)
    scores = beam_init_scores(B, k)
    fin_bufs = jnp.zeros_like(bufs)
    fin_scores = jnp.full((B, k), -jnp.inf, jnp.float32)

    def step(carry, t):
        bufs, scores, fin_bufs, fin_scores = carry
        logits = model.apply({"params": params},
                             bufs.reshape(B * k, max_len), mem_k,
                             memory_mask=mask_k, method=T5.decode)
        logp = jax.nn.log_softmax(
            logits[:, t - 1].astype(jnp.float32)).reshape(B, k, -1)
        if eos_id is None:
            bufs, scores, _ = beam_expand(logp, bufs, scores, t)
        else:
            bufs, scores, fin_bufs, fin_scores, _ = beam_step_eos(
                logp, bufs, scores, fin_bufs, fin_scores, t, 1, eos_id,
                length_penalty)
        return (bufs, scores, fin_bufs, fin_scores), None

    (bufs, scores, fin_bufs, fin_scores), _ = lax.scan(
        step, (bufs, scores, fin_bufs, fin_scores),
        jnp.arange(1, max_len))
    return beam_finalize(bufs, scores, fin_bufs, fin_scores, 1, eos_id,
                         length_penalty)


@functools.partial(jax.jit, static_argnums=(0, 3, 4, 5, 7, 8))
def _t5_beam_cached(decoder_model, state, src_ids, max_len, num_beams,
                    bos_id, src_mask, eos_id=None, length_penalty=0.0):
    """KV-cache seq2seq beam search: encoder once at batch B, per-layer
    cross-attention K/V primed once and repeated per beam, then ONE
    decoder token per hypothesis per step with the self-attention caches
    reordered by beam origin after every expansion (the causal analog:
    generate._beam_search_cached)."""
    from horovod_tpu.models.generate import (beam_expand, beam_finalize,
                                             beam_init_scores,
                                             beam_reorder_cache,
                                             beam_step_eos)
    params, cache = state                       # cache leaves at B*k
    B, k = src_ids.shape[0], num_beams
    Bk = B * k
    memory = decoder_model.apply({"params": params}, src_ids, src_mask,
                                 method=T5.encode)
    cross_kv = decoder_model.apply({"params": params}, memory,
                                   method=T5.project_cross_kv)
    # memory itself is NOT expanded per beam: with cross_kv supplied the
    # decode path never reads it (tp.py cross-attention uses the cached
    # K/V); only the mask and the primed K/V need the per-beam batch.
    mask_k = None if src_mask is None else jnp.repeat(src_mask, k, axis=0)
    ckv_k = jax.tree_util.tree_map(lambda c: jnp.repeat(c, k, axis=0),
                                   cross_kv)
    bufs = jnp.full((B, k, max_len), bos_id, jnp.int32)
    scores = beam_init_scores(B, k)
    fin_bufs = jnp.zeros_like(bufs)
    fin_scores = jnp.full((B, k), -jnp.inf, jnp.float32)

    def feed(cache, tok, t):
        logits, upd = decoder_model.apply(
            {"params": params, "cache": cache}, tok, memory,
            memory_mask=mask_k, pos=t, cross_kv=ckv_k,
            method=T5.decode, mutable=["cache"])
        return upd["cache"], logits[:, 0]

    def step(carry, t):
        bufs, scores, fin_bufs, fin_scores, cache = carry
        tok = lax.dynamic_slice_in_dim(bufs.reshape(Bk, max_len), t - 1, 1,
                                       axis=1)
        cache, logits = feed(cache, tok, t - 1)
        logp = jax.nn.log_softmax(
            logits.astype(jnp.float32)).reshape(B, k, -1)
        if eos_id is None:
            bufs, scores, origin = beam_expand(logp, bufs, scores, t)
        else:
            bufs, scores, fin_bufs, fin_scores, origin = beam_step_eos(
                logp, bufs, scores, fin_bufs, fin_scores, t, 1, eos_id,
                length_penalty)
        cache = beam_reorder_cache(cache, origin, B, k)
        return (bufs, scores, fin_bufs, fin_scores, cache), None

    (bufs, scores, fin_bufs, fin_scores, _), _ = lax.scan(
        step, (bufs, scores, fin_bufs, fin_scores, cache),
        jnp.arange(1, max_len))
    return beam_finalize(bufs, scores, fin_bufs, fin_scores, 1, eos_id,
                         length_penalty)


def t5_beam_decode(model, params, src_ids, max_len, num_beams=4, bos_id=0,
                   src_mask=None, eos_id=None, length_penalty=0.0,
                   use_cache=False):
    """Beam-search seq2seq decoding: encoder once, then k hypotheses
    re-forwarded jointly per step (fixed-length buffer). Returns
    ``(sequences, scores)``: (B, max_len) int32 starting with ``bos_id``
    and the summed token log-probs. ``num_beams=1`` with no EOS equals
    :func:`t5_greedy_decode`. ``eos_id`` / ``length_penalty``: true
    finished-pool semantics with GNMT length normalization (see
    :func:`horovod_tpu.models.beam_search`); ``bos_id == eos_id`` is
    safe — only the EOS expansion move finishes a hypothesis.
    ``use_cache``: KV-cached beam decode (cross-attention K/V primed
    once, self-attention caches reordered by beam origin per expansion;
    ``max_len`` bounded by ``config.max_decode_len``) — identical
    outputs to the re-forward search."""
    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    if max_len < 2:
        raise ValueError(f"max_len must be >= 2, got {max_len}")
    if length_penalty < 0:
        raise ValueError(
            f"length_penalty must be >= 0, got {length_penalty}")
    src_ids = jnp.asarray(src_ids, jnp.int32)
    eos = None if eos_id is None else int(eos_id)
    if use_cache:
        if max_len > model.config.max_decode_len:
            raise ValueError(
                f"max_len {max_len} exceeds the decode cache capacity "
                f"(max_decode_len={model.config.max_decode_len})")
        from horovod_tpu.models.generate import init_decode_cache
        decoder = dataclasses.replace(model, decode_mode=True)
        Bk = src_ids.shape[0] * int(num_beams)
        cache = init_decode_cache(
            decoder, jnp.zeros((Bk, 1), jnp.int32),
            jnp.zeros((Bk, src_ids.shape[1], model.config.hidden_size),
                      model.config.dtype),
            pos=0, method=T5.decode)
        return _t5_beam_cached(decoder, (params, cache), src_ids,
                               int(max_len), int(num_beams), int(bos_id),
                               src_mask, eos, float(length_penalty))
    return _t5_beam(model, params, src_ids,
                    int(max_len), int(num_beams), int(bos_id), src_mask,
                    eos, float(length_penalty))


def t5_generate(model, params, src_ids, max_len, bos_id=0, src_mask=None,
                use_cache=False, eos_id=None, temperature=0.0, rng=None,
                top_k=0, top_p=1.0):
    """Seq2seq decoding with the causal family's sampling controls:
    ``temperature=0`` is greedy (== :func:`t5_greedy_decode`); otherwise
    a tempered categorical draw with optional top-k / nucleus filtering
    (``rng`` required), on either the re-forward or the KV-cached path.
    ``eos_id`` finishes rows as in :func:`horovod_tpu.models.generate`."""
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k < 0 or not 0.0 < top_p <= 1.0:
        raise ValueError(f"need top_k >= 0 and 0 < top_p <= 1, got "
                         f"top_k={top_k}, top_p={top_p}")
    if temperature != 0.0 and rng is None:
        raise ValueError("sampling (temperature != 0) requires rng")
    return _t5_decode(model, params, src_ids, max_len, bos_id, src_mask,
                      use_cache, eos_id, float(temperature), rng,
                      int(top_k), float(top_p))


def t5_greedy_decode(model, params, src_ids, max_len, bos_id=0,
                     src_mask=None, use_cache=False, eos_id=None):
    """Greedy seq2seq decoding as one compiled program. Default: encoder
    once, decoder re-forwards a fixed-length buffer per step (causal
    structure ignores the not-yet-written tail). ``use_cache=True``
    decodes one token per step through per-layer self-attention KV caches
    instead (``max_len`` bounded by ``config.max_decode_len``), with
    identical outputs: the O(L^2) self-attention blowup is gone AND the
    cross-attention K/V are projected from the static encoder memory
    exactly once (primed, then fed back per step) — O(1) projection work
    per generated token. Returns (B, max_len) int32 starting with
    ``bos_id``. For sampling, see :func:`t5_generate`."""
    return _t5_decode(model, params, src_ids, max_len, bos_id, src_mask,
                      use_cache, eos_id, 0.0, None, 0, 1.0)


def _t5_decode(model, params, src_ids, max_len, bos_id, src_mask,
               use_cache, eos_id, temperature, rng, top_k, top_p):
    """Shared dispatch for the greedy/sampled seq2seq decodes (validation
    lives in the public wrappers)."""
    src_ids = jnp.asarray(src_ids, jnp.int32)
    eos = None if eos_id is None else int(eos_id)
    if not use_cache:
        return _t5_greedy(model, params, src_ids, int(max_len), int(bos_id),
                          src_mask, eos, temperature, rng, top_k, top_p)
    if max_len > model.config.max_decode_len:
        raise ValueError(
            f"max_len {max_len} exceeds the decode cache capacity "
            f"(max_decode_len={model.config.max_decode_len})")
    from horovod_tpu.models.generate import init_decode_cache
    decoder = dataclasses.replace(model, decode_mode=True)
    cache = init_decode_cache(
        decoder, jnp.zeros((src_ids.shape[0], 1), jnp.int32),
        jnp.zeros((src_ids.shape[0], src_ids.shape[1],
                   model.config.hidden_size), model.config.dtype),
        pos=0, method=T5.decode)
    return _t5_greedy_cached(decoder, (params, cache), src_ids,
                             int(max_len), int(bos_id), src_mask, eos,
                             temperature, rng, top_k, top_p)
