"""Speculative decoding: draft-model proposal + target-model verification.

Beyond-parity serving capability (the reference ships no inference
tooling, docs/inference.rst): implements the acceptance-rejection scheme
of Leviathan et al. 2023 ("Fast Inference from Transformers via
Speculative Decoding") / Chen et al. 2023 — a cheap DRAFT model proposes
``gamma`` tokens autoregressively, the expensive TARGET model scores all
of them in ONE forward, and an acceptance test keeps a prefix of the
proposals such that the OUTPUT DISTRIBUTION IS EXACTLY THE TARGET
MODEL'S (greedy output is bit-identical to target-only greedy decoding;
sampled output follows the target's tempered/filtered distribution).

TPU-first structure: the whole decode is one compiled program — a
``lax.while_loop`` over speculation blocks, a ``lax.scan`` of ``gamma``
draft steps inside, static shapes throughout (fixed working buffer of
``max_len + gamma + 1``; per-row cursors advance by the per-row accepted
count, so batch rows progress independently with masked column writes
instead of dynamic shapes). Per block the target runs one forward over
the buffer — large batched matmuls on the MXU — instead of one forward
per token.

Acceptance math (the exactness core, unit-tested against numpy in
tests/test_models.py): draft token ``x_i ~ q_i`` is accepted iff
``u_i * q_i(x_i) <= p_i(x_i)`` (i.e. with probability ``min(1, p/q)``);
at the first rejection the replacement is drawn from the residual
``max(p - q, 0)`` renormalized; if all ``gamma`` are accepted a bonus
token is drawn from the target's next-position distribution — so every
block yields between 1 and ``gamma + 1`` target-distributed tokens.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.models.generate import (_check_position_capacity,
                                         _filter_logits)


def _spec_probs(logits, temperature, top_k, top_p):
    """The sampling distribution as fp32 probs (temper then filter, same
    semantics as sample_or_argmax). Accepts (..., V): _filter_logits is
    2-D, so leading dims are flattened around it."""
    shape = logits.shape
    flat = logits.reshape(-1, shape[-1]).astype(jnp.float32) / temperature
    return jax.nn.softmax(_filter_logits(flat, top_k, top_p),
                          axis=-1).reshape(shape)


def speculative_accept(p, q, x, u, r_resid, r_bonus):
    """Vectorized acceptance-rejection for one speculation block.

    Args:
        p: (B, gamma+1, V) fp32 TARGET probs at the gamma proposal
           positions plus the bonus position.
        q: (B, gamma, V) fp32 DRAFT probs the proposals were drawn from.
        x: (B, gamma) int32 draft proposals.
        u: (B, gamma) uniforms for the acceptance tests.
        r_resid / r_bonus: PRNG keys for the residual / bonus draws.

    Returns ``(tokens, count)``: (B, gamma+1) output tokens whose first
    ``count`` entries are valid (accepted prefix + correction-or-bonus),
    1 <= count <= gamma+1.

    Correctness (Leviathan et al., thm. 1): accept x_i with prob
    min(1, p_i(x_i)/q_i(x_i)); on first rejection resample from
    norm(max(p_i - q_i, 0)); after gamma acceptances draw from
    p_{gamma+1}. Marginal of every emitted token == target's.
    """
    B, gamma = x.shape
    px = jnp.take_along_axis(p[:, :gamma], x[..., None], axis=-1)[..., 0]
    qx = jnp.take_along_axis(q, x[..., None], axis=-1)[..., 0]
    # STRICT u*q < p  <=>  u < p/q: accept prob is still min(1, p/q)
    # (u ~ [0,1) is continuous; at p >= q, u < p/q always holds), while a
    # token with p(x) = 0 — outside the target's filtered support — is
    # NEVER accepted even when u draws exactly 0.0.
    accept = u * qx < px
    # leading-accept count: stops at the first rejection
    k = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
    # residual distribution at the (clamped) rejection position
    rej = jnp.minimum(k, gamma - 1)
    p_rej = jnp.take_along_axis(p, rej[:, None, None], axis=1)[:, 0]
    q_rej = jnp.take_along_axis(q, rej[:, None, None], axis=1)[:, 0]
    resid = jnp.maximum(p_rej - q_rej, 0.0)
    z = jnp.sum(resid, axis=-1, keepdims=True)
    # z == 0 (p == q exactly at the rejection position) degenerates to p
    resid = jnp.where(z > 0, resid / jnp.maximum(z, 1e-30), p_rej)
    y_resid = jax.random.categorical(
        r_resid, jnp.log(jnp.maximum(resid, 1e-30)))
    y_bonus = jax.random.categorical(
        r_bonus, jnp.log(jnp.maximum(p[:, gamma], 1e-30)))
    y = jnp.where(k == gamma, y_bonus, y_resid).astype(jnp.int32)
    # tokens: accepted drafts, then y at slot k
    toks = jnp.concatenate([x, jnp.zeros((B, 1), jnp.int32)], axis=1)
    slots = jnp.arange(gamma + 1)[None]
    toks = jnp.where(slots == k[:, None], y[:, None], toks)
    return toks, k + 1


def _greedy_accept(p_logits, x):
    """Greedy acceptance: accept draft tokens while they equal the
    target argmax; the first mismatch (or the bonus slot) takes the
    target argmax — output tokens are exactly target-greedy tokens."""
    B, gamma = x.shape
    tgt = jnp.argmax(p_logits, axis=-1).astype(jnp.int32)  # (B, gamma+1)
    accept = x == tgt[:, :gamma]
    k = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
    # accepted prefix == tgt prefix, and slot k takes tgt[k]: the whole
    # emitted block is just the target's own argmax tokens
    return tgt, k + 1


@functools.partial(jax.jit, static_argnums=(0, 1, 5, 6, 7, 9, 10, 12))
def _speculative(target, draft, t_params, d_params, prompt, max_len, gamma,
                 temperature, rng, top_k, top_p, eos_id, width):
    B, P = prompt.shape
    W = width                                   # max_len + gamma + 1
    cols = jnp.arange(W)[None]                  # (1, W)
    buf = jnp.zeros((B, W), jnp.int32)
    buf = lax.dynamic_update_slice(buf, prompt, (0, 0))
    pos0 = jnp.full((B,), P, jnp.int32)         # next position to fill

    def draft_step(carry, _):
        buf, pos, i, drng = carry
        logits = draft.apply({"params": d_params}, buf)      # (B, W, V)
        prev = (pos + i - 1)[:, None, None]
        lg = jnp.take_along_axis(logits, prev, axis=1)[:, 0]  # (B, V)
        if temperature == 0.0:
            nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            qfull = lg  # structural placeholder; greedy never reads q
        else:
            qfull = _spec_probs(lg, temperature, top_k, top_p)
            drng, sub = jax.random.split(drng)
            nxt = jax.random.categorical(
                sub, jnp.log(jnp.maximum(qfull, 1e-30))).astype(jnp.int32)
        write = cols == (pos + i)[:, None]
        buf = jnp.where(write, nxt[:, None], buf)
        return (buf, pos, i + 1, drng), (nxt, qfull)

    def body(carry):
        buf, pos, done, rng, nblk = carry
        rng, r_draft, r_u, r_resid, r_bonus = jax.random.split(rng, 5)
        (buf, _, _, _), (xs, qs) = lax.scan(
            draft_step, (buf, pos, jnp.zeros((), jnp.int32), r_draft),
            None, length=gamma)
        xs = jnp.moveaxis(xs, 0, 1)             # (B, gamma)
        qs = jnp.moveaxis(qs, 0, 1)             # (B, gamma, V)
        # ONE target forward scores all proposals + the bonus position
        logits = target.apply({"params": t_params}, buf)     # (B, W, V)
        idx = (pos[:, None] - 1 + jnp.arange(gamma + 1)[None])[..., None]
        p_logits = jnp.take_along_axis(logits, idx, axis=1)  # (B, g+1, V)
        if temperature == 0.0:
            toks, count = _greedy_accept(p_logits, xs)
        else:
            p = _spec_probs(p_logits, temperature, top_k, top_p)
            u = jax.random.uniform(r_u, xs.shape)
            toks, count = speculative_accept(p, qs, xs, u, r_resid,
                                             r_bonus)
        # clamp to remaining room; finished rows write nothing
        count = jnp.where(done, 0, jnp.minimum(count, max_len - pos))
        in_block = (cols >= pos[:, None]) & (cols < (pos + count)[:, None])
        slot = jnp.clip(cols - pos[:, None], 0, gamma)
        vals = jnp.take_along_axis(toks, slot, axis=1)       # (B, W)
        buf = jnp.where(in_block, vals, buf)
        if eos_id is not None:
            # a generated EOS inside the block finishes the row; the
            # trailing-cleanup pass pads everything after it
            hit = jnp.any(in_block & (buf == eos_id), axis=1)
            done = done | hit
        pos = pos + count
        done = done | (pos >= max_len)
        return buf, pos, done, rng, nblk + 1

    def cond(carry):
        _, pos, done, _, _ = carry
        return jnp.any(~done)

    buf, pos, done, _, nblk = lax.while_loop(
        cond, body, (buf, pos0, jnp.zeros((B,), bool), rng,
                     jnp.zeros((), jnp.int32)))
    return _eos_pad(buf[:, :max_len], P, eos_id), nblk


def rewind_cache(cache, new_idx):
    """Roll every layer's KV-cache cursor back to ``new_idx`` — the
    speculative REJECTION primitive: stale K/V rows beyond the cursor are
    masked out by the decode attend (`valid = pos <= idx + i`) and
    overwritten by later feeds, so rewinding is just resetting the per-
    layer ``idx`` leaves."""
    import jax.tree_util as jtu

    def _rewind(path, leaf):
        last = path[-1]
        key = getattr(last, "key", None)
        if key == "idx":
            return jnp.asarray(new_idx, leaf.dtype)
        return leaf

    return jtu.tree_map_with_path(_rewind, cache)


def _eos_pad(out, P, eos_id):
    """Fixed-length EOS contract shared by both decode paths: everything
    after the first GENERATED eos becomes eos (matches generate())."""
    if eos_id is None:
        return out
    gcols = jnp.arange(out.shape[1])[None]
    is_eos = (out == eos_id) & (gcols >= P)
    after = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) \
        - is_eos.astype(jnp.int32) > 0
    return jnp.where(after & (gcols >= P), eos_id, out)


@functools.partial(jax.jit, static_argnums=(0, 1, 5, 6, 7, 9, 10, 12))
def _speculative_cached(target, draft, t_state, d_state, prompt, max_len,
                        gamma, temperature, rng, top_k, top_p, eos_id,
                        width):
    """KV-cached speculative decode: the draft runs ``gamma`` one-token
    cached steps, the target verifies the whole block with ONE CHUNKED
    cached feed (gamma+1 query tokens attending cache + intra-chunk
    causal), and rejection is a cache-cursor rewind. Batch rows advance
    in LOCKSTEP by the block's minimum accepted count (``pos`` is a
    SCALAR — one cursor for the whole batch, mirroring the scalar
    per-layer cache cursors); per-token marginals are unchanged
    (truncating an accepted prefix cannot bias it), B=1 serving loses
    nothing. Returns ``(buffer, n_blocks)``."""
    from horovod_tpu.models.generate import _chunk_feed, _decode_feed

    t_params, t_cache = t_state
    d_params, d_cache = d_state
    B, P = prompt.shape
    W = width
    cols = jnp.arange(W)[None]
    buf = jnp.zeros((B, W), jnp.int32)
    buf = lax.dynamic_update_slice(buf, prompt, (0, 0))

    t_chunk = _chunk_feed(target, t_params)
    d_chunk = _chunk_feed(draft, d_params)
    d_feed = _decode_feed(draft, d_params)
    # Chunked prefill (THE shared implementation — bounded chunk size):
    # prompt tokens 0..P-2 enter each cache, cursor lands at P-1.
    from horovod_tpu.models.generate import _prefill_cache
    t_cache = _prefill_cache(t_chunk, t_cache, prompt)
    d_cache = _prefill_cache(d_chunk, d_cache, prompt)

    def body(carry):
        buf, t_cache, d_cache, pos, done, rng, nblk = carry

        rng, r_draft, r_u, r_resid, r_bonus = jax.random.split(rng, 5)

        def dstep(c, i):
            dbuf, dc, drng = c
            tok = lax.dynamic_slice(dbuf, (0, pos + i - 1), (B, 1))
            dc, lg = d_feed(dc, tok, pos + i - 1)
            if temperature == 0.0:
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                qfull = lg
            else:
                qfull = _spec_probs(lg, temperature, top_k, top_p)
                drng, sub = jax.random.split(drng)
                nxt = jax.random.categorical(
                    sub, jnp.log(jnp.maximum(qfull, 1e-30))).astype(
                        jnp.int32)
            write = cols == pos + i
            dbuf = jnp.where(write, nxt[:, None], dbuf)
            return (dbuf, dc, drng), (nxt, qfull)

        (buf, d_cache, _), (xs, qs) = lax.scan(
            dstep, (buf, d_cache, r_draft), jnp.arange(gamma))
        xs = jnp.moveaxis(xs, 0, 1)
        qs = jnp.moveaxis(qs, 0, 1)
        # ONE chunked target feed verifies the block: tokens at positions
        # pos-1 .. pos+gamma-1
        chunk = lax.dynamic_slice(buf, (0, pos - 1), (B, gamma + 1))
        t_cache, p_logits = t_chunk(t_cache, chunk, pos - 1)
        if temperature == 0.0:
            toks, count = _greedy_accept(p_logits, xs)
        else:
            p = _spec_probs(p_logits, temperature, top_k, top_p)
            u = jax.random.uniform(r_u, xs.shape)
            toks, count = speculative_accept(p, qs, xs, u, r_resid,
                                             r_bonus)
        # lockstep: advance by the minimum accepted count over active
        # rows (scalar cursors), bounded by the remaining room
        count = jnp.where(done, gamma + 1, count)
        adv = jnp.minimum(jnp.min(count), max_len - pos)
        per_row = jnp.where(done, 0, adv)
        in_block = (cols >= pos) & (cols < pos + per_row[:, None])
        slot = jnp.clip(cols - pos, 0, gamma)
        vals = jnp.take_along_axis(toks, slot, axis=1)
        buf = jnp.where(in_block, vals, buf)
        if eos_id is not None:
            hit = jnp.any(in_block & (buf == eos_id), axis=1)
            done = done | hit
        # Re-feed the draft cache with the COMMITTED block before
        # rewinding: the gamma-step draft scan never fed x_{gamma-1}, so
        # a FULLY-accepted block would wind the cursor past a row the
        # draft never wrote — a permanent garbage K/V row silently
        # degrading every later proposal. One cheap chunked draft feed
        # writes every committed row; rows at/beyond the cursor stay
        # masked.
        chunk2 = lax.dynamic_slice(buf, (0, pos - 1), (B, gamma + 1))
        d_cache = rewind_cache(d_cache, pos - 1)
        d_cache, _ = d_chunk(d_cache, chunk2, pos - 1)
        # rewind both cursors to the verified frontier: tokens
        # 0..pos+adv-2 are committed, the token at pos+adv-1 is the next
        # feed's input
        new_cursor = pos - 1 + adv
        t_cache = rewind_cache(t_cache, new_cursor)
        d_cache = rewind_cache(d_cache, new_cursor)
        pos = pos + adv
        done = done | (pos >= max_len)
        return buf, t_cache, d_cache, pos, done, rng, nblk + 1

    def cond(carry):
        _, _, _, pos, done, _, _ = carry
        return jnp.any(~done)

    buf, _, _, _, _, _, nblk = lax.while_loop(
        cond, body, (buf, t_cache, d_cache, jnp.asarray(P, jnp.int32),
                     jnp.zeros((B,), bool), rng, jnp.zeros((), jnp.int32)))
    return _eos_pad(buf[:, :max_len], P, eos_id), nblk


def speculative_generate(target_model, target_params, draft_model,
                         draft_params, prompt, max_len, gamma=4,
                         temperature=0.0, rng=None, top_k=0, top_p=1.0,
                         eos_id=None, use_cache=False, return_stats=False):
    """Speculative decoding: generate up to ``max_len`` total tokens with
    the TARGET model's output distribution at a fraction of its forward
    passes.

    - ``target_model``/``draft_model``: causal LMs with the
      :func:`horovod_tpu.models.generate.generate` contract (e.g. a large
      and a small :class:`~horovod_tpu.models.GPT` sharing a tokenizer).
      Both need position capacity for ``max_len + gamma + 1`` (the draft
      runs ``gamma`` positions ahead of the accepted text).
    - ``gamma``: proposals per block; each block costs ``gamma`` draft
      forwards + ONE target forward and yields 1..gamma+1 tokens.
    - ``temperature=0``: output is BIT-IDENTICAL to target-only greedy
      decoding. Otherwise the emitted tokens follow the target's
      tempered/filtered distribution exactly (Leviathan et al. 2023,
      thm. 1) — NOT merely approximately.
    - ``top_k``/``top_p``/``eos_id``: as in ``generate`` (EOS latches and
      pads to ``max_len``).
    - ``use_cache=True``: KV-cached speculation (dense GPT/LLaMA) — the
      draft runs one-token cached steps, the target verifies each block
      with ONE CHUNKED cached feed (gamma+1 query tokens against the
      cache, causal within the chunk), and a rejection is a cache-cursor
      rewind (:func:`rewind_cache`). Batch rows advance in lockstep by
      the block-minimum accepted count (scalar cache cursors); B=1
      serving loses nothing. Greedy output remains bit-identical to
      target-only decoding.

    Returns (B, max_len) int32: prompt + generated tokens. Batch rows
    advance independently (per-row acceptance counts; lockstep under
    ``use_cache``). ``return_stats=True`` returns ``(tokens, stats)``
    with ``stats["blocks"]`` — the number of speculation blocks (=
    target forwards); ``(max_len - P) / blocks`` is the realized
    tokens-per-target-forward, the acceptance-rate diagnostic.
    """
    B, P = prompt.shape
    if not 1 <= P <= max_len:
        raise ValueError(
            f"prompt length {P} must be in [1, max_len={max_len}]")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k < 0 or not 0.0 < top_p <= 1.0:
        raise ValueError(f"need top_k >= 0 and 0 < top_p <= 1, got "
                         f"top_k={top_k}, top_p={top_p}")
    if temperature != 0.0 and rng is None:
        raise ValueError("sampling (temperature != 0) requires rng")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    width = int(max_len) + int(gamma) + 1
    _check_position_capacity(target_model, width)
    _check_position_capacity(draft_model, width)
    prompt = jnp.asarray(prompt, jnp.int32)
    if use_cache:
        import dataclasses as _dc

        from horovod_tpu.models.generate import init_decode_cache
        t_dec = _dc.replace(target_model, decode=True)
        d_dec = _dc.replace(draft_model, decode=True)
        t_cache = init_decode_cache(t_dec, prompt[:, :1], pos=0)
        d_cache = init_decode_cache(d_dec, prompt[:, :1], pos=0)
        out, nblk = _speculative_cached(
            t_dec, d_dec, (target_params, t_cache),
            (draft_params, d_cache), prompt, int(max_len), int(gamma),
            float(temperature), rng, int(top_k), float(top_p),
            None if eos_id is None else int(eos_id), width)
    else:
        out, nblk = _speculative(
            target_model, draft_model, target_params, draft_params,
            prompt, int(max_len), int(gamma), float(temperature), rng,
            int(top_k), float(top_p),
            None if eos_id is None else int(eos_id), width)
    if return_stats:
        return out, {"blocks": int(nblk)}
    return out
