from horovod_tpu.models.resnet import ResNet, ResNet18, ResNet34, ResNet50, ResNet101, ResNet152  # noqa: F401
from horovod_tpu.models.bert import BertConfig, BertModel, BertForPreTraining  # noqa: F401
from horovod_tpu.models.mlp import MLP  # noqa: F401
from horovod_tpu.models.gpt import (  # noqa: F401
    GPT, GPTConfig, GPTEmbed, GPTHead, GPTMoEBlock,
)
from horovod_tpu.models.vgg import VGG, VGG11, VGG13, VGG16, VGG19  # noqa: F401
from horovod_tpu.models.inception import InceptionV3  # noqa: F401
from horovod_tpu.models.vit import ViT, ViTConfig  # noqa: F401
from horovod_tpu.models.llama import Llama, LlamaBlock, LlamaConfig  # noqa: F401
from horovod_tpu.models.t5 import (  # noqa: F401
    T5, T5Config, t5_beam_decode, t5_generate, t5_greedy_decode,
)
from horovod_tpu.models.generate import (  # noqa: F401
    beam_search, generate, prefill_prefix,
)
from horovod_tpu.models.lora import (  # noqa: F401
    adapter_loss_fn, adapter_loss_fn_via_extra, lora_apply, lora_init,
    lora_merge, lora_wire_numbers,
)
from horovod_tpu.models.speculative import (  # noqa: F401
    speculative_accept, speculative_generate,
)
