"""Autoregressive generation for the causal-LM zoo (GPT).

The reference ships no inference tooling (docs/inference.rst just points at
graph-stripping scripts); this is the TPU-native serving loop for the
models this framework trains.

TPU-first choices: the whole decode loop is ONE compiled program — a
``lax.scan`` over token positions with a fixed-length buffer (static
shapes; no per-token host round-trips). Each step re-runs the forward on
the full buffer with positions beyond the current length masked by the
causal structure itself (tokens are only appended, and causal attention
ignores the future), so correctness needs no KV-cache bookkeeping; at the
modest lengths a single chip serves this keeps the MXU busy with large
batched matmuls. Sampling: greedy or temperature with a jax PRNG key.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def init_decode_cache(decoder, *args, **kwargs):
    """Zeroed KV-cache tree for a decode-mode model, STRUCTURE via
    eval_shape of ``decoder.init(rng, *args, **kwargs)`` — no throwaway
    params, no compute. init() itself would also MUTATE the cache it
    returns (cursor advanced past the traced forward plus a garbage K/V
    row), so callers always start from zeros."""
    shapes = jax.eval_shape(
        lambda: decoder.init(jax.random.PRNGKey(0), *args,
                             **kwargs)["cache"])
    return jax.tree.map(lambda t: jnp.zeros(t.shape, t.dtype), shapes)


def _filter_logits(logits, top_k, top_p):
    """Top-k / nucleus filtering on (B, V) logits (static k/p; no-ops at
    k=0 / p=1). Masked entries get a large-negative so categorical never
    picks them."""
    neg = jnp.asarray(-1e30, logits.dtype)
    if top_k:
        k = min(top_k, logits.shape[-1])   # clamp: top_k > V means keep all
        kth = lax.top_k(logits, k)[0][:, -1][:, None]
        logits = jnp.where(logits >= kth, logits, neg)
    if top_p < 1.0:
        srt = jnp.sort(logits, axis=-1)[:, ::-1]              # descending
        probs = jax.nn.softmax(srt.astype(jnp.float32), axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix whose mass reaches top_p (always >= 1)
        keep = cum - probs < top_p
        thresh = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1)[:, None]
        logits = jnp.where(logits >= thresh.astype(logits.dtype),
                           logits, neg)
    return logits


def _absorb_eos(nxt, done, eos_id):
    """Fixed-length EOS semantics: a finished row keeps emitting EOS
    (padding) and its ``done`` flag latches. ``eos_id=None`` = no EOS."""
    if eos_id is None:
        return nxt, done
    nxt = jnp.where(done, jnp.asarray(eos_id, nxt.dtype), nxt)
    return nxt, done | (nxt == eos_id)


def _decode_feed(decoder, params):
    """One cached decode step: feed a (B, s) token chunk starting at
    (traced) position ``t`` (s=1 for the classic one-token step), return
    the updated cache and the FIRST fed token's next-token logits
    (B, V) — chunk consumers that need every row use their own feed
    (models/speculative.py chunk_feed)."""

    def feed(cache, tok, t):
        logits, upd = decoder.apply(
            {"params": params, "cache": cache}, tok, pos=t,
            mutable=["cache"])
        return upd["cache"], logits[:, 0]

    return feed


def _chunk_feed(decoder, params):
    """Multi-token cached feed returning ALL ``s`` logit rows (the
    one-token :func:`_decode_feed` keeps only the first) — used by the
    chunked prefill, prefix caching, and the speculative verifier."""

    def feed(cache, toks, t):
        logits, upd = decoder.apply(
            {"params": params, "cache": cache}, toks, pos=t,
            mutable=["cache"])
        return upd["cache"], logits

    return feed


def _prefill_cache(feed, cache, prompt, chunk=512, start=0, end=None):
    """Teacher-force prompt tokens ``[start, end)`` into the cache — in
    CHUNKED feeds of up to ``chunk`` tokens: the decode path accepts
    s-token chunks (causal within the chunk), so time-to-first-token
    costs ~P/chunk forwards instead of a P-1-step scan, while the
    per-layer fp32 score transient stays bounded at
    (B, heads, chunk, cache_len) — one giant chunk would peak prefill
    memory far above the decode loop's. ``end`` defaults to P-1 (the
    last prompt token is the first decode step's input); ``start > 0``
    continues from a precomputed prefix cache (:func:`prefill_prefix`).
    Logits are discarded (prefill wants only the K/V rows)."""
    end = prompt.shape[1] - 1 if end is None else end
    for s in range(start, end, chunk):
        cache, _ = feed(cache, prompt[:, s:min(s + chunk, end)], s)
    return cache


def prefill_prefix(model, params, prefix):
    """Precompute the decode cache for a FIXED prompt prefix (the serving
    system-prompt pattern): feed ALL ``Pp`` prefix tokens once, reuse the
    result across ``generate(..., use_cache=True, prefix_state=state)``
    calls — each call then prefills only the tokens AFTER the prefix.

    ``prefix``: (B, Pp) int32, or (1, Pp) to be tiled to any decode
    batch. Returns an opaque state dict; the prompt passed to generate
    must still carry the FULL sequence (prefix + continuation) and must
    begin with exactly these prefix tokens (validated)."""
    import dataclasses as _dc

    prefix = jnp.asarray(prefix, jnp.int32)
    # fail loudly, like every decode entry point: an over-long prefix
    # would silently CLAMP its cache writes onto the last rows
    _check_position_capacity(model, prefix.shape[1])
    decoder = _dc.replace(model, decode=True)
    cache = init_decode_cache(decoder, prefix[:, :1], pos=0)
    cache = _prefill_cache(_chunk_feed(decoder, params), cache, prefix,
                           end=prefix.shape[1])
    return {"cache": cache, "len": int(prefix.shape[1]), "prefix": prefix}


def sample_or_argmax(logits, rng, temperature, top_k, top_p):
    """Next token from (B, V) logits — THE sampling branch for every
    decode path (causal and seq2seq): argmax at temperature 0, else a
    tempered categorical over the top-k / nucleus filtered distribution
    (temper BEFORE filtering, the standard top-p semantics). Returns
    ``(token_ids, rng)`` with the key split exactly once per sampled
    step, so cached and re-forward decodes share one PRNG stream."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), rng
    rng, sub = jax.random.split(rng)
    nxt = jax.random.categorical(
        sub, _filter_logits(logits / temperature, top_k,
                            top_p)).astype(jnp.int32)
    return nxt, rng


@functools.partial(jax.jit, static_argnums=(0, 3, 4, 6, 7, 8, 9))
def _generate_cached(decoder, state, prompt, max_len, temperature, rng,
                     top_k, top_p, eos_id=None, prefill_start=0):
    """KV-cache decode: ONE token per step through the cache-enabled model
    (O(1) projections per step; attention reads the filled prefix). A
    chunked prefill teacher-forces the prompt into the cache (no
    sampling, so the PRNG stream aligns with the re-forward path), then
    a decode scan samples one token per step. ``prefill_start > 0``:
    the supplied cache already holds a prefix (:func:`prefill_prefix`)
    and only the later prompt tokens are fed."""
    params, cache = state
    B, P = prompt.shape
    buf = jnp.zeros((B, max_len), jnp.int32)
    buf = lax.dynamic_update_slice(buf, prompt, (0, 0))

    feed = _decode_feed(decoder, params)
    cache = _prefill_cache(feed, cache, prompt, start=prefill_start)

    def step(carry, t):
        buf, cache, rng, done = carry
        tok = jax.lax.dynamic_slice_in_dim(buf, t, 1, axis=1)
        cache, nxt_logits = feed(cache, tok, t)
        nxt, rng = sample_or_argmax(nxt_logits, rng, temperature, top_k,
                                    top_p)
        nxt, done = _absorb_eos(nxt, done, eos_id)
        buf = lax.dynamic_update_slice(buf, nxt[:, None], (0, t + 1))
        return (buf, cache, rng, done), None

    done0 = jnp.zeros((B,), bool)
    (buf, _, _, _), _ = lax.scan(step, (buf, cache, rng, done0),
                                 jnp.arange(P - 1, max_len - 1))
    return buf


@functools.partial(jax.jit, static_argnums=(0, 3, 4, 6, 7, 8))
def _generate(model, params, prompt, max_len, temperature, rng,
              top_k, top_p, eos_id=None):
    # ``model`` is static: flax modules hash by their dataclass config, so
    # repeated generate() calls with the same model/max_len/temperature
    # reuse one compiled program.
    B, P = prompt.shape

    buf = jnp.zeros((B, max_len), jnp.int32)
    buf = lax.dynamic_update_slice(buf, prompt, (0, 0))

    def step(carry, t):
        buf, rng, done = carry
        logits = model.apply({"params": params}, buf)   # (B, max_len, V)
        # logits at position t-1 predict token t
        nxt_logits = jax.lax.dynamic_slice_in_dim(
            logits, t - 1, 1, axis=1)[:, 0]         # (B, V)
        nxt, rng = sample_or_argmax(nxt_logits, rng, temperature, top_k,
                                    top_p)
        nxt, done = _absorb_eos(nxt, done, eos_id)
        buf = lax.dynamic_update_slice(buf, nxt[:, None], (0, t))
        return (buf, rng, done), None

    # Positions < P are the prompt: start decoding at P (one forward per
    # GENERATED token, none wasted re-writing prompt tokens).
    done0 = jnp.zeros((B,), bool)
    (buf, _, _), _ = lax.scan(step, (buf, rng, done0),
                              jnp.arange(P, max_len))
    return buf


def _check_position_capacity(model, max_len):
    """Fail loudly when ``max_len`` exceeds the model's position table.

    Learned position embeddings are fetched with a clamping gather, so an
    out-of-range decode would silently reuse the last position row and
    emit plausible-looking junk (the cached path's dynamic_update_slice
    clamps the same way). Applies to every decode path, not just the
    cached one."""
    cap = getattr(getattr(model, "config", None),
                  "max_position_embeddings", None)
    if cap is not None and max_len > cap:
        raise ValueError(
            f"max_len {max_len} exceeds the model's position capacity "
            f"(max_position_embeddings={cap})")


def beam_init_scores(B, k):
    """All beams start identical: only beam 0 may seed the first
    expansion, or the top-k would fill with k copies of the same
    hypothesis."""
    scores = jnp.where(jnp.arange(k) == 0, 0.0, -jnp.inf)
    return jnp.broadcast_to(scores[None], (B, k)).astype(jnp.float32)


def beam_expand(logp, bufs, scores, t):
    """One beam expansion shared by the causal and seq2seq searches:
    joint (beam, token) top-k over ``scores + logp``, beams reordered by
    origin, the chosen tokens written at position ``t``.
    ``logp``: (B, k, V) next-token log-probs; ``bufs``: (B, k, L).
    Returns ``(bufs, scores, origin)`` — ``origin[b, j]`` is the previous
    beam index the new beam j continues (the cached search reorders its
    KV caches by it; the re-forward searches ignore it)."""
    B, k, V = logp.shape
    cand = (scores[:, :, None] + logp).reshape(B, k * V)
    scores, idx = lax.top_k(cand, k)                    # (B, k)
    beam, tok = idx // V, (idx % V).astype(jnp.int32)
    bufs = jnp.take_along_axis(bufs, beam[:, :, None], axis=1)
    bufs = lax.dynamic_update_slice(bufs, tok[:, :, None], (0, 0, t))
    return bufs, scores, beam


def beam_best(bufs, scores):
    """Best hypothesis per batch row: ((B, L) sequences, (B,) scores)."""
    best = jnp.argmax(scores, axis=1)
    return (jnp.take_along_axis(bufs, best[:, None, None], axis=1)[:, 0],
            jnp.take_along_axis(scores, best[:, None], axis=1)[:, 0])


def beam_step_eos(logp, bufs, scores, fin_bufs, fin_scores, t, prompt_len,
                  eos_id, length_penalty):
    """One beam expansion with a TRUE finished-hypothesis pool (fixed
    shapes: k live + k finished slots), shared by the causal and seq2seq
    searches.

    Each live beam's finish-now candidate (its score plus the EOS
    log-prob, GNMT-normalized by generated length including the EOS) is
    merged into the finished pool by top-k over the 2k candidates, so a
    completed hypothesis can never be evicted by later live expansions —
    the property the simpler absorbing-state formulation lacks. Live
    beams then expand with the EOS column masked out (a live buffer never
    contains EOS, so prompt tokens can never falsely finish anything)."""
    B, k, V = logp.shape
    L = bufs.shape[-1]
    fin_cand_raw = scores + logp[:, :, eos_id]               # (B, k)
    gen_len = jnp.maximum(t - prompt_len + 1, 1).astype(jnp.float32)
    fin_cand = fin_cand_raw / (gen_len ** length_penalty
                               if length_penalty else 1.0)
    # the finished buffer: the hypothesis so far, EOS-padded from t on
    pos = jnp.arange(L)
    cand_bufs = jnp.where(pos[None, None, :] >= t,
                          jnp.asarray(eos_id, bufs.dtype), bufs)
    all_scores = jnp.concatenate([fin_scores, fin_cand], axis=1)  # (B, 2k)
    all_bufs = jnp.concatenate([fin_bufs, cand_bufs], axis=1)
    fin_scores, idx = lax.top_k(all_scores, k)
    fin_bufs = jnp.take_along_axis(all_bufs, idx[:, :, None], axis=1)
    live_logp = logp.at[:, :, eos_id].set(-jnp.inf)
    bufs, scores, origin = beam_expand(live_logp, bufs, scores, t)
    return bufs, scores, fin_bufs, fin_scores, origin


def beam_reorder_cache(cache, origin, B, k):
    """Reorder decode-cache rows so each new beam inherits its ORIGIN
    beam's history (shared by the causal and seq2seq cached searches).
    Only batch-carrying leaves (leading dim B*k) are gathered; scalar
    bookkeeping (the cache cursor) is beam-invariant."""
    Bk = B * k
    flat_origin = (jnp.arange(B)[:, None] * k + origin).reshape(Bk)
    return jax.tree_util.tree_map(
        lambda c: jnp.take(c, flat_origin, axis=0)
        if getattr(c, "ndim", 0) >= 1 and c.shape[0] == Bk else c, cache)


def beam_finalize(bufs, scores, fin_bufs, fin_scores, prompt_len, eos_id,
                  length_penalty):
    """Best hypothesis per row across the live beams (normalized by the
    full generated span) AND the finished pool (already normalized at
    finish time). Without an EOS the pool is empty and this is plain
    best-of-live selection."""
    B, k, L = bufs.shape
    if length_penalty:
        scores = scores / float(max(L - prompt_len, 1)) ** length_penalty
    if eos_id is None:
        return beam_best(bufs, scores)
    return beam_best(jnp.concatenate([fin_bufs, bufs], axis=1),
                     jnp.concatenate([fin_scores, scores], axis=1))


@functools.partial(jax.jit, static_argnums=(0, 3, 4, 5, 6))
def _beam_search(model, params, prompt, max_len, num_beams, eos_id,
                 length_penalty):
    B, P = prompt.shape
    k = num_beams
    bufs = jnp.zeros((B, k, max_len), jnp.int32)
    bufs = lax.dynamic_update_slice(
        bufs, jnp.broadcast_to(prompt[:, None], (B, k, P)), (0, 0, 0))
    scores = beam_init_scores(B, k)
    fin_bufs = jnp.zeros_like(bufs)
    fin_scores = jnp.full((B, k), -jnp.inf, jnp.float32)

    def step(carry, t):
        bufs, scores, fin_bufs, fin_scores = carry
        logits = model.apply({"params": params},
                             bufs.reshape(B * k, max_len))
        logp = jax.nn.log_softmax(
            logits[:, t - 1].astype(jnp.float32)).reshape(B, k, -1)
        if eos_id is None:
            bufs, scores, _ = beam_expand(logp, bufs, scores, t)
        else:
            bufs, scores, fin_bufs, fin_scores, _ = beam_step_eos(
                logp, bufs, scores, fin_bufs, fin_scores, t, P, eos_id,
                length_penalty)
        return (bufs, scores, fin_bufs, fin_scores), None

    (bufs, scores, fin_bufs, fin_scores), _ = lax.scan(
        step, (bufs, scores, fin_bufs, fin_scores),
        jnp.arange(P, max_len))
    return beam_finalize(bufs, scores, fin_bufs, fin_scores, P, eos_id,
                         length_penalty)


@functools.partial(jax.jit, static_argnums=(0, 3, 4, 5, 6))
def _beam_search_cached(decoder, state, prompt, max_len, num_beams, eos_id,
                        length_penalty):
    """KV-cache beam search: ONE token per step per hypothesis through
    the decode-mode model; after each expansion the per-layer caches are
    REORDERED along the (B*k) batch axis by each new beam's origin, so
    every cache row always holds its hypothesis's own history. The
    prompt prefills at batch B once (every beam shares it) and the cache
    rows are repeated to B*k for the decode scan — 1/k the prefill
    work."""
    params, cache = state                    # cache leaves at batch B
    B, P = prompt.shape
    k = num_beams
    Bk = B * k
    bufs = jnp.zeros((B, k, max_len), jnp.int32)
    bufs = lax.dynamic_update_slice(
        bufs, jnp.broadcast_to(prompt[:, None], (B, k, P)), (0, 0, 0))
    scores = beam_init_scores(B, k)
    fin_bufs = jnp.zeros_like(bufs)
    fin_scores = jnp.full((B, k), -jnp.inf, jnp.float32)

    feed = _decode_feed(decoder, params)
    cache = _prefill_cache(feed, cache, prompt)
    # beam-minor replication: row b*k + j is (batch b, beam j), matching
    # bufs.reshape(B*k, L); scalar bookkeeping (the cursor) has no batch
    # axis and is shared.
    cache = jax.tree_util.tree_map(
        lambda c: jnp.repeat(c, k, axis=0)
        if getattr(c, "ndim", 0) >= 1 and c.shape[0] == B else c, cache)

    def step(carry, t):
        bufs, scores, fin_bufs, fin_scores, cache = carry
        tok = lax.dynamic_slice_in_dim(bufs.reshape(Bk, max_len), t - 1, 1,
                                       axis=1)
        cache, logits = feed(cache, tok, t - 1)
        logp = jax.nn.log_softmax(
            logits.astype(jnp.float32)).reshape(B, k, -1)
        if eos_id is None:
            bufs, scores, origin = beam_expand(logp, bufs, scores, t)
        else:
            bufs, scores, fin_bufs, fin_scores, origin = beam_step_eos(
                logp, bufs, scores, fin_bufs, fin_scores, t, P, eos_id,
                length_penalty)
        cache = beam_reorder_cache(cache, origin, B, k)
        return (bufs, scores, fin_bufs, fin_scores, cache), None

    (bufs, scores, fin_bufs, fin_scores, _), _ = lax.scan(
        step, (bufs, scores, fin_bufs, fin_scores, cache),
        jnp.arange(P, max_len))
    return beam_finalize(bufs, scores, fin_bufs, fin_scores, P, eos_id,
                         length_penalty)


def beam_search(model, params, prompt, max_len, num_beams=4, eos_id=None,
                length_penalty=0.0, use_cache=False):
    """Beam-search decoding for the causal LMs: ONE compiled program, k
    hypotheses re-forwarded per step through the same fixed-length-buffer
    scheme as greedy :func:`generate`. Returns ``(sequences, scores)``:
    (B, max_len) int32 best hypotheses and their (length-normalized when
    ``length_penalty>0``) summed token log-probs. ``num_beams=1`` with no
    EOS reproduces greedy decoding exactly.

    ``eos_id``: a hypothesis that emits it is finished — it moves into a
    FINISHED pool (k slots, merged by normalized score, never evicted by
    later live expansions — true finished-set semantics) and pads with
    ``eos_id``; live beams keep competing with the EOS move excluded, so
    EOS tokens inside the prompt never count. ``length_penalty``:
    GNMT-style ``score / gen_len**alpha`` (generated length including
    the EOS) applied when each hypothesis finishes and to live beams at
    selection; 0 disables.

    ``use_cache``: KV-cache beam decode — O(1) projection work per
    hypothesis per step, with the per-layer caches reordered by beam
    origin after every expansion (dense GPT/LLaMA, like
    :func:`generate`'s cached path). Identical outputs to the
    re-forward search.
    """
    B, P = prompt.shape
    if not 1 <= P < max_len:
        raise ValueError(
            f"prompt length {P} must be in [1, max_len={max_len})")
    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    if length_penalty < 0:
        raise ValueError(
            f"length_penalty must be >= 0, got {length_penalty}")
    _check_position_capacity(model, max_len)
    prompt = jnp.asarray(prompt, jnp.int32)
    eos = None if eos_id is None else int(eos_id)
    if use_cache:
        import dataclasses as _dc
        decoder = _dc.replace(model, decode=True)
        # batch-B cache: the prompt prefill is shared across beams and
        # the rows are repeated to B*k inside the search
        cache = init_decode_cache(decoder, jnp.zeros((B, 1), jnp.int32),
                                  pos=0)
        return _beam_search_cached(decoder, (params, cache), prompt,
                                   int(max_len), int(num_beams), eos,
                                   float(length_penalty))
    return _beam_search(model, params, prompt, int(max_len),
                        int(num_beams), eos, float(length_penalty))


def generate(model, params, prompt, max_len, temperature=0.0, rng=None,
             use_cache=False, top_k=0, top_p=1.0, eos_id=None,
             prefix_state=None):
    """Generate up to ``max_len`` total tokens from ``prompt``.

    - ``model``: a causal LM whose ``apply({"params": p}, ids)`` returns
      next-token logits ``(B, L, V)`` (e.g. :class:`horovod_tpu.models.GPT`
      with ``max_position_embeddings >= max_len``).
    - ``prompt``: (B, P) int32 token ids, P <= max_len.
    - ``temperature``: 0 -> greedy argmax; otherwise categorical sampling
      (requires ``rng``).
    - ``top_k`` / ``top_p``: sampling filters (0 / 1.0 = off): keep only
      the k highest logits and/or the smallest nucleus of cumulative
      probability ``top_p`` before the categorical draw.
    - ``use_cache``: KV-cache decoding — one token per step with O(1)
      projection work (dense causal LMs: GPT and LLaMA; MoE blocks are
      unsupported; ``max_len`` must be within the model's
      ``max_position_embeddings``). Same outputs as the default
      full-re-forward path.
    - ``eos_id``: once a row GENERATES it, the row is finished and pads
      with ``eos_id`` to ``max_len`` (fixed shapes; slice at the first
      EOS to recover the variable-length output). EOS tokens inside the
      prompt do not count.
    - ``prefix_state`` (with ``use_cache=True``): a
      :func:`prefill_prefix` result — the cache already holds the shared
      prefix (system prompt), so only the prompt tokens after it are
      prefilled. ``prompt`` must still carry the FULL sequence and begin
      with the prefix tokens (validated; a (1, Pp) prefix cache is tiled
      to the prompt batch).

    Returns (B, max_len) int32: the prompt followed by generated tokens.
    The decode loop is one compiled program; like any jit, it retraces per
    distinct (model config, max_len, temperature, prompt SHAPE) — pad
    prompts to a fixed (B, P) for cache reuse across requests.
    """
    B, P = prompt.shape
    if not 1 <= P <= max_len:
        raise ValueError(
            f"prompt length {P} must be in [1, max_len={max_len}] "
            "(position 0 must come from the prompt)")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k < 0 or not 0.0 < top_p <= 1.0:
        raise ValueError(f"need top_k >= 0 and 0 < top_p <= 1, got "
                         f"top_k={top_k}, top_p={top_p}")
    if temperature != 0.0 and rng is None:
        raise ValueError("sampling (temperature != 0) requires rng")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    prompt = jnp.asarray(prompt, jnp.int32)
    _check_position_capacity(model, max_len)
    if prefix_state is not None and not use_cache:
        raise ValueError("prefix_state requires use_cache=True (the "
                         "prefix lives in the decode cache)")
    if use_cache:
        # KV-cache path: O(1) projection work per token instead of a full
        # re-forward (dense GPT/LLaMA; the cache model shares the params
        # tree).
        import dataclasses as _dc
        decoder = _dc.replace(model, decode=True)
        start = 0
        if prefix_state is not None:
            start = int(prefix_state["len"])
            pfx = prefix_state["prefix"]
            if start >= P:
                # The prefix cache's cursor already sits PAST its last
                # token; the decode scan must still feed prompt[:, P-1],
                # so a prefix covering the whole prompt would double-feed
                # it (duplicate K/V row, positions shifted by one).
                raise ValueError(
                    f"prefix length {start} must be SHORTER than the "
                    f"prompt ({P}): the last prompt token is the first "
                    f"decode input")
            if pfx.shape[0] not in (1, B):
                raise ValueError(
                    f"prefix batch {pfx.shape[0]} incompatible with "
                    f"prompt batch {B} (use 1 or {B})")
            want = np.broadcast_to(np.asarray(pfx), (B, start))
            if not np.array_equal(np.asarray(prompt[:, :start]), want):
                raise ValueError(
                    "prompt does not begin with the prefix the "
                    "prefix_state was built from — the cached K/V rows "
                    "would silently describe different text")
            cache = prefix_state["cache"]
            if pfx.shape[0] == 1 and B > 1:
                # tile the 1-row prefix cache to the decode batch
                # (scalar cursors stay shared)
                cache = jax.tree_util.tree_map(
                    lambda c: jnp.repeat(c, B, axis=0)
                    if getattr(c, "ndim", 0) >= 1 and c.shape[0] == 1
                    else c, cache)
        else:
            cache = init_decode_cache(decoder, prompt[:, :1], pos=0)
        return _generate_cached(decoder, (params, cache), prompt,
                                int(max_len), float(temperature), rng,
                                int(top_k), float(top_p),
                                None if eos_id is None else int(eos_id),
                                start)
    return _generate(model, params, prompt,
                     int(max_len), float(temperature), rng,
                     int(top_k), float(top_p),
                     None if eos_id is None else int(eos_id))
