"""Autoregressive generation for the causal-LM zoo (GPT).

The reference ships no inference tooling (docs/inference.rst just points at
graph-stripping scripts); this is the TPU-native serving loop for the
models this framework trains.

TPU-first choices: the whole decode loop is ONE compiled program — a
``lax.scan`` over token positions with a fixed-length buffer (static
shapes; no per-token host round-trips). Each step re-runs the forward on
the full buffer with positions beyond the current length masked by the
causal structure itself (tokens are only appended, and causal attention
ignores the future), so correctness needs no KV-cache bookkeeping; at the
modest lengths a single chip serves this keeps the MXU busy with large
batched matmuls. Sampling: greedy or temperature with a jax PRNG key.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.jit, static_argnums=(0, 3, 4))
def _generate(model, params, prompt, max_len, temperature, rng):
    # ``model`` is static: flax modules hash by their dataclass config, so
    # repeated generate() calls with the same model/max_len/temperature
    # reuse one compiled program.
    B, P = prompt.shape

    buf = jnp.zeros((B, max_len), jnp.int32)
    buf = lax.dynamic_update_slice(buf, prompt, (0, 0))

    def step(carry, t):
        buf, rng = carry
        logits = model.apply({"params": params}, buf)   # (B, max_len, V)
        # logits at position t-1 predict token t
        nxt_logits = jax.lax.dynamic_slice_in_dim(
            logits, t - 1, 1, axis=1)[:, 0]         # (B, V)
        if temperature == 0.0:
            nxt = jnp.argmax(nxt_logits, axis=-1).astype(jnp.int32)
        else:
            rng, sub = jax.random.split(rng)
            nxt = jax.random.categorical(
                sub, nxt_logits / temperature).astype(jnp.int32)
        buf = lax.dynamic_update_slice(buf, nxt[:, None], (0, t))
        return (buf, rng), None

    # Positions < P are the prompt: start decoding at P (one forward per
    # GENERATED token, none wasted re-writing prompt tokens).
    (buf, _), _ = lax.scan(step, (buf, rng), jnp.arange(P, max_len))
    return buf


def generate(model, params, prompt, max_len, temperature=0.0, rng=None):
    """Generate up to ``max_len`` total tokens from ``prompt``.

    - ``model``: a causal LM whose ``apply({"params": p}, ids)`` returns
      next-token logits ``(B, L, V)`` (e.g. :class:`horovod_tpu.models.GPT`
      with ``max_position_embeddings >= max_len``).
    - ``prompt``: (B, P) int32 token ids, P <= max_len.
    - ``temperature``: 0 -> greedy argmax; otherwise categorical sampling
      (requires ``rng``).

    Returns (B, max_len) int32: the prompt followed by generated tokens.
    The decode loop is one compiled program; like any jit, it retraces per
    distinct (model config, max_len, temperature, prompt SHAPE) — pad
    prompts to a fixed (B, P) for cache reuse across requests.
    """
    B, P = prompt.shape
    if not 1 <= P <= max_len:
        raise ValueError(
            f"prompt length {P} must be in [1, max_len={max_len}] "
            "(position 0 must come from the prompt)")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if temperature != 0.0 and rng is None:
        raise ValueError("sampling (temperature != 0) requires rng")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return _generate(model, params, jnp.asarray(prompt, jnp.int32),
                     int(max_len), float(temperature), rng)
