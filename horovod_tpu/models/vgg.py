"""VGG family in flax, TPU-first.

VGG-16 is one of the reference's three headline benchmark models (reference:
docs/benchmarks.rst:12-13 — ~68 % scaling efficiency at 512 GPUs; the
tf_cnn_benchmarks procedure of docs/benchmarks.rst:15-64).

TPU-first choices: bfloat16 activations with fp32 params (MXU native dtype),
channels-last NHWC (XLA TPU's preferred conv layout). The default
``classic_head=True`` keeps the published 7x7x512→4096 flatten head (exact
138M-param VGG-16, what the reference benchmarks); ``classic_head=False``
swaps in a global-average head — identical conv trunk with ~120M fewer
all-reduced parameters — for training-efficiency work.
"""

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# (num_convs, filters) per stage; maxpool between stages.
_CFGS = {
    11: ((1, 64), (1, 128), (2, 256), (2, 512), (2, 512)),
    13: ((2, 64), (2, 128), (2, 256), (2, 512), (2, 512)),
    16: ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512)),
    19: ((2, 64), (2, 128), (4, 256), (4, 512), (4, 512)),
}


class VGG(nn.Module):
    stage_cfg: Sequence = _CFGS[16]
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    classic_head: bool = True     # two 4096-wide FC layers, as published
    dropout_rate: float = 0.5
    train: bool = True

    @nn.compact
    def __call__(self, x, train=None):
        train = self.train if train is None else train
        conv = partial(nn.Conv, kernel_size=(3, 3), padding="SAME",
                       dtype=self.dtype)
        x = x.astype(self.dtype)
        for i, (reps, filters) in enumerate(self.stage_cfg):
            for j in range(reps):
                x = nn.relu(conv(filters, name=f"conv{i}_{j}")(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        if self.classic_head:
            x = x.reshape((x.shape[0], -1))
            for k in range(2):
                x = nn.relu(nn.Dense(4096, dtype=self.dtype,
                                     name=f"fc{k}")(x))
                x = nn.Dropout(self.dropout_rate,
                               deterministic=not train)(x)
        else:
            x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


VGG11 = partial(VGG, stage_cfg=_CFGS[11])
VGG13 = partial(VGG, stage_cfg=_CFGS[13])
VGG16 = partial(VGG, stage_cfg=_CFGS[16])
VGG19 = partial(VGG, stage_cfg=_CFGS[19])
