"""Vision Transformer (ViT) for the image benchmark zoo.

The reference framework ships no model zoo (its examples/ tree is absent
from the snapshot, SURVEY.md intro); models here exercise and benchmark the
distributed machinery. ViT rounds out the image family (ResNet/VGG/
Inception are conv-era; this is the MXU-friendliest image model: one patch
conv then pure matmuls) and reuses the framework's parallel encoder block —
``TPTransformerBlock(causal=False)`` — so tensor parallelism and the Pallas
flash-attention kernels apply to vision the same way they do to GPT/BERT.

TPU-first choices: bf16 activations with fp32 params/logits, NHWC patching
via one strided conv, learned position embeddings, pre-LN blocks, mean
pooling (no cls token: one less ragged dimension for the MXU).
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from horovod_tpu.parallel.tp import TPTransformerBlock


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    num_classes: int = 1000
    dtype: Any = jnp.float32
    tp_axis: Optional[str] = None   # tensor parallelism over heads/MLP
    use_flash: bool = False         # Pallas attention (ops/pallas)
    # jax.checkpoint each block's backward (see GPTConfig.remat)
    remat: bool = False

    @staticmethod
    def base(**kw):
        """ViT-B/16 (86M params)."""
        return ViTConfig(**kw)

    @staticmethod
    def tiny(**kw):
        base = dict(image_size=32, patch_size=8, hidden_size=64,
                    num_layers=2, num_heads=4, intermediate_size=128,
                    num_classes=10)
        base.update(kw)
        return ViTConfig(**base)


class ViT(nn.Module):
    """Patch embed -> encoder blocks -> mean-pool -> linear head."""
    config: ViTConfig

    @nn.compact
    def __call__(self, images):
        c = self.config
        p = c.patch_size
        x = nn.Conv(c.hidden_size, (p, p), strides=(p, p), padding="VALID",
                    dtype=c.dtype, name="patch_embed")(
                        images.astype(c.dtype))
        B = x.shape[0]
        x = x.reshape(B, -1, c.hidden_size)           # (B, n_patches, H)
        n_tok = x.shape[1]
        expect = (c.image_size // p) ** 2
        if n_tok != expect:
            # A smaller image would silently take the first rows of the 2-D
            # position grid (wrong geometry) — fail loudly instead.
            raise ValueError(
                f"got {n_tok} patches but config.image_size="
                f"{c.image_size} implies {expect}; resize the input or "
                "the config")
        pos = self.param("pos_emb", nn.initializers.normal(0.02),
                         (expect, c.hidden_size), jnp.float32)
        x = x + jnp.asarray(pos, c.dtype)[None]
        block_cls = nn.remat(TPTransformerBlock) if c.remat \
            else TPTransformerBlock
        for i in range(c.num_layers):
            x = block_cls(
                c.num_heads, c.hidden_size, c.intermediate_size,
                dtype=c.dtype, axis_name=c.tp_axis, causal=False,
                use_flash=c.use_flash, name=f"layer_{i}")(x)
        x = nn.LayerNorm(dtype=c.dtype, name="ln_f")(x)
        x = jnp.mean(x.astype(jnp.float32), axis=1)   # mean pool, fp32
        return nn.Dense(c.num_classes, dtype=jnp.float32, name="head")(x)
