"""Small MLP (MNIST-scale) — the smoke-test model, mirroring the role of the
reference's MNIST examples in CI (reference: .buildkite/gen-pipeline.sh MNIST
smoke runs)."""

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    features: Sequence[int] = (128, 64, 10)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for i, f in enumerate(self.features[:-1]):
            x = nn.relu(nn.Dense(f, dtype=self.dtype)(x))
        return nn.Dense(self.features[-1], dtype=jnp.float32)(x)
