"""Inception V3 in flax, TPU-first.

Inception V3 is one of the reference's three headline benchmark models
(reference: docs/benchmarks.rst:12-13 — ~90 % scaling efficiency at 512
GPUs; tf_cnn_benchmarks procedure of docs/benchmarks.rst:15-64).

Architecture per Szegedy et al. 2015 ("Rethinking the Inception
Architecture"): factorized 7x7 -> 1x7/7x1 convolutions, grid reductions with
parallel stride-2 branches, optional auxiliary classifier head.

TPU-first choices: bfloat16 activations with fp32 params/batch-stats,
channels-last NHWC, branch concat on the minor (channel) axis so XLA keeps
lane-dim layouts, BN without the conv bias (folded at inference by XLA).
"""

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    """conv -> batch-norm -> relu, the Inception basic cell."""
    filters: int
    kernel: tuple = (1, 1)
    strides: tuple = (1, 1)
    padding: str = "SAME"
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.filters, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not self.train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype)(x)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        c = partial(ConvBN, dtype=self.dtype, train=self.train)
        b1 = c(64)(x)
        b5 = c(64, (5, 5))(c(48)(x))
        b3 = c(96, (3, 3))(c(96, (3, 3))(c(64)(x)))
        bp = c(self.pool_features)(_avg_pool_same(x))
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    """Grid reduction 35x35 -> 17x17."""
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        c = partial(ConvBN, dtype=self.dtype, train=self.train)
        b3 = c(384, (3, 3), (2, 2), padding="VALID")(x)
        bd = c(96, (3, 3), (2, 2), padding="VALID")(
            c(96, (3, 3))(c(64)(x)))
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    """Factorized 7x7 block at 17x17."""
    channels_7x7: int
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        c = partial(ConvBN, dtype=self.dtype, train=self.train)
        c7 = self.channels_7x7
        b1 = c(192)(x)
        b7 = c(192, (7, 1))(c(c7, (1, 7))(c(c7)(x)))
        bd = c(192, (1, 7))(c(c7, (7, 1))(c(c7, (1, 7))(
            c(c7, (7, 1))(c(c7)(x)))))
        bp = c(192)(_avg_pool_same(x))
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    """Grid reduction 17x17 -> 8x8."""
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        c = partial(ConvBN, dtype=self.dtype, train=self.train)
        b3 = c(320, (3, 3), (2, 2), padding="VALID")(c(192)(x))
        b7 = c(192, (3, 3), (2, 2), padding="VALID")(
            c(192, (7, 1))(c(192, (1, 7))(c(192)(x))))
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    """Expanded-filter-bank block at 8x8."""
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        c = partial(ConvBN, dtype=self.dtype, train=self.train)
        b1 = c(320)(x)
        y = c(384)(x)
        b3 = jnp.concatenate([c(384, (1, 3))(y), c(384, (3, 1))(y)], axis=-1)
        z = c(384, (3, 3))(c(448)(x))
        bd = jnp.concatenate([c(384, (1, 3))(z), c(384, (3, 1))(z)], axis=-1)
        bp = c(192)(_avg_pool_same(x))
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionAux(nn.Module):
    """Auxiliary classifier over the 17x17 grid (training regularizer)."""
    num_classes: int
    dtype: Any = jnp.bfloat16
    train: bool = True

    @nn.compact
    def __call__(self, x):
        c = partial(ConvBN, dtype=self.dtype, train=self.train)
        x = nn.avg_pool(x, (5, 5), strides=(3, 3), padding="VALID")
        x = c(128)(x)
        x = c(768, (5, 5), padding="VALID")(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    aux_logits: bool = False
    dropout_rate: float = 0.5
    train: bool = True

    @nn.compact
    def __call__(self, x, train=None):
        train = self.train if train is None else train
        c = partial(ConvBN, dtype=self.dtype, train=train)
        x = x.astype(self.dtype)
        # Stem: 299x299x3 -> 35x35x192.
        x = c(32, (3, 3), (2, 2), padding="VALID")(x)
        x = c(32, (3, 3), padding="VALID")(x)
        x = c(64, (3, 3))(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = c(80)(x)
        x = c(192, (3, 3), padding="VALID")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        # 3 x InceptionA at 35x35.
        for pf in (32, 64, 64):
            x = InceptionA(pf, dtype=self.dtype, train=train)(x)
        x = InceptionB(dtype=self.dtype, train=train)(x)
        # 4 x InceptionC at 17x17.
        for c7 in (128, 160, 160, 192):
            x = InceptionC(c7, dtype=self.dtype, train=train)(x)
        aux = None
        if self.aux_logits and train:
            aux = InceptionAux(self.num_classes, dtype=self.dtype,
                               train=train)(x)
        x = InceptionD(dtype=self.dtype, train=train)(x)
        for _ in range(2):
            x = InceptionE(dtype=self.dtype, train=train)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        x = x.astype(jnp.float32)
        return (x, aux) if aux is not None else x
