"""ResNet family in flax, TPU-first.

The reference benchmarks Horovod with tf_cnn_benchmarks ResNet-50/101
(reference: docs/benchmarks.rst:15-64); this is the equivalent flagship model
for the TPU build's data-parallel benchmark (BASELINE.md target:
images/sec/chip, ResNet-50).

TPU-first choices: bfloat16 activations with fp32 params/batch-stats (MXU
native dtype), channels-last NHWC (XLA TPU's preferred conv layout), optional
cross-replica SyncBatchNorm over the DP axis.
"""

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

from horovod_tpu.ops.sync_batch_norm import SyncBatchNorm

ModuleDef = Any


class ResNetBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckResNetBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu
    sync_batch_norm_axis: str = None  # DP mesh axis for SyncBatchNorm
    train: bool = True
    # "conv": the classic 7x7 stride-2 stem. "space_to_depth": rearrange
    # 2x2 pixel blocks into channels first (224x224x3 -> 112x112x12) and
    # run an equal-receptive-field 4x4 stride-1 conv — the raw image's 3
    # input channels drive the MXU's 128 input lanes at 3/128 utilization,
    # which makes the stem a disproportionate share of step time on TPU
    # (the standard MLPerf-ResNet TPU stem transform).
    stem: str = "conv"

    @nn.compact
    def __call__(self, x, train=None):
        train = self.train if train is None else train
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        if self.sync_batch_norm_axis is not None:
            norm = partial(SyncBatchNorm, use_running_average=not train,
                           axis_name=self.sync_batch_norm_axis,
                           momentum=0.9, dtype=self.dtype)
        else:
            norm = partial(nn.BatchNorm, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5, dtype=self.dtype)

        x = x.astype(self.dtype)
        if self.stem == "space_to_depth":
            B, H, W, C = x.shape
            if H % 2 or W % 2:
                raise ValueError(
                    f"space_to_depth stem needs even spatial dims, got "
                    f"{(H, W)}")
            # (B, H, W, C) -> (B, H/2, W/2, 4C): each output pixel carries
            # its 2x2 source block; a 4x4 stride-1 window then spans the
            # same 8x8 input field as the padded 7x7 stride-2 conv.
            x = x.reshape(B, H // 2, 2, W // 2, 2, C)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
                B, H // 2, W // 2, 4 * C)
            x = conv(self.num_filters, (4, 4), (1, 1), padding="SAME",
                     name="conv_init")(x)
        elif self.stem == "conv":
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r} "
                             "(use 'conv' or 'space_to_depth')")
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i, conv=conv,
                                   norm=norm, act=self.act, strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                   block_cls=BottleneckResNetBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                    block_cls=BottleneckResNetBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3],
                    block_cls=BottleneckResNetBlock)
