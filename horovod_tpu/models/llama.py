"""LLaMA-family decoder-only LM: RMSNorm, RoPE, SwiGLU, grouped-query
attention.

The reference framework has no model zoo (SURVEY.md intro) — models here
exercise and benchmark the distributed machinery. Where :class:`GPT` is the
GPT-2 lineage (learned positions, LayerNorm, gelu MLP, MHA), this is the
modern open-weights lineage: rotary positions applied inside attention
(``parallel/tp.py`` ``apply_rope``), pre-RMSNorm, gated SwiGLU MLP, and
``num_kv_heads < num_heads`` grouped-query attention whose decode-time KV
cache shrinks by the group factor.

TPU-first choices mirror GPT's: bf16 activations with fp32 params/logits,
fused projections (QKV in one column-parallel matmul, gate+up in another),
static shapes, and shape-invariant blocks so the same stack composes with
tensor (tp_axis), sequence (sp_axis: ring / Ulysses + Pallas flash), and
pipeline parallelism.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from horovod_tpu.parallel.tp import TPSelfAttention, TPSwiGLUMlp


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None    # None -> MHA
    intermediate_size: int = 11008
    max_position_embeddings: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.float32
    tp_axis: Optional[str] = "tp"   # None -> no tensor parallelism
    use_flash: bool = False         # Pallas flash attention (ops/pallas)
    sp_axis: Optional[str] = None   # sequence parallelism: tokens sharded
    sp_impl: str = "ring"           # "ring" | "ulysses" (parallel/sequence)
    # jax.checkpoint each block's backward (see GPTConfig.remat)
    remat: bool = False
    kv_cache_int8: bool = False     # quantized decode cache (serving)

    @staticmethod
    def tiny(**kw):
        """For tests / dry runs (GQA on: 4 query heads per 2 kv heads)."""
        base = dict(vocab_size=256, hidden_size=64, num_layers=4, num_heads=4,
                    num_kv_heads=2, intermediate_size=128,
                    max_position_embeddings=64)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def llama2_7b(**kw):
        """LLaMA-2-7B shapes (MHA, 4k context)."""
        base = dict()
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def llama3_8b(**kw):
        """LLaMA-3-8B shapes: GQA 32q/8kv, 128k vocab, theta 5e5."""
        base = dict(vocab_size=128256, hidden_size=4096, num_layers=32,
                    num_heads=32, num_kv_heads=8, intermediate_size=14336,
                    max_position_embeddings=8192, rope_theta=500000.0)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def bench(**kw):
        """~400M-param config sized so a full training step (fp32 master +
        adam moments) fits one chip's HBM for bench.py."""
        base = dict(vocab_size=32000, hidden_size=1024, num_layers=24,
                    num_heads=16, num_kv_heads=8, intermediate_size=2816,
                    max_position_embeddings=4096)
        base.update(kw)
        return LlamaConfig(**base)


class LlamaBlock(nn.Module):
    """Pre-RMSNorm block: GQA+RoPE attention, SwiGLU MLP, no biases
    (2 psums total under tp, exactly like :class:`TPTransformerBlock`).
    Shape-invariant, so it pipelines over a ``pp`` axis unchanged."""
    config: LlamaConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x, pos=None):
        c = self.config
        a = TPSelfAttention(
            c.num_heads, c.hidden_size, dtype=c.dtype, axis_name=c.tp_axis,
            causal=True, use_flash=c.use_flash, sp_axis=c.sp_axis,
            sp_impl=c.sp_impl, decode=self.decode,
            cache_len=c.max_position_embeddings,
            kv_cache_int8=c.kv_cache_int8,
            num_kv_heads=c.num_kv_heads, rope_theta=c.rope_theta,
            use_bias=False, name="attention")(
                nn.RMSNorm(epsilon=c.rms_eps, dtype=c.dtype,
                           name="ln_attn")(x), pos=pos)
        x = x + a
        h = TPSwiGLUMlp(c.intermediate_size, c.hidden_size, dtype=c.dtype,
                        axis_name=c.tp_axis, name="mlp")(
                            nn.RMSNorm(epsilon=c.rms_eps, dtype=c.dtype,
                                       name="ln_mlp")(x))
        return x + h


class LlamaEmbed(nn.Module):
    """Token embedding only — no positional table; positions enter via RoPE
    inside every attention block. ``pos`` is accepted for the decoder
    interface but carries no embedding work."""
    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, pos=None):
        c = self.config
        return nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype,
                        name="tok_emb")(input_ids)


class LlamaHead(nn.Module):
    """Final RMSNorm + fp32 LM head (bias-free)."""
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        c = self.config
        x = nn.RMSNorm(epsilon=c.rms_eps, dtype=c.dtype, name="ln_f")(x)
        return nn.Dense(c.vocab_size, use_bias=False, dtype=jnp.float32,
                        name="lm_head")(x)


class Llama(nn.Module):
    """Full model: token embed -> blocks -> RMSNorm -> fp32 LM head.

    Compose :class:`LlamaEmbed` / :class:`LlamaBlock` / :class:`LlamaHead`
    yourself for pipeline parallelism (see ``parallel/composite.py``'s
    ``CompositeLlama``).
    """
    config: LlamaConfig
    decode: bool = False   # KV-cache single-token decoding

    @nn.compact
    def __call__(self, input_ids, pos=None, features_only=False):
        """``features_only=True``: pre-head hidden states — see
        :class:`horovod_tpu.models.gpt.GPT` and
        :func:`horovod_tpu.optim.next_token_xent_chunked`."""
        c = self.config
        if self.decode and pos is None:
            raise ValueError("decode mode requires pos (the token's "
                             "global position)")
        x = LlamaEmbed(c, name="embed")(input_ids, pos)
        block_cls = (nn.remat(LlamaBlock) if c.remat and not self.decode
                     else LlamaBlock)
        for i in range(c.num_layers):
            x = block_cls(c, decode=self.decode, name=f"layer_{i}")(
                x, pos=pos if self.decode else None)
        if features_only:
            return x
        return LlamaHead(c, name="head")(x)
