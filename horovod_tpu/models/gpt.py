"""GPT-style decoder-only LM, built from the framework's parallel layers.

The reference framework has no model zoo of its own (its examples/ tree is
absent from the snapshot, SURVEY.md intro) — models here exist to exercise and
benchmark the distributed machinery. This one is the composite-parallelism
flagship: tensor-parallel attention/MLP blocks (parallel/tp.py), optional
expert-parallel MoE FFN (parallel/moe.py), and a shape-invariant block design
so the same blocks pipeline over a ``pp`` axis (parallel/pp.py).

TPU-first choices: bf16 activations with fp32 params/logits, fused QKV, static
causal mask, no data-dependent control flow.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from horovod_tpu.parallel.moe import MoEMlp
from horovod_tpu.parallel.tp import TPTransformerBlock


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    num_experts: int = 0            # 0 -> dense MLP blocks only
    moe_k: int = 1
    capacity_factor: float = 2.0
    # Hierarchical expert dispatch over the ep axis: None = auto (the
    # HOROVOD_HIERARCHICAL_ALLTOALL / a2a strategy registry chain),
    # True/False force it (parallel/moe.py).
    moe_hierarchical: Optional[bool] = None
    dtype: Any = jnp.float32
    tp_axis: Optional[str] = "tp"   # None -> no tensor parallelism
    ep_axis: Optional[str] = "ep"   # axis carrying the experts (often = dp)
    use_flash: bool = False         # Pallas flash attention (ops/pallas)
    sp_axis: Optional[str] = None   # sequence parallelism: tokens sharded
    sp_impl: str = "ring"           # "ring" | "ulysses" (parallel/sequence)
    # Rematerialize each block's activations in the backward pass
    # (jax.checkpoint): activation memory drops from O(layers) to O(1)
    # blocks at ~1/3 extra FLOPs — the lever for bigger per-chip batches
    # (MFU) and longer contexts on fixed HBM.
    remat: bool = False
    kv_cache_int8: bool = False     # quantized decode cache (serving)

    @staticmethod
    def tiny(**kw):
        """For tests / dry runs."""
        base = dict(vocab_size=256, hidden_size=64, num_layers=4, num_heads=4,
                    intermediate_size=128, max_position_embeddings=64)
        base.update(kw)
        return GPTConfig(**base)


class GPTEmbed(nn.Module):
    """Token + learned position embeddings (replicated params).

    ``pos`` (decode mode): a traced scalar — the global position of the
    FIRST token in ``input_ids`` (shape (B, s)); the table is sliced
    dynamically at positions ``pos..pos+s-1`` instead of by the static
    prefix (s=1 is the classic one-token step; s>1 is the chunked feed
    the speculative verifier uses). A (B,) VECTOR ``pos`` is the
    continuous-batching serving path: every batch row (slot) sits at its
    own position, so the table is gathered per row.
    """
    config: GPTConfig

    @nn.compact
    def __call__(self, input_ids, pos=None):
        c = self.config
        L = input_ids.shape[-1]
        tok = nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype,
                       name="tok_emb")(input_ids)
        table = self.param("pos_emb", nn.initializers.normal(0.02),
                           (c.max_position_embeddings, c.hidden_size),
                           jnp.float32)
        if pos is not None:
            import jax
            if jnp.ndim(pos) == 1:            # per-row (serving) positions
                rows = pos.astype(jnp.int32)[:, None] + jnp.arange(L)
                sl = jnp.take(table, rows, axis=0)          # (B, s, H)
                return tok + jnp.asarray(sl, c.dtype)
            sl = jax.lax.dynamic_slice_in_dim(table, pos, L)   # (s, H)
            return tok + jnp.asarray(sl, c.dtype)[None]
        pos = table  # legacy local name for the static paths below
        if c.sp_axis is not None:
            # Sequence-parallel: input_ids carry this chip's token shard;
            # index the position table at the GLOBAL positions of the shard
            # (outside the axis, e.g. init, the offset is zero).
            from horovod_tpu.parallel.tp import axis_size_or_1
            n_sp = axis_size_or_1(c.sp_axis)
            if n_sp > 1:
                import jax
                if n_sp * L > c.max_position_embeddings:
                    # dynamic_slice would CLAMP out-of-range shards onto
                    # the last positions — fail loudly like the unsharded
                    # path's broadcast error does.
                    raise ValueError(
                        f"global sequence {n_sp}x{L} exceeds "
                        f"max_position_embeddings="
                        f"{c.max_position_embeddings}")
                off = jax.lax.axis_index(c.sp_axis) * L
                sl = jax.lax.dynamic_slice_in_dim(pos, off, L)
                return tok + jnp.asarray(sl, c.dtype)[None]
        return tok + jnp.asarray(pos[:L], c.dtype)[None]


class GPTHead(nn.Module):
    """Final LayerNorm + language-model head (fp32 logits)."""
    config: GPTConfig

    @nn.compact
    def __call__(self, x):
        c = self.config
        x = nn.LayerNorm(dtype=c.dtype, name="ln_f")(x)
        return nn.Dense(c.vocab_size, use_bias=False, dtype=jnp.float32,
                        name="lm_head")(x)


class GPTMoEBlock(nn.Module):
    """Pre-LN block: TP causal attention + expert-parallel MoE FFN.

    Returns only the hidden state (shape-invariant, pipelineable); the MoE
    load-balance loss is accumulated in the ``"losses"`` collection via
    ``Module.sow`` so callers fetch it with ``mutable=["losses"]``.
    """
    config: GPTConfig

    @nn.compact
    def __call__(self, x):
        from horovod_tpu.parallel.tp import TPSelfAttention
        c = self.config
        a = TPSelfAttention(c.num_heads, c.hidden_size, dtype=c.dtype,
                            axis_name=c.tp_axis, causal=True,
                            use_flash=c.use_flash, sp_axis=c.sp_axis,
                            sp_impl=c.sp_impl, name="attention")(
                                nn.LayerNorm(dtype=c.dtype, name="ln_attn")(x))
        x = x + a
        h, aux = MoEMlp(c.num_experts, c.hidden_size, c.intermediate_size,
                        k=c.moe_k, capacity_factor=c.capacity_factor,
                        dtype=c.dtype, axis_name=c.ep_axis,
                        hierarchical=c.moe_hierarchical, name="moe")(
                            nn.LayerNorm(dtype=c.dtype, name="ln_mlp")(x))
        self.sow("losses", "moe_aux", aux)
        return x + h


class GPT(nn.Module):
    """Full (non-pipelined) model: embed -> blocks -> head.

    Blocks are dense TP blocks, with MoE blocks interleaved every
    ``moe_every``-th layer when ``config.num_experts > 0``. For pipeline
    parallelism, compose :class:`GPTEmbed` / block modules / :class:`GPTHead`
    yourself via :func:`horovod_tpu.parallel.pp.pipeline` (see
    ``parallel/composite.py``).
    """
    config: GPTConfig
    moe_every: int = 2
    decode: bool = False   # KV-cache single-token decoding (dense only)

    @nn.compact
    def __call__(self, input_ids, pos=None, features_only=False):
        """``features_only=True`` (apply-time only) returns the pre-head
        hidden states ``(B, L, H)`` — feed them to
        :func:`horovod_tpu.optim.next_token_xent_chunked` with the head
        bound to ``params["head"]`` so the full (B, L, V) logits tensor
        never materializes (initialize with the default False so the head
        params exist)."""
        c = self.config
        if self.decode:
            if c.num_experts:
                raise ValueError("decode mode does not support MoE blocks")
            if pos is None:
                raise ValueError("decode mode requires pos (the token's "
                                 "global position)")
        x = GPTEmbed(c, name="embed")(input_ids,
                                      pos if self.decode else None)
        # remat (training only — decode has no backward): recompute each
        # block in the vjp instead of stashing its activations.
        dense_cls = TPTransformerBlock
        moe_cls = GPTMoEBlock
        if c.remat and not self.decode:
            dense_cls = nn.remat(TPTransformerBlock)
            moe_cls = nn.remat(GPTMoEBlock)
        for i in range(c.num_layers):
            if c.num_experts and i % self.moe_every == self.moe_every - 1:
                x = moe_cls(c, name=f"layer_{i}")(x)
            else:
                x = dense_cls(
                    c.num_heads, c.hidden_size, c.intermediate_size,
                    dtype=c.dtype, axis_name=c.tp_axis, causal=True,
                    use_flash=c.use_flash, sp_axis=c.sp_axis,
                    sp_impl=c.sp_impl, decode=self.decode,
                    cache_len=c.max_position_embeddings,
                    kv_cache_int8=c.kv_cache_int8,
                    name=f"layer_{i}")(
                        x, pos=pos if self.decode else None)
        if features_only:
            return x
        return GPTHead(c, name="head")(x)
