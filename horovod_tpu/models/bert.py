"""BERT in flax, TPU-first.

BERT-Large fine-tune is one of the tracked baseline configs (BASELINE.md,
driver config "BERT-Large fine-tune with tensor fusion"). Written fresh for
TPU: bfloat16 activations, fused QKV projection (one MXU matmul instead of
three), static shapes throughout, no data-dependent control flow.
"""

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 1024          # BERT-Large
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = 4096
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    dtype: Any = jnp.bfloat16
    use_flash: bool = False          # Pallas flash attention (ops/pallas);
    # engages when no padding mask is given and dropout is off
    # jax.checkpoint each block's backward (see GPTConfig.remat)
    remat: bool = False

    @staticmethod
    def base(**kw):
        cfg = dict(hidden_size=768, num_layers=12, num_heads=12,
                   intermediate_size=3072)
        cfg.update(kw)
        return BertConfig(**cfg)

    @staticmethod
    def large(**kw):
        return BertConfig(**kw)

    @staticmethod
    def tiny(**kw):
        """For tests / dry runs."""
        cfg = dict(vocab_size=1024, hidden_size=128, num_layers=2,
                   num_heads=4, intermediate_size=256,
                   max_position_embeddings=128)
        cfg.update(kw)
        return BertConfig(**cfg)


class SelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, mask, deterministic=True):
        c = self.config
        head_dim = c.hidden_size // c.num_heads
        # Fused QKV: one (h, 3h) matmul keeps the MXU busy with a single
        # large tile instead of three small ones.
        qkv = nn.Dense(3 * c.hidden_size, dtype=c.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(t.shape[:-1] + (c.num_heads, head_dim))

        q, k, v = heads(q), heads(k), heads(v)
        if c.use_flash and mask is None and (deterministic
                                             or c.dropout_rate == 0.0):
            # Bidirectional flash (tiled online softmax): padding masks and
            # attention dropout aren't expressible in the kernel, so those
            # cases keep the plain path below.
            from horovod_tpu.ops.pallas import flash_attention
            out = flash_attention(q, k, v, causal=False)
        else:
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(head_dim)
            if mask is not None:
                big_neg = jnp.asarray(-1e9, scores.dtype)
                scores = jnp.where(mask[:, None, None, :], scores, big_neg)
            probs = nn.softmax(scores.astype(jnp.float32)).astype(c.dtype)
            probs = nn.Dropout(c.dropout_rate)(probs,
                                               deterministic=deterministic)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        out = out.reshape(out.shape[:-2] + (c.hidden_size,))
        return nn.Dense(c.hidden_size, dtype=c.dtype, name="out")(out)


class TransformerBlock(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, mask, deterministic=True):
        c = self.config
        a = SelfAttention(c, name="attention")(x, mask, deterministic)
        a = nn.Dropout(c.dropout_rate)(a, deterministic=deterministic)
        x = nn.LayerNorm(dtype=c.dtype, name="ln_attn")(x + a)
        h = nn.Dense(c.intermediate_size, dtype=c.dtype, name="mlp_in")(x)
        h = nn.gelu(h)
        h = nn.Dense(c.hidden_size, dtype=c.dtype, name="mlp_out")(h)
        h = nn.Dropout(c.dropout_rate)(h, deterministic=deterministic)
        return nn.LayerNorm(dtype=c.dtype, name="ln_mlp")(x + h)


class BertModel(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic=True):
        c = self.config
        B, L = input_ids.shape
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        # No synthesized all-ones mask: None means "no padding", which the
        # attention treats identically and which lets flash engage.
        tok = nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype,
                       name="tok_emb")(input_ids)
        pos = nn.Embed(c.max_position_embeddings, c.hidden_size,
                       dtype=c.dtype, name="pos_emb")(
                           jnp.arange(L)[None].repeat(B, 0))
        typ = nn.Embed(c.type_vocab_size, c.hidden_size, dtype=c.dtype,
                       name="type_emb")(token_type_ids)
        x = nn.LayerNorm(dtype=c.dtype, name="ln_emb")(tok + pos + typ)
        x = nn.Dropout(c.dropout_rate)(x, deterministic=deterministic)
        mask = None if attention_mask is None \
            else attention_mask.astype(bool)
        # static_argnums: ``deterministic`` is a python bool consumed by
        # Dropout's control flow — it must not become a tracer under remat
        # (arg 0 of the transformed fn is the module itself, so
        # ``deterministic`` — x, mask, deterministic — is argnum 3)
        block_cls = nn.remat(TransformerBlock, static_argnums=(3,)) \
            if c.remat else TransformerBlock
        for i in range(c.num_layers):
            x = block_cls(c, name=f"layer_{i}")(
                x, mask, deterministic)
        pooled = nn.tanh(nn.Dense(c.hidden_size, dtype=c.dtype,
                                  name="pooler")(x[:, 0]))
        return x, pooled


class BertForPreTraining(nn.Module):
    """MLM + NSP heads, the standard pre-training/fine-tune objective."""
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic=True):
        c = self.config
        x, pooled = BertModel(c, name="bert")(
            input_ids, token_type_ids, attention_mask, deterministic)
        mlm = nn.Dense(c.hidden_size, dtype=c.dtype, name="mlm_transform")(x)
        mlm = nn.LayerNorm(dtype=c.dtype, name="mlm_ln")(nn.gelu(mlm))
        mlm_logits = nn.Dense(c.vocab_size, dtype=jnp.float32,
                              name="mlm_head")(mlm)
        nsp_logits = nn.Dense(2, dtype=jnp.float32, name="nsp_head")(pooled)
        return mlm_logits.astype(jnp.float32), nsp_logits.astype(jnp.float32)
