"""Keras callbacks (reference: horovod/_keras/callbacks.py:23-193).

The flax-loop equivalents live in horovod_tpu/callbacks.py; these are the
keras.callbacks.Callback adapters over the same semantics.
"""

import keras
import numpy as np


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast all model/optimizer variables from root at train start so
    every host begins identically (reference: _keras/callbacks.py:23-60)."""

    def __init__(self, root_rank=0, process_set=None):
        super().__init__()
        self.root_rank = root_rank
        self.process_set = process_set
        self.broadcast_done = False

    def on_batch_begin(self, batch, logs=None):
        if self.broadcast_done:
            return
        import horovod_tpu.tensorflow as hvd_tf
        # All weights, trainable AND non-trainable (BatchNorm moving stats
        # must sync too — reference broadcasts every global variable).
        hvd_tf.broadcast_variables(self.model.weights,
                                   root_rank=self.root_rank,
                                   process_set=self.process_set)
        if self.model.optimizer is not None:
            hvd_tf.broadcast_variables(self.model.optimizer.variables,
                                       root_rank=self.root_rank,
                                       process_set=self.process_set)
        self.broadcast_done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch-end metrics across hosts (reference:
    _keras/callbacks.py:62-109)."""

    def __init__(self, process_set=None):
        super().__init__()
        self.process_set = process_set

    def on_epoch_end(self, epoch, logs=None):
        if not logs:
            return
        import horovod_tpu.tensorflow as hvd_tf
        for k, v in list(logs.items()):
            if isinstance(v, (int, float, np.floating, np.integer)):
                logs[k] = float(hvd_tf.allreduce(
                    np.asarray(v, np.float32), op=hvd_tf.Average,
                    process_set=self.process_set).numpy())


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Multiply the base LR by ``multiplier`` inside [start_epoch, end_epoch)
    (reference: _keras/callbacks.py:111-160)."""

    def __init__(self, initial_lr, multiplier, start_epoch=0, end_epoch=None,
                 staircase=True, momentum_correction=True, steps_per_epoch=None):
        super().__init__()
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def _in_range(self, epoch):
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase and self._in_range(epoch):
            self._set_lr(self.initial_lr * self.multiplier(epoch))

    def on_batch_begin(self, batch, logs=None):
        if self.staircase or not self._in_range(self.current_epoch):
            return
        if self.steps_per_epoch is None:
            return
        frac_epoch = self.current_epoch + batch / self.steps_per_epoch
        self._set_lr(self.initial_lr * self.multiplier(frac_epoch))

    def _set_lr(self, lr):
        self.model.optimizer.learning_rate.assign(lr)

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = float(
                np.asarray(self.model.optimizer.learning_rate))


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Linear LR ramp from initial_lr to initial_lr*size over warmup_epochs
    (reference: _keras/callbacks.py:162-193 — the gradual warmup of the
    'ImageNet in 1 Hour' recipe)."""

    def __init__(self, initial_lr, warmup_epochs=5, momentum_correction=True,
                 steps_per_epoch=None, verbose=0, process_set=None):
        from horovod_tpu.common import basics

        def multiplier(epoch):
            # epoch may be fractional (per-batch ramp)
            size = basics.size()
            return 1.0 / size + epoch * (1.0 - 1.0 / size) / warmup_epochs

        super().__init__(initial_lr=initial_lr, multiplier=multiplier,
                         start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)
        self.verbose = verbose


class BestModelCheckpoint(keras.callbacks.ModelCheckpoint):
    """ModelCheckpoint pinned to save-best-only; ``filepath`` may be set
    after construction by a training harness (reference:
    keras/callbacks.py:161-186 — the Spark Keras estimator uses it to keep
    only the best epoch's model)."""

    def __init__(self, monitor="val_loss", verbose=0, mode="auto",
                 save_freq="epoch", filepath=None):
        # Keras 3 validates filepath eagerly (must end in .keras); the
        # reference passes None and lets the estimator fill it in later —
        # use a placeholder name the harness overwrites via `.filepath`.
        super().__init__(filepath=filepath or "best_model.keras",
                         monitor=monitor,
                         verbose=verbose, save_best_only=True,
                         save_weights_only=False, mode=mode,
                         save_freq=save_freq)
