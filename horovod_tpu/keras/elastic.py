"""Elastic state for Keras models (reference: horovod/keras/elastic.py —
KerasState:24 delegates to TensorFlowKerasState with the keras backend;
CommitStateCallback/UpdateBatchStateCallback:44-92 commit/track per batch).
"""

from horovod_tpu.elastic.state import run  # noqa: F401  (re-export)
from horovod_tpu.tensorflow.elastic import TensorFlowKerasState


class KerasState(TensorFlowKerasState):
    """State of a Keras model + optimizer (reference: keras/elastic.py:24)."""

    def __init__(self, model, optimizer=None, **kwargs):
        super().__init__(model, optimizer=optimizer, **kwargs)


def _make_callback_base():
    import tensorflow as tf
    return tf.keras.callbacks.Callback


class CommitStateCallback:
    """Commit the elastic state every ``batches_per_commit`` batches
    (reference: keras/elastic.py:44-66). Implemented as a factory returning
    a Keras callback so TF import stays lazy."""

    def __new__(cls, state, batches_per_commit=1):
        Base = _make_callback_base()

        class _Commit(Base):
            def __init__(self):
                super().__init__()
                self._count = 0

            def on_batch_end(self, batch, logs=None):
                self._count += 1
                if self._count % batches_per_commit == 0:
                    state.commit()

        return _Commit()


class UpdateBatchStateCallback:
    """Track ``state.batch``/``state.epoch`` so a restored worker resumes
    mid-epoch (reference: keras/elastic.py:69-92)."""

    def __new__(cls, state):
        Base = _make_callback_base()

        class _Update(Base):
            def on_epoch_begin(self, epoch, logs=None):
                state.epoch = epoch

            def on_batch_end(self, batch, logs=None):
                state.batch = batch

            def on_epoch_end(self, epoch, logs=None):
                state.batch = 0

        return _Update()


class UpdateEpochStateCallback:
    """Track ``state.epoch`` only — for epoch-granular resume where
    ``initial_epoch=state.epoch`` is passed to ``model.fit`` (reference:
    keras/elastic.py UpdateEpochStateCallback)."""

    def __new__(cls, state):
        Base = _make_callback_base()

        class _UpdateEpoch(Base):
            def on_epoch_end(self, epoch, logs=None):
                state.epoch = epoch + 1

        return _UpdateEpoch()
