"""Keras frontend — the ``horovod.keras`` API surface for Keras 3.

Reference: horovod/keras/__init__.py (DistributedOptimizer :40-130,
load_model :252) + horovod/_keras/ shared impl. The reference wraps the
legacy ``optimizer.get_gradients``; Keras 3 removed it, so the TPU-native
wrapper intercepts ``apply_gradients`` — the one choke point every Keras 3
train step passes through — and allreduces there.
"""

from horovod_tpu.common.basics import (init, shutdown, is_initialized, rank,
                                       local_rank, cross_rank, size,
                                       local_size, cross_size,
                                       is_homogeneous, mpi_threads_supported,
                                       mpi_enabled, mpi_built, gloo_enabled,
                                       gloo_built, nccl_built, ddl_built,
                                       ccl_built, cuda_built, rocm_built,
                                       xla_built, ici_built, start_timeline,
                                       stop_timeline)
from horovod_tpu.common.process_sets import global_process_set
from horovod_tpu.ops.collective_ops import (Adasum, Average, Max, Min, Product,
                                            Sum)
from horovod_tpu.tensorflow import (Compression, allgather, allreduce,
                                    alltoall, broadcast, broadcast_object,
                                    broadcast_variables, reducescatter)

from horovod_tpu.keras import callbacks  # noqa: F401

__all__ = ["init", "shutdown", "is_initialized", "rank", "local_rank",
           "cross_rank", "size", "local_size", "cross_size",
           "Average", "Sum", "Adasum", "Min", "Max", "Product",
           "Compression", "allreduce", "allgather", "broadcast", "alltoall",
           "reducescatter", "broadcast_object", "broadcast_variables",
           "broadcast_global_variables", "global_process_set",
           "DistributedOptimizer", "PartialDistributedOptimizer",
           "load_model", "callbacks", "elastic",
           "is_homogeneous", "mpi_threads_supported", "mpi_enabled",
           "mpi_built", "gloo_enabled", "gloo_built", "nccl_built",
           "ddl_built", "ccl_built", "cuda_built", "rocm_built", "xla_built",
           "ici_built", "start_timeline", "stop_timeline"]


def __getattr__(name):
    if name == "elastic":
        import horovod_tpu.keras.elastic as elastic
        return elastic
    raise AttributeError(name)


def broadcast_global_variables(root_rank=0):
    """Broadcast every TF1-style global variable from root (reference:
    keras/__init__.py broadcast_global_variables). Keras 3 keeps no global
    collection — eager models should broadcast ``model.variables`` via
    :func:`broadcast_variables` or the BroadcastGlobalVariablesCallback."""
    import tensorflow as tf
    broadcast_variables(tf.compat.v1.global_variables(),
                        root_rank=root_rank)


def DistributedOptimizer(optimizer, name=None,
                         compression=Compression.none,
                         sparse_as_dense=False, op=Average,
                         backward_passes_per_step=1,
                         average_aggregated_gradients=False,
                         gradient_predivide_factor=1.0,
                         groups=None, num_groups=0,
                         process_set=None,
                         local_layers=None, scale_local_gradients=True):
    """Wrap a Keras optimizer so gradients are averaged across hosts inside
    ``apply_gradients`` (reference: hvd.DistributedOptimizer
    keras/__init__.py:40-130).

    The instance's class is swapped in place (same trick as the reference's
    dynamic subclass) so already-built optimizer state — restored Adam
    moments, iteration counts — survives wrapping, e.g. through
    :func:`load_model`.
    """
    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd_tf

    if gradient_predivide_factor != 1.0 and op != Average:
        raise ValueError(
            "gradient_predivide_factor not supported with op != Average")
    if num_groups != 0 and groups is None:
        groups = num_groups

    cls = optimizer.__class__
    # Accumulation state lives in the closure, NOT as instance attributes:
    # Keras 3's attribute tracking wraps assigned lists in tracked copies, so
    # in-place mutations through a local alias would be silently dropped.
    # Each _Distributed class wraps exactly one optimizer instance.
    agg = {"acc": None, "count": 0}

    class _Distributed(cls):
        _hvd_wrapped = True

        def _hvd_accumulate(self, grads):
            """Eager local aggregation over backward_passes_per_step calls;
            returns the averaged gradients on the flush call, else None
            (reference: tensorflow/gradient_aggregation_eager.py)."""
            if not tf.executing_eagerly():
                raise NotImplementedError(
                    "backward_passes_per_step > 1 requires an eager training "
                    "loop (model.compile(run_eagerly=True)); inside "
                    "tf.function use a larger batch instead")
            if agg["acc"] is None:
                agg["acc"] = [None] * len(grads)
                agg["count"] = 0
            acc = agg["acc"]
            for i, g in enumerate(grads):
                if g is not None:
                    acc[i] = g if acc[i] is None else acc[i] + g
            agg["count"] += 1
            if agg["count"] < backward_passes_per_step:
                return None
            scale = (backward_passes_per_step
                     if average_aggregated_gradients else 1)
            out = [None if a is None else a / scale for a in acc]
            agg["acc"] = None
            return out

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            grads_and_vars = list(grads_and_vars)
            grads = [g for g, _ in grads_and_vars]
            variables = [v for _, v in grads_and_vars]
            if sparse_as_dense:
                grads = [tf.convert_to_tensor(g)
                         if isinstance(g, tf.IndexedSlices) else g
                         for g in grads]
            if backward_passes_per_step > 1:
                grads = self._hvd_accumulate(grads)
                if grads is None:
                    return None  # mid-accumulation: no variable update
            _key = hvd_tf.var_key

            local_refs = set()
            for layer in (local_layers or []):
                lvars = getattr(layer, "trainable_variables", None)
                for v in (lvars if lvars is not None else [layer]):
                    local_refs.add(_key(v))
            # The predivide-split and groups-chunking machinery is the TF
            # frontend's _make_allreduce_grads_fn — shared, not duplicated
            # (it uses the same var_key identity). Local variables are
            # masked out of the reduction and their gradients reinserted.
            reduce_fn = hvd_tf._make_allreduce_grads_fn(
                op=op, gradient_predivide_factor=gradient_predivide_factor,
                compression=compression, sparse_as_dense=sparse_as_dense,
                process_set=process_set, groups=groups)
            masked = [None if _key(v) in local_refs else g
                      for g, v in zip(grads, variables)]
            reduced = reduce_fn(masked, variables)
            grads = [g if _key(v) in local_refs else r
                     for g, v, r in zip(grads, variables, reduced)]
            if local_refs and scale_local_gradients:
                ps = (process_set if process_set is not None
                      else hvd_tf.global_process_set)
                grads = [g / ps.size() if g is not None
                         and _key(v) in local_refs else g
                         for g, v in zip(grads, variables)]
            return super().apply_gradients(zip(grads, variables), *args,
                                           **kwargs)

    _Distributed.__name__ = cls.__name__
    optimizer.__class__ = _Distributed
    return optimizer


def PartialDistributedOptimizer(optimizer, local_layers=None, name=None,
                                compression=Compression.none,
                                sparse_as_dense=False, op=Average,
                                backward_passes_per_step=1, process_set=None,
                                scale_local_gradients=True):
    """A DistributedOptimizer whose ``local_layers`` keep worker-local
    gradients (reference: keras PartialDistributedOptimizer,
    horovod/keras/__init__.py)."""
    return DistributedOptimizer(
        optimizer, name=name, compression=compression,
        sparse_as_dense=sparse_as_dense, op=op,
        backward_passes_per_step=backward_passes_per_step,
        process_set=process_set, local_layers=local_layers,
        scale_local_gradients=scale_local_gradients)


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """Load a Keras model wrapping its optimizer as a DistributedOptimizer
    (reference: keras/__init__.py:252-289)."""
    import keras

    model = keras.models.load_model(filepath,
                                    custom_objects=custom_objects)
    if model.optimizer is not None and \
            not getattr(model.optimizer, "_hvd_wrapped", False):
        model.optimizer = DistributedOptimizer(model.optimizer,
                                               compression=compression)
    return model
