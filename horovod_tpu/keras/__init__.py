"""Keras frontend — the ``horovod.keras`` API surface for Keras 3.

Reference: horovod/keras/__init__.py (DistributedOptimizer :40-130,
load_model :252) + horovod/_keras/ shared impl. The reference wraps the
legacy ``optimizer.get_gradients``; Keras 3 removed it, so the TPU-native
wrapper intercepts ``apply_gradients`` — the one choke point every Keras 3
train step passes through — and allreduces there.
"""

from horovod_tpu.common.basics import (init, shutdown, is_initialized, rank,
                                       local_rank, cross_rank, size,
                                       local_size, cross_size)
from horovod_tpu.ops.collective_ops import (Adasum, Average, Max, Min, Product,
                                            Sum)
from horovod_tpu.tensorflow import (Compression, allgather, allreduce,
                                    broadcast, broadcast_object,
                                    broadcast_variables)

from horovod_tpu.keras import callbacks  # noqa: F401

__all__ = ["init", "shutdown", "is_initialized", "rank", "local_rank",
           "cross_rank", "size", "local_size", "cross_size",
           "Average", "Sum", "Adasum", "Min", "Max", "Product",
           "Compression", "allreduce", "allgather", "broadcast",
           "broadcast_object", "broadcast_variables",
           "DistributedOptimizer", "load_model", "callbacks"]


def DistributedOptimizer(optimizer, name=None,
                         compression=Compression.none,
                         sparse_as_dense=False, op=Average,
                         backward_passes_per_step=1, process_set=None):
    """Wrap a Keras optimizer so gradients are averaged across hosts inside
    ``apply_gradients`` (reference: hvd.DistributedOptimizer
    keras/__init__.py:40-130)."""
    import horovod_tpu.tensorflow as hvd_tf

    cls = optimizer.__class__

    class _Distributed(cls):
        _hvd_wrapped = True

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            grads_and_vars = list(grads_and_vars)
            grads = [g for g, _ in grads_and_vars]
            variables = [v for _, v in grads_and_vars]
            live = [g for g in grads if g is not None]
            if live:
                reduced = iter(hvd_tf.grouped_allreduce(
                    live, op=op, process_set=process_set))
                grads = [None if g is None else next(reduced) for g in grads]
            return super().apply_gradients(zip(grads, variables), *args,
                                           **kwargs)

    _Distributed.__name__ = cls.__name__
    cfg = optimizer.get_config()
    dist = _Distributed.from_config(cfg)
    return dist


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """Load a Keras model wrapping its optimizer as a DistributedOptimizer
    (reference: keras/__init__.py:252-289)."""
    import keras

    model = keras.models.load_model(filepath,
                                    custom_objects=custom_objects)
    if model.optimizer is not None and \
            not getattr(model.optimizer, "_hvd_wrapped", False):
        model.optimizer = DistributedOptimizer(model.optimizer,
                                               compression=compression)
    return model
