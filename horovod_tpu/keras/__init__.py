"""Keras frontend — the ``horovod.keras`` API surface for Keras 3.

Reference: horovod/keras/__init__.py (DistributedOptimizer :40-130,
load_model :252) + horovod/_keras/ shared impl. The reference wraps the
legacy ``optimizer.get_gradients``; Keras 3 removed it, so the TPU-native
wrapper intercepts ``apply_gradients`` — the one choke point every Keras 3
train step passes through — and allreduces there.
"""

from horovod_tpu.common.basics import (init, shutdown, is_initialized, rank,
                                       local_rank, cross_rank, size,
                                       local_size, cross_size)
from horovod_tpu.ops.collective_ops import (Adasum, Average, Max, Min, Product,
                                            Sum)
from horovod_tpu.tensorflow import (Compression, allgather, allreduce,
                                    broadcast, broadcast_object,
                                    broadcast_variables)

from horovod_tpu.keras import callbacks  # noqa: F401

__all__ = ["init", "shutdown", "is_initialized", "rank", "local_rank",
           "cross_rank", "size", "local_size", "cross_size",
           "Average", "Sum", "Adasum", "Min", "Max", "Product",
           "Compression", "allreduce", "allgather", "broadcast",
           "broadcast_object", "broadcast_variables",
           "DistributedOptimizer", "load_model", "callbacks"]


def DistributedOptimizer(optimizer, name=None,
                         compression=Compression.none,
                         sparse_as_dense=False, op=Average,
                         backward_passes_per_step=1, process_set=None):
    """Wrap a Keras optimizer so gradients are averaged across hosts inside
    ``apply_gradients`` (reference: hvd.DistributedOptimizer
    keras/__init__.py:40-130).

    The instance's class is swapped in place (same trick as the reference's
    dynamic subclass) so already-built optimizer state — restored Adam
    moments, iteration counts — survives wrapping, e.g. through
    :func:`load_model`.
    """
    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd_tf

    cls = optimizer.__class__
    # Accumulation state lives in the closure, NOT as instance attributes:
    # Keras 3's attribute tracking wraps assigned lists in tracked copies, so
    # in-place mutations through a local alias would be silently dropped.
    # Each _Distributed class wraps exactly one optimizer instance.
    agg = {"acc": None, "count": 0}

    class _Distributed(cls):
        _hvd_wrapped = True

        def _hvd_accumulate(self, grads):
            """Eager local aggregation over backward_passes_per_step calls;
            returns the averaged gradients on the flush call, else None
            (reference: tensorflow/gradient_aggregation_eager.py)."""
            if not tf.executing_eagerly():
                raise NotImplementedError(
                    "backward_passes_per_step > 1 requires an eager training "
                    "loop (model.compile(run_eagerly=True)); inside "
                    "tf.function use a larger batch instead")
            if agg["acc"] is None:
                agg["acc"] = [None] * len(grads)
                agg["count"] = 0
            acc = agg["acc"]
            for i, g in enumerate(grads):
                if g is not None:
                    acc[i] = g if acc[i] is None else acc[i] + g
            agg["count"] += 1
            if agg["count"] < backward_passes_per_step:
                return None
            out = [None if a is None else a / backward_passes_per_step
                   for a in acc]
            agg["acc"] = None
            return out

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            grads_and_vars = list(grads_and_vars)
            grads = [g for g, _ in grads_and_vars]
            variables = [v for _, v in grads_and_vars]
            if sparse_as_dense:
                grads = [tf.convert_to_tensor(g)
                         if isinstance(g, tf.IndexedSlices) else g
                         for g in grads]
            if backward_passes_per_step > 1:
                grads = self._hvd_accumulate(grads)
                if grads is None:
                    return None  # mid-accumulation: no variable update
            live = [g for g in grads if g is not None]
            if live:
                reduced = iter(hvd_tf.grouped_allreduce(
                    live, op=op, compression=compression,
                    process_set=process_set))
                grads = [None if g is None else next(reduced) for g in grads]
            return super().apply_gradients(zip(grads, variables), *args,
                                           **kwargs)

    _Distributed.__name__ = cls.__name__
    optimizer.__class__ = _Distributed
    return optimizer


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """Load a Keras model wrapping its optimizer as a DistributedOptimizer
    (reference: keras/__init__.py:252-289)."""
    import keras

    model = keras.models.load_model(filepath,
                                    custom_objects=custom_objects)
    if model.optimizer is not None and \
            not getattr(model.optimizer, "_hvd_wrapped", False):
        model.optimizer = DistributedOptimizer(model.optimizer,
                                               compression=compression)
    return model
