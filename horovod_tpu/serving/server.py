"""HTTP request frontend for the serving engine.

The same dependency-free threaded-HTTP idiom as the metrics scrape
endpoint and the runner KV store: ``POST /generate`` with
``{"prompt": [token ids], "max_new": n, "temperature": t, "top_k": k,
"top_p": p, "eos_id": e, "seed": s}`` blocks until the request
completes and answers ``{"rid", "tokens", "generated", "ttft_s"}``;
``GET /health`` returns the engine snapshot (503 + ``Retry-After`` when
the queue is saturated — load balancers read this as backpressure);
``GET /debug/trace/<rid>`` returns the request's live span tree (queue /
prefill / decode / stream phases, requeue/restore markers — see
``horovod_tpu/trace`` and docs/troubleshooting.md's latency runbook).

A background drive thread owns every device interaction
(:meth:`ServingEngine.step`); handler threads only enqueue and wait on
the request's completion event, so request concurrency is bounded by
the HTTP thread pool while the decode batch stays at the engine's fixed
slot count — continuous batching does the multiplexing.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from horovod_tpu.serving.scheduler import QueueFull

_IDLE_SLEEP_S = 0.002


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence
        pass

    def _send(self, obj, code=200, retry_after=None):
        data = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        if retry_after is not None:
            self.send_header("Retry-After", str(retry_after))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        if self.path.startswith("/debug/trace/"):
            from horovod_tpu import trace
            rid = self.path[len("/debug/trace/"):]
            tree = trace.tree_for_rid(rid)
            if tree is None:
                # Unknown OR already evicted from the bounded store —
                # the rid in the body tells the caller which id missed.
                self._send({"error": "no trace", "rid": rid}, code=404)
                return
            self._send(tree)
            return
        if self.path not in ("/health", "/serving/health"):
            self._send({"error": "not found"}, code=404)
            return
        snap = self.server.frontend.engine.snapshot()
        if snap.get("saturated"):
            self._send(snap, code=503, retry_after=1)
            return
        self._send(snap)

    def do_POST(self):
        if self.path != "/generate":
            self._send({"error": "not found"}, code=404)
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"{}")
            prompt = body["prompt"]
            max_new = int(body.get("max_new", 16))
        except (KeyError, TypeError, ValueError,
                json.JSONDecodeError) as e:
            self._send({"error": f"bad request: {e}"}, code=400)
            return
        fe = self.server.frontend
        try:
            req = fe.engine.submit(
                prompt, max_new,
                temperature=float(body.get("temperature", 0.0)),
                top_k=int(body.get("top_k", 0)),
                top_p=float(body.get("top_p", 1.0)),
                eos_id=body.get("eos_id"),
                seed=int(body.get("seed", 0)))
        except QueueFull:
            self._send({"error": "queue full"}, code=503, retry_after=1)
            return
        except (TypeError, ValueError) as e:
            # TypeError: non-numeric JSON values (e.g. "temperature":
            # null) reaching the float()/int() coercions — a client
            # error, not a handler crash.
            self._send({"error": str(e)}, code=400)
            return
        try:
            tokens = req.result(timeout=fe.request_timeout)
        except TimeoutError:
            self._send({"error": "timed out", "rid": req.rid,
                        "generated": len(req.committed)}, code=504)
            return
        self._send({
            "rid": req.rid,
            "tid": req.tid,
            "tokens": [int(t) for t in tokens],
            "generated": len(req.committed),
            "ttft_s": None if req.t_first is None
            else round(req.t_first - req.t_submit, 6)})


class ServingFrontend:
    """Drive thread + HTTP server over one engine; ``port=0`` binds a
    free port (read ``.port`` after :meth:`start`).

    ``drive=False`` starts only the HTTP listener: the caller owns the
    engine loop (the elastic serve path, where stepping and committing
    must share one thread — a commit racing a step could snapshot a
    half-applied decode)."""

    def __init__(self, engine, port=0, addr="0.0.0.0",
                 request_timeout=300.0, drive=True):
        self.engine = engine
        self.request_timeout = float(request_timeout)
        self.drive = bool(drive)
        self._httpd = ThreadingHTTPServer((addr, port), _Handler)
        self._httpd.frontend = self
        self._stop = threading.Event()
        self._threads = []

    @property
    def port(self):
        return self._httpd.server_address[1]

    def _drive(self):
        while not self._stop.is_set():
            try:
                if not self.engine.step():
                    time.sleep(_IDLE_SLEEP_S)
            except Exception:  # noqa: BLE001 — keep serving; forensics ring
                from horovod_tpu.flight import recorder as _flight
                _flight.record_event("serving", what="drive_error")
                time.sleep(0.05)

    def start(self):
        self._threads = [
            threading.Thread(target=self._httpd.serve_forever, daemon=True,
                             name="hvd-serving-http"),
        ]
        if self.drive:
            self._threads.append(
                threading.Thread(target=self._drive, daemon=True,
                                 name="hvd-serving-drive"))
        for t in self._threads:
            t.start()
        return self.port

    def stop(self):
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []
        # Persist this process's request traces when a dump dir is
        # configured (trace_r<rank>.json, merged by
        # `python -m horovod_tpu.trace.analyze`): the live
        # /debug/trace/<rid> store dies with the frontend.
        import os
        trace_dir = os.environ.get("HOROVOD_TRACE_DIR", "")
        if trace_dir:
            try:
                from horovod_tpu import trace
                rank = int(os.environ.get("HOROVOD_RANK", "0") or 0)
                os.makedirs(trace_dir, exist_ok=True)
                trace.dump(os.path.join(trace_dir,
                                        f"trace_r{rank}.json"), rank=rank)
            except Exception:  # noqa: BLE001 — dumps must not block stop
                pass
