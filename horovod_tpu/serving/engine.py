"""Continuous-batching inference engine over the collective runtime.

The serving shape (ROADMAP item 2 — "millions of users"): a FIFO request
queue fronts a sharded causal LM; admitted requests are packed into a
FIXED-SLOT decode batch whose per-slot KV caches live in one device
tree, and slots retire/refill independently — continuous batching, not
static batches. Three compiled programs cover the whole hot path:

- **prefill** — a batch-1 chunked feed at explicit positions (the
  per-row ``pos`` vector path of ``TPSelfAttention._decode_attend``)
  builds the new request's K/V rows without touching its neighbours;
- **install** — scatters the batch-1 cache into the admitted slot of the
  big ``(num_slots, ...)`` cache tree (dynamic_update_slice per leaf);
- **decode step** — ONE token for every slot per call at per-slot
  positions (each row masked by its own cursor), cache donated so XLA
  updates it in place.

Sampling runs on host from the step's ``(S, V)`` logits: per-request
temperature/top-k/top-p with draws keyed on ``(seed, position)``, so a
request re-queued from its last committed token after an elastic
disruption reproduces its exact remaining token stream — the zero-drop
invariant the chaos soak asserts. Greedy parity with
``models.generate`` is exact (same argmax over the same logits).

Elasticity rides :class:`horovod_tpu.serving.state.ServingState`
(a ``TpuState``): request-level state commits per step-group, in-flight
caches either migrate through rendezvous as host snapshots
(``HOROVOD_SERVING_MIGRATE_KV``) or re-queue from the last committed
token and re-prefill. Observability: every lifecycle event and decode
step lands in the SLO series of ``metrics/instruments.py`` (TTFT,
inter-token latency, tokens/sec, queue depth, batch fill), per-step
attribution in the step profiler (``mark_steps``), and request
transitions in the flight ring.
"""

import dataclasses
import functools
import threading
import time

import numpy as np

from horovod_tpu import trace
from horovod_tpu.flight import recorder as _flight
from horovod_tpu.metrics import instruments as _metrics
from horovod_tpu.serving.request import Request
from horovod_tpu.serving.scheduler import SlotScheduler
from horovod_tpu.telemetry import slo as _slo

# The newest engine, for the /serving/health endpoint and telemetry gate.
_current = None


def get_engine():
    return _current


def serving_snapshot():
    """JSON-able engine state for ``/serving/health`` (None when no
    engine runs in this process)."""
    eng = _current
    return None if eng is None else eng.snapshot()


def _host_filter_logits(logits, top_k, top_p):
    """numpy mirror of ``models.generate._filter_logits`` for one (V,)
    row (same keep-set semantics; host-side because per-request k/p are
    data, not static program constants)."""
    if top_k:
        k = min(top_k, logits.size)
        kth = np.partition(logits, -k)[-k]
        logits = np.where(logits >= kth, logits, -np.inf)
    if top_p < 1.0:
        srt = np.sort(logits)[::-1]
        z = srt - srt[0]
        probs = np.exp(z) / np.exp(z).sum()
        cum = np.cumsum(probs)
        keep = cum - probs < top_p
        thresh = srt[keep][-1] if keep.any() else srt[0]
        logits = np.where(logits >= thresh, logits, -np.inf)
    return logits


def sample_token(logits, temperature, top_k, top_p, seed, position):
    """Next token from one (V,) float row — greedy at temperature 0, else
    a tempered categorical over the filtered distribution, drawn from a
    generator keyed on ``(seed, position)``: position-keyed draws are
    what make a re-queued request's remaining stream identical to the
    uninterrupted one."""
    if temperature == 0.0:
        return int(np.argmax(logits))
    z = _host_filter_logits(logits.astype(np.float64) / temperature,
                            top_k, top_p)
    z = z - np.max(z)
    p = np.exp(z)
    p = p / p.sum()
    rng = np.random.default_rng((int(seed) & 0x7FFFFFFF, int(position)))
    return int(rng.choice(p.size, p=p))


class ServingEngine:
    """See the module docstring. ``model`` is any causal LM supporting the
    decode-mode per-row ``pos`` protocol (GPT / LLaMA zoo — LoRA-merged
    and speculative-target params serve unchanged: the engine only calls
    ``apply``).

    ``step_fn`` / ``prefill_fn`` / ``install_fn`` are test seams: the
    perf guard stubs the device programs to bound the pure host cost of
    enqueue → schedule → dispatch.
    """

    def __init__(self, model, params, num_slots=4, max_len=None,
                 prefill_chunk=64, queue_limit=0, migrate_kv=False,
                 mark_steps=True, step_fn=None, prefill_fn=None,
                 install_fn=None):
        import jax.numpy as jnp

        self.model = model
        self.params = params
        cap = getattr(getattr(model, "config", None),
                      "max_position_embeddings", None)
        self.max_len = int(max_len or cap or 0)
        if self.max_len < 2:
            raise ValueError("need max_len >= 2 (model config carries none)")
        if cap is not None and self.max_len > cap:
            raise ValueError(f"max_len {self.max_len} exceeds the model's "
                             f"position capacity ({cap})")
        self.num_slots = int(num_slots)
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.migrate_kv = bool(migrate_kv)
        self.mark_steps = bool(mark_steps)
        # Guards submission (HTTP handler threads) against the elastic
        # restore's scheduler swap on the serve thread: a submit must
        # land either in the old scheduler BEFORE the restore captures
        # its contents, or in the rebuilt one — never in a discarded
        # deque (a silently dropped request).
        self._submit_lock = threading.Lock()
        self._decoder = dataclasses.replace(model, decode=True)
        self._sched = SlotScheduler(self.num_slots, queue_limit=queue_limit)
        self._requests = {}          # rid -> Request (live registry)
        self._step_count = 0
        self._served = 0
        self._tokens = np.zeros((self.num_slots,), np.int32)
        self._pos = np.zeros((self.num_slots,), np.int32)
        self._cache_valid = True
        self._stub = (step_fn, prefill_fn, install_fn)
        self._zero = jnp.zeros            # kept for runtime rebuilds
        self._build_runtime()
        global _current
        _current = self

    # --- compiled programs ----------------------------------------------

    def _build_runtime(self):
        """(Re)build the cache tree and the three jitted programs — called
        at construction and after an elastic backend rebuild (old
        executables and buffers die with the old PJRT client)."""
        import jax
        import jax.numpy as jnp

        from horovod_tpu.models.generate import init_decode_cache

        decoder = self._decoder
        S = self.num_slots
        step_fn, prefill_fn, install_fn = self._stub

        if step_fn is None:
            @functools.partial(jax.jit, donate_argnums=(1,))
            def step_fn(params, cache, toks, pos):
                logits, upd = decoder.apply(
                    {"params": params, "cache": cache}, toks[:, None],
                    pos=pos, mutable=["cache"])
                return logits[:, 0], upd["cache"]

        if prefill_fn is None:
            @jax.jit
            def prefill_fn(params, cache, toks, t):
                # batch-1 chunked feed at explicit positions (pos vector
                # path); logits discarded — prefill wants the K/V rows.
                _, upd = decoder.apply(
                    {"params": params, "cache": cache}, toks,
                    pos=jnp.full((1,), t, jnp.int32), mutable=["cache"])
                return upd["cache"]

        if install_fn is None:
            @functools.partial(jax.jit, donate_argnums=(0,))
            def install_fn(big, small, slot):
                def leaf(b, s_):
                    if getattr(b, "ndim", 0) >= 1 and b.shape[0] == S:
                        return jax.lax.dynamic_update_slice_in_dim(
                            b, s_.astype(b.dtype), slot, axis=0)
                    return b                 # scalar bookkeeping (cursor)
                return jax.tree_util.tree_map(leaf, big, small)

        self._step_fn = step_fn
        self._prefill_fn = prefill_fn
        self._install_fn = install_fn
        if self._stub[0] is not None:
            # Stubbed runtime (perf guard): no device trees at all.
            self._cache = {}
            self._small_zero = {}
            return
        self._cache = init_decode_cache(
            decoder, jnp.zeros((S, 1), jnp.int32),
            pos=jnp.zeros((S,), jnp.int32))
        self._small_zero = init_decode_cache(
            decoder, jnp.zeros((1, 1), jnp.int32),
            pos=jnp.zeros((1,), jnp.int32))

    # --- submission ------------------------------------------------------

    def submit(self, prompt, max_new, temperature=0.0, top_k=0, top_p=1.0,
               eos_id=None, seed=0):
        """Enqueue one request; returns the :class:`Request` (its
        ``result()`` blocks until completion). Raises
        :class:`~horovod_tpu.serving.scheduler.QueueFull` at the queue
        limit and ValueError when prompt + budget exceed the cache."""
        req = Request(prompt, max_new, temperature=temperature,
                      top_k=top_k, top_p=top_p, eos_id=eos_id, seed=seed)
        if len(req.prompt) + req.max_new > self.max_len:
            raise ValueError(
                f"prompt ({len(req.prompt)}) + max_new ({req.max_new}) "
                f"exceeds the engine's cache capacity ({self.max_len})")
        with self._submit_lock:
            self._sched.submit(req)      # raises QueueFull after reject()
            # Registered only once actually queued: rejected requests
            # must not pin their prompt in the live registry forever.
            self._requests[req.rid] = req
        # Root of the request's span tree: admission wall time. Rejected
        # requests never register — the trace store holds queued work.
        trace.register(req.tid, rid=req.rid, t0=req.t_wall)
        trace.add_instant(req.tid, "submit", t=req.t_wall, cat="serving")
        _flight.record_event("serving", what="submit", name=f"r{req.rid}",
                             trace=req.tid)
        return req

    # --- the serve loop ---------------------------------------------------

    def _prefill_into(self, slot, req):  # hvdrace: disable=HVR203 -- _tokens/_pos/_cache_valid are serve-thread-owned; the restore path only writes them under _submit_lock while serving is quiesced
        """Teacher-force the request's effective prompt (prompt + any
        committed tokens from a previous incarnation) into its slot."""
        import jax.numpy as jnp

        toks = req.full_tokens()
        P = len(toks)
        end = P - 1                       # last token is the decode input
        small = self._small_zero          # reusable zero template: the
        c = self.prefill_chunk            # un-donated feed never mutates it
        # Close the CURRENT incarnation's queue phase: t_queued restarts
        # at submit and at every requeue, so the span tree shows one
        # queue span per incarnation (before and after an elastic kill).
        now = time.time()
        trace.add_span(req.tid, "queue", t0=req.t_queued,
                       dur=max(now - req.t_queued, 0.0), cat="serving",
                       args={"slot": slot})
        t = 0
        while t < end:
            s = min(c, end - t)           # exact remainder: no pad rows
            chunk = jnp.asarray([toks[t:t + s]], jnp.int32)
            with trace.span("chunk", parent="prefill", cat="serving",
                            tid=req.tid):
                small = self._prefill_fn(self.params, small, chunk, t)
            t += s
        with trace.span("install", parent="prefill", cat="serving",
                        tid=req.tid):
            self._cache = self._install_fn(self._cache, small,
                                           np.int32(slot))
        self._tokens[slot] = toks[-1]
        self._pos[slot] = P - 1
        # A rollback always empties the slot table before invalidating,
        # so every active slot after it reaches the cache through THIS
        # prefill — the first admission makes the cache live again (the
        # readiness gate must not report a recovered engine CACHE-STALE
        # forever).
        self._cache_valid = True
        _flight.record_event("serving", what="admit", name=f"r{req.rid}",
                             seq=slot, trace=req.tid)

    def step(self):  # hvdrace: disable=HVR203 -- the serve loop is the scheduler's single consumer: _sched/_step_count reads here race nothing; _submit_lock guards only the submit-vs-commit/restore swap
        """One engine iteration: admit + prefill free slots, then one
        decode step for every active slot. Returns True when any work
        happened (False = idle)."""
        import jax.numpy as jnp

        for slot, req in self._sched.admit():
            self._prefill_into(slot, req)
        active = self._sched.active()
        if not active:
            return False
        t0 = time.perf_counter()
        logits, self._cache = self._step_fn(
            self.params, self._cache, jnp.asarray(self._tokens),
            jnp.asarray(self._pos))
        logits_np = np.asarray(logits)        # device sync
        dt = time.perf_counter() - t0
        # One decode_step span per batched slot, sharing the step's wall
        # window — the synthesized "decode" phase of each request's tree
        # is the envelope of its decode_step children, so the phase
        # covers the whole resident-in-batch stretch, gaps included.
        t_wall = time.time() - dt
        committed = 0
        for slot, req in active.items():
            trace.add_span(req.tid, "decode_step", t0=t_wall, dur=dt,
                           parent="decode", cat="serving")
            tok = sample_token(logits_np[slot], req.temperature,
                               req.top_k, req.top_p, req.seed,
                               len(req.committed))
            first = not req.committed
            finished = req.commit_token(tok)
            if first:
                ttft = req.t_first - req.t_submit
                _metrics.record_serving_ttft(ttft)
                _slo.observe_ttft(ttft)
            self._tokens[slot] = tok
            self._pos[slot] += 1
            committed += 1
            if finished:
                self._sched.retire(slot)
                req.finish()
                # The registry holds only live (restorable) requests —
                # without the prune, a long-running server leaks every
                # prompt + token list it ever served. A restore that
                # rolls back PAST this completion re-materializes the
                # request from the snapshot; the caller's already
                # resolved future keeps the identical (deterministic)
                # stream.
                with self._submit_lock:
                    self._requests.pop(req.rid, None)
                    self._served += 1
                _metrics.record_serving_request("completed")
                # Terminal stream phase (final-token delivery: host
                # sampling + future resolution), then close the root —
                # the trace's duration is the request's true wall time.
                t_end = t_wall + dt
                trace.add_span(req.tid, "stream", t0=t_end,
                               dur=max(time.time() - t_end, 0.0),
                               cat="serving")
                trace.finish(req.tid)
                _flight.record_event("serving", what="complete",
                                     name=f"r{req.rid}",
                                     dur=req.t_done - req.t_submit,
                                     trace=req.tid)
        _metrics.record_serving_step(dt, len(active), self.num_slots,
                                     committed)
        _slo.observe_tokens(committed)
        # Serving goodput: this step's token-seconds count as goodput iff
        # every declared SLO objective is within budget right now (burn
        # <= 1); with no declared objectives all traffic is in-SLO. The
        # burn read follows observe_tokens so the step judges itself.
        try:
            from horovod_tpu.goodput import ledger as _goodput
            burns = _slo.burn_rates()
            _goodput.record_serving_step(
                dt, committed,
                in_slo=all(b <= 1.0 for b in burns.values()))
        except Exception:  # noqa: BLE001
            pass
        self._step_count += 1
        if self.mark_steps:
            _flight.step_marker(self._step_count)
        return True

    def run_until_idle(self, max_steps=100000, commit=None):
        """Drive :meth:`step` until queue and slots drain; ``commit`` (an
        optional callable) runs after every step — the elastic commit
        hook the soak worker uses."""
        steps = 0
        while not self.idle():
            progressed = self.step()
            if commit is not None:
                commit()
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"serving engine did not drain in {max_steps} steps "
                    f"(queue={self._sched.queue_depth()}, "
                    f"active={self._sched.n_active()})")
            if not progressed and self.idle():
                break
        return steps

    def idle(self):
        return self._sched.idle()

    def queue_depth(self):
        """Admission-queue depth — cheap (no snapshot frame): hot loops
        (bench pacing, backpressure probes) poll it per iteration."""
        return self._sched.queue_depth()

    # --- elastic integration ----------------------------------------------

    def request_snapshot(self):
        """Picklable request-level state: active slots first (they re-admit
        ahead of the queue — FIFO completion order survives), then the
        queue, oldest first."""
        # The commit runs on the elastic coordination path while HTTP
        # submit threads race it; collect a consistent frame under the
        # same lock submit() takes, emit trace markers after release (the
        # trace store has its own lock — see load_request_snapshot).
        with self._submit_lock:
            active = dict(self._sched.active())
            snap = {
                "active": [active[s].snapshot() for s in sorted(active)],
                "queued": [r.snapshot() for r in self._sched.queued()],
                "served": self._served,
            }
        for req in active.values():
            # Commit marker (NOT a barrier: it must not break the decode
            # phase chain); the span cap bounds a long decode's markers.
            trace.add_instant(req.tid, "commit", cat="elastic",
                              args={"committed": len(req.committed)})
        return snap

    def kv_snapshot(self):
        """Host snapshot of the live slot caches + cursors (the migration
        payload; None when the runtime is stubbed or caches are stale)."""
        import jax
        if not self._cache_valid or self._stub[0] is not None:
            return None
        return {"cache": jax.device_get(self._cache),
                "pos": self._pos.copy(), "tokens": self._tokens.copy(),
                "slots": {s: r.rid
                          for s, r in self._sched.active().items()}}

    def load_request_snapshot(self, snap):
        """Restore request-level state from :meth:`request_snapshot`.
        Known rids keep their live Request objects (callers' futures stay
        wired); unknown rids (a worker that joined after submission)
        materialize fresh ones. Active-at-snapshot requests re-queue at
        the head — the cache that backed them is declared stale. Live
        requests submitted AFTER the snapshot was taken are merged in
        behind it (a restore must not drop work that arrived since the
        last commit)."""
        if snap is None:
            return
        with self._submit_lock:
            emissions = self._load_request_snapshot_locked(snap)
        # Trace/flight/metrics sinks each take their own lock; emitting
        # them while holding _submit_lock would order _submit_lock before
        # every sink lock on this path while other paths (submit, step)
        # build the opposite nesting — run them after the swap publishes.
        for emit in emissions:
            emit()

    def _load_request_snapshot_locked(self, snap):
        emissions = []
        snap_rids = {rs["rid"]
                     for rs in list(snap.get("active", ()))
                     + list(snap.get("queued", ()))}
        # Requests running in THIS engine right now are the ones the
        # rollback actually re-queues (the sync that follows a restore
        # replays the same snapshot over an already-queued set — that
        # second pass must not double-count).
        was_active = {r.rid for r in self._sched.active().values()}
        later = [r for r in list(self._sched.active().values())
                 + self._sched.queued()
                 if r.rid not in snap_rids and not r.done()]
        self._sched = SlotScheduler(self.num_slots,
                                    queue_limit=self._sched.queue_limit)
        self._served = int(snap.get("served", 0))
        for rs in list(snap.get("active", ())) + list(snap.get("queued",
                                                               ())):
            req = self._requests.get(rs["rid"])
            if req is not None \
                    and req.identity() != Request.snapshot_identity(rs):
                # Cross-process rid collision: rids are process-local
                # counters, so a broadcast snapshot (scale-up sync) can
                # carry another worker's request under a rid a DIFFERENT
                # local request already owns. Never graft the foreign
                # committed tokens onto it — materialize the snapshot's
                # request separately and leave the local one's registry
                # slot (and its caller's future) alone.
                req = None
                register = False
            else:
                register = True
            if req is None:
                # The snapshot's tid keeps the trace ONE contiguous tree
                # across the kill: the restored request re-registers
                # under the id minted at original admission (idempotent —
                # spans recorded before the disruption survive).
                req = Request(rs["prompt"], rs["max_new"],
                              temperature=rs["temperature"],
                              top_k=rs["top_k"], top_p=rs["top_p"],
                              eos_id=rs["eos_id"], seed=rs["seed"],
                              rid=rs["rid"], tid=rs.get("tid"))
                if register:
                    self._requests[req.rid] = req
            req.restore_committed(rs["committed"])
            # Monotonic: the committed snapshot's count can only LAG the
            # live one (the bump below, or an eviction that preceded this
            # sync) — a replay of the same snapshot must never roll the
            # disruption accounting back.
            req.requeues = max(req.requeues, int(rs.get("requeues", 0)))
            req.t_queued = time.time()
            emissions.append(lambda req=req, rs=rs: trace.register(
                req.tid, rid=req.rid, t0=rs.get("t0")))
            if req.rid in was_active:
                req.requeues += 1
                emissions.append(
                    lambda: _metrics.record_serving_request("requeued"))
                # Barrier instant: spans after it open a FRESH incarnation
                # of their phase (queue/prefill again) instead of nesting
                # under the pre-kill one.
                emissions.append(lambda req=req: trace.add_instant(
                    req.tid, "requeue", cat="elastic", barrier=True,
                    args={"committed": len(req.committed),
                          "requeues": req.requeues}))
                emissions.append(lambda req=req: _flight.record_event(
                    "serving", what="requeue", name=f"r{req.rid}",
                    trace=req.tid))
            else:
                emissions.append(lambda req=req: trace.add_instant(
                    req.tid, "restore", cat="elastic", barrier=True))
            self._sched.enqueue_restored(req)
        for req in later:
            self._sched.enqueue_restored(req)
        self._cache_valid = False
        self._pos[:] = 0
        self._tokens[:] = 0
        return emissions

    def invalidate_cache(self):
        """Mark slot caches unusable (a restore rolled requests behind the
        cache's cursors)."""
        self._cache_valid = False

    def detach_to_host(self):
        """Pull the cache tree to host memory before a backend teardown
        (the graceful-migration path: buffers of the dying PJRT client
        must not leak, but the K/V VALUES survive as numpy)."""
        import jax
        if self._stub[0] is None and self._cache_valid:
            self._cache = jax.device_get(self._cache)

    def reset_runtime(self, kv=None):
        """Rebuild programs + caches on the (possibly new) backend after
        an elastic membership change.

        Priority: an explicit ``kv`` snapshot (committed migration
        payload) > the live detached cache (graceful host-update with
        ``migrate_kv``) > evict-and-requeue (in-flight requests re-enter
        the queue from their last committed token and re-prefill)."""
        import jax
        import jax.numpy as jnp

        live = None
        if kv is not None:
            live = kv
        elif self.migrate_kv and self._cache_valid \
                and self._stub[0] is None:
            live = {"cache": self._cache, "pos": self._pos.copy(),
                    "tokens": self._tokens.copy(),
                    "slots": {s: r.rid
                              for s, r in self._sched.active().items()}}
        self._build_runtime()
        if live is not None and self._stub[0] is None:
            # Re-place the migrated K/V rows on the new backend. Slot
            # assignments and cursors resume exactly where the snapshot
            # left them — no re-prefill, zero recompute.
            self._cache = jax.tree_util.tree_map(jnp.asarray,
                                                 live["cache"])
            self._pos[:] = live["pos"]
            self._tokens[:] = live["tokens"]
            active = {r.rid: r for r in self._sched.active().values()}
            want = live.get("slots", {})
            if set(want.values()) != set(active):
                # Snapshot and scheduler disagree (snapshot predates a
                # load_request_snapshot eviction): fall back to requeue.
                self._evict_all()
            self._cache_valid = True
            return
        self._evict_all()
        self._cache_valid = True

    def _evict_all(self):
        for req in self._sched.evict_active():
            req.t_queued = time.time()
            trace.add_instant(req.tid, "requeue", cat="elastic",
                              barrier=True)
            _flight.record_event("serving", what="requeue",
                                 name=f"r{req.rid}", trace=req.tid)
        self._pos[:] = 0
        self._tokens[:] = 0

    # --- observability ----------------------------------------------------

    def snapshot(self):
        """One JSON-able frame for ``/serving/health`` and the telemetry
        readiness gate."""
        # HTTP threads race submit/restore here; read the scheduler frame
        # under the lock, compute the (lock-taking) SLO read outside it.
        with self._submit_lock:
            sched = self._sched
            active = dict(sched.active())
            frame = {
                "t": time.time(),
                "slots": self.num_slots,
                "active": len(active),
                "queue_depth": sched.queue_depth(),
                "queue_limit": sched.queue_limit,
                "fill_ratio": round(sched.fill_ratio(), 4),
                "served": self._served,
                "steps": self._step_count,
                "max_len": self.max_len,
                "cache_valid": self._cache_valid,
                "requests": {
                    str(s): {"rid": r.rid, "generated": len(r.committed),
                             "budget": r.max_new, "requeues": r.requeues}
                    for s, r in active.items()},
                # Saturation = queue at (or beyond) its declared limit:
                # the load balancer should stop sending here.
                "saturated": bool(sched.queue_limit
                                  and sched.queue_depth()
                                  >= sched.queue_limit),
            }
        # {} unless SLO objectives are declared (HOROVOD_SLO_*); the
        # read also refreshes the slo_burn_rate{objective} gauges.
        frame["slo"] = _slo.burn_rates()
        try:
            from horovod_tpu.goodput import ledger as _goodput
            gp = _goodput.serving_snapshot()
            if gp.get("steps"):
                frame["goodput"] = gp
        except Exception:  # noqa: BLE001
            pass
        return frame
