"""ServingState: elastic commit/restore/sync for a live serving engine.

Extends :class:`horovod_tpu.elastic.TpuState` so the serving fleet rides
the SAME rendezvous machinery as training: ``commit()`` snapshots the
model params (a tracked tree) plus the request-level state (queue order
and every in-flight request's committed tokens — a picklable attr that
``sync()`` broadcasts to workers joining at scale-up); on a collective
failure ``restore()`` rolls requests back to the last commit and marks
the slot caches stale; on a membership change ``reset()`` rebuilds the
engine runtime on the new backend, either MIGRATING the in-flight K/V
caches (graceful host updates detach them to host first —
``HOROVOD_SERVING_MIGRATE_KV``) or re-queuing every in-flight request
from its last committed token for re-prefill.

Either way the zero-drop invariant holds: a request is never lost and
never skips ahead — its remaining tokens are reproduced exactly
(position-keyed sampling), so a rolling restart or worker kill is
invisible in the token streams.

Usage (the chaos soak's shape)::

    engine = ServingEngine(model, params, num_slots=4)
    reqs = [engine.submit(p, max_new=8) for p in prompts]
    state = ServingState(engine, step=0)
    elastic.attach_listener(state)

    @elastic.run
    def serve(state):
        def commit():
            state.step += 1
            state.commit()
        engine.run_until_idle(commit=commit)
        return [r.result(0) for r in reqs]
"""

from horovod_tpu.elastic.state import TpuState


class ServingState(TpuState):
    def __init__(self, engine, trees=None, **kwargs):
        self._engine = engine
        self._params_src = None      # identity of the last-saved params
        all_trees = {"params": engine.params}
        all_trees.update(trees or {})
        kwargs.setdefault("reqs", engine.request_snapshot())
        super().__init__(trees=all_trees, **kwargs)

    def save(self):
        self.reqs = self._engine.request_snapshot()
        # Keep the tracked params tree pointed at the engine's live one
        # (LoRA hot-swaps replace engine.params between commits).
        self._trees["params"] = self._engine.params
        # Serving commits run per step GROUP (default cadence 1 = per
        # generated token): re-snapshotting the params tree every commit
        # would device_get the whole model per token even though serving
        # never mutates it. Reuse the previous host copy while the live
        # tree is the SAME object (the engine never donates params; a
        # LoRA hot-swap replaces the object and forces a fresh copy).
        prev = self._saved_trees.get("params") \
            if self._params_src is self._engine.params else None
        if prev is not None:
            del self._trees["params"]
            try:
                super().save()
            finally:
                self._trees["params"] = self._engine.params
            self._saved_trees["params"] = prev
        else:
            super().save()
        self._params_src = self._engine.params

    def restore(self):
        super().restore()
        self._engine.params = self._trees["params"]
        self._params_src = None      # restored copy: re-snapshot next save
        # Requests roll back to the last commit; the device caches are now
        # AHEAD of the committed streams, so they are stale by definition.
        self._engine.load_request_snapshot(self.reqs)
        self._engine.invalidate_cache()

    def sync(self):
        super().sync()
        self._engine.params = self._trees["params"]
        self._params_src = None      # broadcast copy: re-snapshot next save
        # Joining workers materialize the broadcast request set; existing
        # workers merge (known rids keep their caller futures). A worker
        # whose live request state ALREADY equals the broadcast snapshot
        # — the graceful-migration boundary: commit, membership change,
        # sync — skips the merge: rolling back to an identical snapshot
        # would evict the freshly migrated slot caches for nothing.
        if self._engine.request_snapshot() != self.reqs:
            self._engine.load_request_snapshot(self.reqs)

    def detach_to_host(self):
        # Engine first: the K/V migration payload must leave the dying
        # backend before TpuState detaches the params.
        self._engine.detach_to_host()
        super().detach_to_host()

    def reset(self):
        # New backend, new (possibly resized) world: rebuild the runtime.
        # The engine migrates its detached live cache when armed for it
        # and the slot table survived; otherwise it evicts-and-requeues.
        self._engine.reset_runtime()
        super().reset()
