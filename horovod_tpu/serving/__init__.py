"""Elastic continuous-batching inference (docs/inference.md).

A request queue + slot scheduler front a sharded causal LM: admitted
requests pack into a fixed-slot decode batch with a real per-slot KV
cache, slots retire and refill independently, and the whole state rides
the elastic rendezvous machinery so scale up/down (or a worker kill)
drops zero in-flight requests. SLO metrics land on the standard scrape
endpoint; ``telemetry top --once --serving`` is the load-balancer
readiness gate.
"""

from horovod_tpu.serving.engine import (  # noqa: F401
    ServingEngine, get_engine, sample_token, serving_snapshot,
)
from horovod_tpu.serving.request import Request  # noqa: F401
from horovod_tpu.serving.scheduler import (  # noqa: F401
    QueueFull, SlotScheduler,
)
from horovod_tpu.serving.state import ServingState  # noqa: F401
