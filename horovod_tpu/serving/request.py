"""Serving request: the unit the continuous-batching engine schedules.

A request is host-side bookkeeping only — prompt token ids in, generated
token ids out — so the scheduler and the elastic requeue story stay pure
python (fast tier-1 testable, picklable into an elastic commit). The
engine owns every device interaction.

The elastic contract rides on ``committed``: tokens the engine has
sampled AND the caller's elastic state has committed. After a disruption
the request re-enters the queue with ``prompt + committed`` as its
effective prompt (:meth:`full_tokens`) — decoding resumes from the last
committed token, never from scratch and never skipping ahead, which is
what makes a rolling restart drop zero in-flight requests (greedy
decoding then reproduces the exact token stream of an undisturbed run;
sampled decoding reproduces it too because draws are keyed on
``(seed, position)``, see :meth:`draw`).
"""

import itertools
import threading
import time

from horovod_tpu import trace

QUEUED = "queued"
ACTIVE = "active"
DONE = "done"
REJECTED = "rejected"

_rid_counter = itertools.count()


class Request:
    """One generation request.

    ``prompt``: list/array of int token ids (at least one).
    ``max_new``: generation budget AFTER the prompt.
    ``temperature`` 0 = greedy; otherwise a categorical draw keyed on
    ``(seed, position)`` so a requeued request re-draws the same tokens.
    ``eos_id``: generation stops when the engine samples it (the EOS
    itself is committed, matching ``models.generate``'s semantics).
    """

    def __init__(self, prompt, max_new, temperature=0.0, top_k=0,
                 top_p=1.0, eos_id=None, seed=0, rid=None, tid=None):
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        if temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if top_k < 0 or not 0.0 < top_p <= 1.0:
            raise ValueError(f"need top_k >= 0 and 0 < top_p <= 1, got "
                             f"top_k={top_k}, top_p={top_p}")
        self.rid = rid if rid is not None else next(_rid_counter)
        # Trace id: minted at admission, carried through every elastic
        # snapshot/restore so the span tree stays ONE trace across
        # disruptions (horovod_tpu/trace).
        self.tid = tid if tid is not None else trace.mint("request")
        self.prompt = prompt
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.seed = int(seed)
        self.committed = []          # generated tokens, oldest first
        self.state = QUEUED
        self.requeues = 0
        self.t_submit = time.monotonic()
        self.t_first = None          # first generated token commit
        self.t_done = None
        # Wall-clock twins for the trace layer: monotonic stamps cannot
        # cross processes, and the span tree is wall-time based. t_queued
        # restarts at every (re)entry into the admission queue — it is
        # the start of the CURRENT incarnation's queue span.
        self.t_wall = time.time()
        self.t_queued = self.t_wall
        self._done = threading.Event()

    # --- engine-side transitions ---------------------------------------

    def full_tokens(self):
        """prompt + committed — the effective prompt after a requeue."""
        return self.prompt + self.committed

    def remaining(self):
        return self.max_new - len(self.committed)

    def commit_token(self, tok):
        """Record one generated token; returns True when the request is
        finished (EOS sampled or budget exhausted)."""
        self.committed.append(int(tok))
        if self.t_first is None:
            self.t_first = time.monotonic()
        return (self.eos_id is not None and int(tok) == self.eos_id) \
            or len(self.committed) >= self.max_new

    def finish(self):
        self.state = DONE
        self.t_done = time.monotonic()
        self._done.set()

    def reject(self):
        self.state = REJECTED
        self._done.set()

    # --- caller-side API ------------------------------------------------

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block until finished; returns prompt + generated tokens.
        Raises on rejection (queue full) or timeout."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.rid} not done "
                               f"after {timeout}s")
        if self.state == REJECTED:
            raise RuntimeError(f"request {self.rid} rejected (queue full)")
        return self.full_tokens()

    # --- elastic snapshot ------------------------------------------------

    def identity(self):
        """Everything that determines the request's token stream — the
        rid-collision check must compare ALL of it: rids are
        process-local counters, and two workers' unrelated requests can
        share a rid AND a prompt while differing in budget or sampling
        params."""
        return (tuple(self.prompt), self.max_new, self.temperature,
                self.top_k, self.top_p, self.eos_id, self.seed)

    @staticmethod
    def snapshot_identity(rs):
        """:meth:`identity` of a :meth:`snapshot` dict."""
        return (tuple(int(t) for t in rs["prompt"]), int(rs["max_new"]),
                float(rs["temperature"]), int(rs["top_k"]),
                float(rs["top_p"]),
                None if rs["eos_id"] is None else int(rs["eos_id"]),
                int(rs["seed"]))

    def snapshot(self):
        """Picklable state for an elastic commit (threading.Event and
        timestamps stay process-local)."""
        return {"rid": self.rid, "tid": self.tid, "t0": self.t_wall,
                "prompt": list(self.prompt),
                "max_new": self.max_new, "temperature": self.temperature,
                "top_k": self.top_k, "top_p": self.top_p,
                "eos_id": self.eos_id, "seed": self.seed,
                "committed": list(self.committed),
                "requeues": self.requeues}

    def restore_committed(self, committed):
        """Roll generated tokens back/forward to an elastic snapshot's
        committed list (restore after a failed step group)."""
        self.committed = [int(t) for t in committed]
        if not self.committed:
            # Rolled back past the first generated token: the next first
            # commit is the user-visible first token again, so TTFT must
            # re-measure through the disruption — a stale pre-rollback
            # timestamp would understate the post-disruption SLO.
            self.t_first = None
