"""Serving chaos soak — the acceptance leg of the serving subsystem.

Drives an 8-process elastic SERVING cluster (every worker runs the
continuous-batching engine over a replicated tiny GPT, committing
through :class:`ServingState` with a fleet-heartbeat allreduce per step
group) through a seeded worker-kill + rolling-restart plan, and asserts
the zero-drop invariants:

1. every submitted request completes on every surviving worker
   (zero in-flight drops across two staggered worker kills),
2. every completed token stream equals the single-process clean run's
   exactly (requeue-from-committed-token + greedy determinism),
3. elastic resets stay within the plan's kill budget (no flapping),
4. the flight-recorder dumps localize each kill: the victim's rank, the
   first unmatched heartbeat-collective sequence number, and the
   causing injection (:func:`chaos.soak._assert_flight_forensics`),
5. trace continuity: every request's span tree is one contiguous trace
   id from admission through requeue-from-committed-tokens to
   completion, with the requeue/restore barrier markers present
   (horovod_tpu/trace; the mid-flight-kill steps make this a real
   through-the-disruption check, not a clean-path one).

The heartbeat allreduce is not test scaffolding only: serving fleets
exchange load/SLO accounting the same way, and it is what makes every
survivor fail FAST into the elastic recovery path on a peer kill
instead of decoding obliviously past a dead rank.

CLI: ``python -m horovod_tpu.serving.soak``; runbook:
docs/robustness.md. Marked slow in tests (tests/test_serving_soak.py).
"""

import json
import os

import numpy as np

from horovod_tpu.chaos import soak as _base


def soak_model():
    """The fixture every process (and the clean reference) builds
    identically: tiny GPT, seeded init — replicated serving compute."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models import GPT, GPTConfig

    cfg = GPTConfig.tiny(tp_axis=None, ep_axis=None,
                         max_position_embeddings=48)
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params, cfg


def soak_prompts(n_requests, vocab, seed=5):
    """Deterministic request set (lengths 2..6, seeded token ids)."""
    rng = np.random.default_rng(seed)
    return [[int(t) for t in
             rng.integers(0, vocab, size=int(rng.integers(2, 7)))]
            for _ in range(n_requests)]


def expected_streams(n_requests, max_new):
    """Single-process clean run: the token streams every soak worker
    must reproduce bit-for-bit."""
    from horovod_tpu.serving import ServingEngine

    model, params, cfg = soak_model()
    engine = ServingEngine(model, params, num_slots=2, mark_steps=False)
    reqs = [engine.submit(p, max_new=max_new)
            for p in soak_prompts(n_requests, cfg.vocab_size)]
    engine.run_until_idle()
    return [[int(t) for t in r.result(0)] for r in reqs]


def serving_soak_worker(n_requests, max_new, slots):
    """The per-worker serve loop (importable by name — spawned workers
    resolve it from the installed package)."""
    import jax.numpy as jnp

    import horovod_tpu as hvd
    from horovod_tpu import elastic
    from horovod_tpu.serving import ServingEngine, ServingState

    hvd.init()
    model, params, cfg = soak_model()
    engine = ServingEngine(model, params, num_slots=slots,
                           mark_steps=False)
    reqs = [engine.submit(p, max_new=max_new)
            for p in soak_prompts(n_requests, cfg.vocab_size)]
    state = ServingState(engine, step=0, worlds=[])
    elastic.attach_listener(state)

    @elastic.run
    def serve(state):
        def commit():
            # Fleet heartbeat: one tiny allreduce per step group — the
            # load-accounting exchange a real fleet runs anyway. It makes
            # every survivor fail FAST on a peer kill (collective error →
            # elastic restore) and gives the flight forensics a collective
            # sequence stream to localize the victim with.
            hvd.allreduce(jnp.ones((1, 1)), op=hvd.Average)
            state.step += 1
            state.worlds.append(hvd.process_count())
            state.commit()

        engine.run_until_idle(commit=commit)
        snap = hvd.metrics_snapshot()

        from horovod_tpu import trace as _trace
        req_traces = []
        for r in reqs:
            rec = _trace.get(r.tid) or {}
            names = [s["name"] for s in rec.get("spans", ())]
            req_traces.append({
                "rid": r.rid, "tid": r.tid,
                # one contiguous id: the rid still resolves to the tid
                # minted at original admission, across every kill.
                "same_tid": _trace.for_rid(r.rid) == r.tid,
                "done": bool(rec.get("done")),
                "requeue_marks": names.count("requeue"),
                "restore_marks": names.count("restore"),
                "queue_spans": names.count("queue"),
                "stream_spans": names.count("stream"),
                "requeues": r.requeues,
            })

        def count(name, labels=None):
            total = 0
            for s in snap.get(name, {}).get("series", ()):
                if labels is None or all(s["labels"].get(k) == v
                                         for k, v in labels.items()):
                    total += s.get("count", s.get("value", 0))
            return total

        return {
            "streams": [[int(t) for t in r.result(0)] for r in reqs],
            "requeues": sum(r.requeues for r in reqs),
            "worlds": list(state.worlds),
            "final_world": hvd.process_count(),
            "cross_rank": hvd.cross_rank(),
            "resets": count("elastic_events_total", {"event": "reset"}),
            "completed": count("serving_requests_total",
                               {"event": "completed"}),
            "requeued_events": count("serving_requests_total",
                                     {"event": "requeued"}),
            "ttft_count": count("serving_ttft_seconds"),
            "req_traces": req_traces,
            "cluster": _base.wait_cluster_view(),
        }

    return serve(state)


def rolling_kill_plan(procs, seed, first_step=3, second_step=8):
    """Two staggered worker kills — the rolling-restart drill: the fleet
    shrinks twice while requests are in flight, and each shrink must
    re-queue-from-committed, not drop.

    The kill steps are chosen so the survivors DETECT the failure (their
    next heartbeat allreduce, one commit later) mid-generation: with
    ``slots=2`` and ``max_new=5`` every slot pair retires on commits
    ≡ 0 (mod 5), so a kill at a step ≡ 4 (mod 5) would surface exactly
    in the retired-but-not-yet-refilled window where nothing is in
    flight and no requeue is forced — steps 3 and 8 land the detection
    on commits 4 and 9, mid-flight for both slot pairs."""
    victims = [procs - 3 if procs > 3 else procs - 1, 2 % procs]
    return victims, {
        "seed": seed,
        "note": f"serving soak: rolling kills r{victims[0]}@s{first_step}"
                f", r{victims[1]}@s{second_step}",
        "faults": [
            {"site": "elastic.commit", "kind": "crash",
             "rank": victims[0], "at_step": [first_step], "max_fires": 1},
            {"site": "elastic.commit", "kind": "crash",
             "rank": victims[1], "at_step": [second_step],
             "max_fires": 1},
        ],
    }


def _elastic_serving_run(procs, min_np, workdir, chaos_env, n_requests,
                         max_new, slots):
    from horovod_tpu.runner import run_elastic

    script = os.path.join(workdir, "discover.sh")
    _base._write_discovery(script, procs)
    env = {
        "HOROVOD_BLACKLIST_COOLDOWN_RANGE": "600,600",
        "HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT": "5",
    }
    env.update(chaos_env)
    with _base._scoped_env(env):
        return run_elastic(serving_soak_worker,
                           args=(n_requests, max_new, slots),
                           min_np=min_np, host_discovery_script=script)


def run_serving_soak(procs=8, n_requests=10, max_new=5, slots=2,
                     seed=123, workdir=None):
    """Clean reference + chaos serving run; asserts the zero-drop
    invariants and returns the evidence dict."""
    import tempfile

    workdir = workdir or tempfile.mkdtemp(prefix="hvd_serving_soak_")
    os.makedirs(workdir, exist_ok=True)
    victims, plan_dict = rolling_kill_plan(procs, seed)
    budget = _base.plan_kill_budget(plan_dict)
    min_np = max(procs - budget, 1)
    plan_path = os.path.join(workdir, "plan.yaml")
    with open(plan_path, "w") as f:
        json.dump(plan_dict, f)
    ledger_dir = os.path.join(workdir, "ledger")
    flight_dir = os.path.join(workdir, "flight")

    _base._progress("serving soak clean reference", procs=procs,
                    requests=n_requests)
    expected = expected_streams(n_requests, max_new)

    _base._progress("serving soak chaos run start", victims=victims)
    try:
        results = _elastic_serving_run(procs, min_np, workdir, {
            "HOROVOD_CHAOS_PLAN": plan_path,
            "HOROVOD_CHAOS_SEED": str(seed),
            "HOROVOD_CHAOS_LEDGER": ledger_dir,
            "HOROVOD_FLIGHT_DIR": flight_dir,
        }, n_requests, max_new, slots)
    finally:
        from horovod_tpu import chaos
        chaos.uninstall()
    _base._progress("serving soak chaos run done", hosts=len(results))

    evidence = {"procs": procs, "plan": plan_dict, "victims": victims,
                "kill_budget": budget, "workdir": workdir,
                "expected": expected, "results": results}
    # (1) zero drops: every worker completed every submitted request...
    for r in results:
        assert len(r["streams"]) == n_requests, r
        assert r["completed"] >= n_requests, r
        # (2) ...with token streams identical to the clean run.
        assert r["streams"] == expected, (
            f"worker r{r['cross_rank']} token streams diverged from the "
            f"clean run under chaos")
        # (3) no flapping: resets within the kill budget.
        assert r["resets"] <= budget, r
        assert r["final_world"] == procs - budget, r
        assert r["ttft_count"] >= n_requests, r
    # The disruption actually forced requeues on at least one survivor.
    assert any(r["requeued_events"] > 0 or r["requeues"] > 0
               for r in results), results
    # (5) trace continuity across the kills: every request's span tree
    # is ONE contiguous trace — the rid resolves to the id minted at
    # original admission and the root closed — and at least one
    # mid-flight victim's request shows the requeue barrier followed by
    # a fresh queue incarnation under the SAME tid, with restore
    # markers present on the replayed queued set.
    for r in results:
        for t in r["req_traces"]:
            assert t["same_tid"] and t["done"] and t["stream_spans"] >= 1, \
                (r["cross_rank"], t)
    assert any(t["requeue_marks"] > 0 and t["queue_spans"] >= 2
               for r in results for t in r["req_traces"]), \
        [r["req_traces"] for r in results]
    assert any(t["restore_marks"] > 0
               for r in results for t in r["req_traces"]), \
        [r["req_traces"] for r in results]
    # Both kills fired, exactly once each.
    from horovod_tpu.chaos import injector
    entries = injector.read_ledger(ledger_dir)
    kills = [e for e in entries if e["kind"] == "crash"]
    assert len(kills) == budget, entries
    assert sorted({k["rank"] for k in kills}) == sorted(set(victims)), \
        kills
    # (4) flight forensics localize each kill.
    evidence["flight_report"] = _base._assert_flight_forensics(
        flight_dir, ledger_dir, kills, procs)
    _base._progress("serving soak done", ok=True)
    return evidence


def main():
    ev = run_serving_soak()
    print(json.dumps({"ok": True, "workdir": ev["workdir"],
                      "victims": ev["victims"],
                      "requests": len(ev["expected"])}))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
