"""``python -m horovod_tpu.serving`` — the reference serving worker.

What ``hvdrun --serving`` launches when you have no app of your own::

    hvdrun --serving --serving-port 9000 -np 8 \
        python -m horovod_tpu.serving

Each worker initializes the collective runtime, builds the configured
model (``HOROVOD_SERVING_MODEL``: ``gpt_tiny`` [default, random weights
— a smoke/load-test target], ``gpt2`` or ``llama_tiny``; point real
deployments at a checkpoint via ``--serving`` + your own script), and
serves ``POST /generate`` on ``HOROVOD_SERVING_PORT + local_rank``. The
metrics endpoint (``HOROVOD_METRICS_PORT``) carries ``/serving/health``
and the SLO series; under ``HOROVOD_ELASTIC`` the engine state rides a
:class:`~horovod_tpu.serving.state.ServingState` so membership changes
drop zero in-flight requests.
"""

import signal
import sys
import time


def build_model(name, max_len):
    import jax
    import jax.numpy as jnp

    from horovod_tpu import models

    if name == "gpt2":
        cfg = models.GPTConfig(max_position_embeddings=max_len,
                               tp_axis=None, ep_axis=None)
        model = models.GPT(cfg)
    elif name == "llama_tiny":
        cfg = models.LlamaConfig.tiny(tp_axis=None,
                                      max_position_embeddings=max_len)
        model = models.Llama(cfg)
    else:
        cfg = models.GPTConfig.tiny(tp_axis=None, ep_axis=None,
                                    max_position_embeddings=max_len)
        model = models.GPT(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 4), jnp.int32))["params"]
    return model, params


def main():
    import horovod_tpu as hvd
    from horovod_tpu.common.config import Config
    from horovod_tpu.serving import ServingEngine, ServingState
    from horovod_tpu.serving.server import ServingFrontend

    hvd.init()
    # SIGTERM (how hvdrun's elastic driver and any orchestrator stop a
    # worker) must unwind like Ctrl-C: only fe.stop() persists the
    # HOROVOD_TRACE_DIR shard, and the default disposition skips it. The
    # 5 s terminate→kill escalation in runner/exec.py bounds the drain.
    def _term(signum, frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _term)
    cfg = Config.from_env()
    name = cfg.serving_model
    max_len = cfg.serving_max_len or 256
    model, params = build_model(name, max_len)
    engine = ServingEngine(
        model, params, num_slots=cfg.serving_slots, max_len=max_len,
        prefill_chunk=cfg.serving_prefill_chunk,
        queue_limit=cfg.serving_queue_limit,
        migrate_kv=cfg.serving_migrate_kv)
    port = cfg.serving_port + hvd.local_rank() if cfg.serving_port else 0
    fe = ServingFrontend(engine, port=port, addr=cfg.metrics_addr,
                         drive=not cfg.elastic)
    bound = fe.start()
    print(f"# serving {name} on :{bound} "
          f"(slots={engine.num_slots}, max_len={engine.max_len})",
          file=sys.stderr, flush=True)

    if cfg.elastic:
        from horovod_tpu import elastic

        state = ServingState(engine, step=0)
        elastic.attach_listener(state)

        @elastic.run
        def serve(state):
            # One thread owns stepping AND committing (the frontend only
            # enqueues): a commit must never race a half-applied step.
            cadence = max(cfg.serving_commit_steps, 1)
            idle_commit_s = 0.25
            last_commit = time.monotonic()
            while True:
                if engine.step():
                    state.step += 1
                    if state.step % cadence == 0:
                        state.commit()
                        last_commit = time.monotonic()
                else:
                    # Idle: nothing new to snapshot — but commit() is
                    # also the membership poll (check_host_updates), so
                    # keep a low-rate heartbeat instead of spinning
                    # full-cadence params snapshots at ~500/s.
                    time.sleep(0.002)
                    now = time.monotonic()
                    if now - last_commit >= idle_commit_s:
                        state.commit()
                        last_commit = now

        try:
            serve(state)
        except KeyboardInterrupt:
            fe.stop()
        return 0
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        fe.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
