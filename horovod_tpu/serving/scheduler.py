"""Slot scheduler: continuous batching over a fixed-slot decode batch.

The decode program has a FIXED batch of ``num_slots`` rows (static
shapes — one compiled program for the engine's lifetime); scheduling is
therefore slot assignment, not batch construction: a finished slot
retires and refills from the FIFO admission queue on the next step
while its neighbours keep decoding (continuous batching, not static
batches — no request ever waits for a stranger's last token).

Pure host-side python (no jax): the slot lifecycle, the requeue
ordering, and the queue-depth accounting are all tier-1 testable
without touching a device, and the engine perf guard can bound this
layer's cost with the device program stubbed out.
"""

from collections import deque

from horovod_tpu.metrics import instruments as _metrics
from horovod_tpu.serving import request as _rq


class QueueFull(RuntimeError):
    """Admission queue at capacity — the caller's backpressure signal."""


class SlotScheduler:
    def __init__(self, num_slots, queue_limit=0):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_slots = int(num_slots)
        self.queue_limit = int(queue_limit)      # 0 = unbounded
        self._queue = deque()
        self._slots = [None] * self.num_slots    # slot -> Request | None

    # --- admission -------------------------------------------------------

    def submit(self, req):
        """FIFO admission; raises :class:`QueueFull` at the limit (the
        request is marked rejected so a waiting caller unblocks)."""
        if self.queue_limit and len(self._queue) >= self.queue_limit:
            req.reject()
            _metrics.record_serving_request("rejected")
            _metrics.record_serving_queue(len(self._queue))
            raise QueueFull(
                f"serving queue at capacity ({self.queue_limit}); "
                f"request {req.rid} rejected")
        self._queue.append(req)
        _metrics.record_serving_request("submitted")
        _metrics.record_serving_queue(len(self._queue))
        return req

    def enqueue_restored(self, req):
        """Re-materialize a request during an elastic restore: appended in
        snapshot order, past the queue limit (restores must never drop or
        re-count work), no lifecycle metrics."""
        req.state = _rq.QUEUED
        self._queue.append(req)

    def requeue(self, req):
        """Put an in-flight request BACK at the head of the queue (elastic
        disruption / slot eviction): it resumes from its last committed
        token before any younger queued request is admitted, preserving
        FIFO completion order."""
        req.state = _rq.QUEUED
        req.requeues += 1
        self._queue.appendleft(req)
        _metrics.record_serving_request("requeued")
        _metrics.record_serving_queue(len(self._queue))

    def admit(self):
        """Fill free slots from the queue head; returns the new
        ``[(slot, request)]`` assignments (engine prefills each)."""
        placed = []
        for s in range(self.num_slots):
            if self._slots[s] is None and self._queue:
                req = self._queue.popleft()
                req.state = _rq.ACTIVE
                self._slots[s] = req
                placed.append((s, req))
                _metrics.record_serving_request("admitted")
        if placed:
            _metrics.record_serving_queue(len(self._queue))
        return placed

    # --- slot lifecycle ---------------------------------------------------

    def retire(self, slot):
        """Free a slot; returns the request that occupied it."""
        req = self._slots[slot]
        self._slots[slot] = None
        return req

    def evict_active(self):
        """Requeue EVERY active request from its last committed token
        (elastic membership change: slot caches die with the old backend).
        Slot order keeps completion order stable: lower slots were
        admitted earlier, so they re-enter the queue head first."""
        active = [(s, r) for s, r in enumerate(self._slots)
                  if r is not None]
        for s, req in reversed(active):      # appendleft ⇒ reverse order
            self._slots[s] = None
            self.requeue(req)
        return [r for _, r in active]

    # --- introspection ----------------------------------------------------

    def active(self):
        """{slot: request} for occupied slots."""
        return {s: r for s, r in enumerate(self._slots) if r is not None}

    def n_active(self):
        return sum(1 for r in self._slots if r is not None)

    def queue_depth(self):
        return len(self._queue)

    def queued(self):
        return list(self._queue)

    def fill_ratio(self):
        return self.n_active() / float(self.num_slots)

    def idle(self):
        return not self._queue and self.n_active() == 0
