"""PowerSGD low-rank gradient compression with error feedback.

Beyond-parity capability (the reference's wire compression stops at fp16
casts, horovod/torch/compression.py; this implements Vogels et al.,
"PowerSGD: Practical Low-Rank Gradient Compression for Distributed
Optimization", NeurIPS 2019 — the algorithm torch.distributed ships as its
``powerSGD_hook``): each gradient matrix ``M (n, m)`` is exchanged as two
rank-``r`` factors instead of ``n*m`` elements,

1. ``P = M @ Q`` with the previous step's ``Q`` (warm start),
2. allreduce-average ``P`` (r*n elements on the wire), orthonormalize,
3. ``Q = M^T @ P``, allreduce-average ``Q`` (r*m elements),
4. decompress ``M_hat = P @ Q^T``; the LOCAL residual ``M + e - M_hat``
   becomes the next step's error-feedback ``e`` (what low-rank dropped
   this step is re-injected next step, which is what makes the method
   converge like exact SGD).

TPU-native mapping: the whole procedure runs inside the jitted train step.
Every leaf's ``P`` (then every ``Q``) rides ONE fused flat-buffer
allreduce (:func:`horovod_tpu.optim.optimizer.fused_allreduce_tree`), so
the per-step collective count stays O(1) regardless of layer count —
PowerSGD composes with the fusion buffer exactly like the reference's
fp16 cast does. Matmuls are (n,m)@(m,r) MXU work. Orthonormalization is a
reduced QR on the (n,r) tall-skinny averaged ``P`` — identical on every
rank since the input is identical, so the factor state stays replicated
without extra communication.

Tensors that don't pay for compression — 1-D leaves (biases, norms),
tiny matrices where ``r*(n+m) * min_compression_rate > n*m`` — are
reduced uncompressed in the same fused buckets (torch's
``min_compression_rate`` rule).
"""

import jax
import jax.numpy as jnp
import optax
from jax import lax

from horovod_tpu.common.topology import HVD_AXIS
from horovod_tpu.ops.collective_ops import Average, ReduceOp, Sum


class PowerSGDCompressor:
    """Marker carried through ``DistributedOptimizer(compression=...)``.

    Unlike the cast compressors this one is STATEFUL (warm-start factors
    + error feedback), so it cannot run inside the stateless
    ``fused_allreduce_tree`` — the optimizer routes gradients through
    :func:`powersgd_gradients_transform` instead when it sees this
    marker. ``Compression.powersgd(rank)`` constructs it.
    """

    def __init__(self, rank=4, min_compression_rate=2.0, ef_dtype=None):
        if rank < 1:
            raise ValueError(f"PowerSGD rank must be >= 1, got {rank}")
        self.rank = int(rank)
        self.min_compression_rate = float(min_compression_rate)
        # None: error feedback in the leaf dtype. bf16 training can pass
        # jnp.float32 to keep the residual accumulation full-precision.
        self.ef_dtype = ef_dtype

    # Stateless-path guards: reaching compress() means a code path that
    # cannot provide state was handed this compressor.
    def compress(self, tensor):
        raise ValueError(
            "Compression.powersgd is stateful (warm-start factors + error "
            "feedback) and only works through DistributedOptimizer / "
            "powersgd_gradients_transform — the stateless eager/fused "
            "compression path cannot run it")

    def decompress(self, tensor, ctx):
        raise ValueError(
            "Compression.powersgd only works through DistributedOptimizer")


def _as_matrix(leaf):
    """(n, m) view: dim-0 rows vs everything else (torch powerSGD_hook's
    matrixization rule)."""
    return leaf.reshape(leaf.shape[0], -1)


def _use_powersgd(shape, rank, min_rate):
    if len(shape) < 2:
        return False
    n = shape[0]
    m = 1
    for s in shape[1:]:
        m *= s
    r = min(rank, n, m)
    return r * (n + m) * min_rate <= n * m


def _init_q(shape, rank, i, dtype):
    """Deterministic per-leaf factor init — identical on every rank (the
    factors must stay replicated; any fixed seed works, rank-dependent
    seeds would break the algorithm)."""
    n = shape[0]
    m = 1
    for s in shape[1:]:
        m *= s
    r = min(rank, n, m)
    q = jax.random.normal(jax.random.PRNGKey(17 + i), (m, r), jnp.float32)
    return q.astype(dtype)


def powersgd_gradients_transform(rank=4, op=Average, axis_name=HVD_AXIS,
                                 process_set=None, min_compression_rate=2.0,
                                 prescale_factor=1.0, postscale_factor=1.0,
                                 ef_dtype=None):
    """Optax transform: PowerSGD-compressed cross-replica gradient
    reduction (drop-in for ``allreduce_gradients_transform``).

    Only ``Average`` and ``Sum`` are defined for low-rank factors
    (matching the int8 route's contract); ``axis_name=None`` degrades to
    identity like the plain transform.
    """
    from horovod_tpu.ops.compression import Compression
    from horovod_tpu.optim.optimizer import fused_allreduce_tree

    op = ReduceOp(op)
    if op not in (Sum, Average):
        raise ValueError(
            f"PowerSGD supports Sum/Average only, got {op!r} (Min/Max/"
            f"Product/Adasum have no low-rank-factor semantics)")

    def init_fn(params):
        leaves = jax.tree_util.tree_leaves(params)
        qs = []
        errs = []
        for i, p in enumerate(leaves):
            e_dt = ef_dtype or p.dtype
            if _use_powersgd(p.shape, rank, min_compression_rate):
                qs.append(_init_q(p.shape, rank, i, jnp.float32))
                errs.append(jnp.zeros(p.shape, e_dt))
            else:
                qs.append(jnp.zeros((0,), jnp.float32))
                errs.append(jnp.zeros((0,), e_dt))
        return {"q": tuple(qs), "err": tuple(errs)}

    def update_fn(updates, state, params=None):
        del params
        if axis_name is None:
            return updates, state
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        compressed_idx = [
            i for i, l in enumerate(leaves)
            if _use_powersgd(l.shape, rank, min_compression_rate)]
        plain_idx = [i for i in range(len(leaves))
                     if i not in set(compressed_idx)]

        # --- uncompressed leaves: ordinary fused allreduce -------------
        plain_out = {}
        if plain_idx:
            reduced = fused_allreduce_tree(
                [leaves[i] for i in plain_idx], op=op, axis_name=axis_name,
                process_set=process_set, compression=Compression.none,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor)
            plain_out = dict(zip(plain_idx, reduced))

        new_qs = list(state["q"])
        new_errs = list(state["err"])
        out = [None] * len(leaves)
        if compressed_idx:
            mats = []
            for i in compressed_idx:
                m = _as_matrix(leaves[i]).astype(jnp.float32)
                if prescale_factor != 1.0:
                    m = m * prescale_factor
                # Error feedback: re-inject what low-rank dropped last
                # step BEFORE projecting (Vogels et al. alg. 2, line 2).
                m = m + _as_matrix(state["err"][i]).astype(jnp.float32)
                mats.append(m)
            # Phase 1: P = M @ Q, ONE fused allreduce over every P.
            ps = [m @ state["q"][j] for m, j
                  in zip(mats, compressed_idx)]
            ps = fused_allreduce_tree(ps, op=Average, axis_name=axis_name,
                                      process_set=process_set)
            # Orthonormalize the averaged P's (reduced QR on identical
            # inputs -> identical factors on every rank).
            ps = [jnp.linalg.qr(p)[0] for p in ps]
            # Phase 2: Q = M^T @ P, ONE fused allreduce over every Q.
            qs = [m.T @ p for m, p in zip(mats, ps)]
            qs = fused_allreduce_tree(qs, op=Average, axis_name=axis_name,
                                      process_set=process_set)
            # Static participant count for the Sum rescale: the factor
            # exchange averaged over the process SET (in_jit.allreduce
            # scopes to its axis_index_groups), so the scale must be the
            # set's size, not the world's.
            if op == Sum:
                n_participants = process_set.size() \
                    if process_set is not None and process_set.ranks \
                    is not None else lax.axis_size(axis_name)
            for m, p, q, i in zip(mats, ps, qs, compressed_idx):
                m_hat = p @ q.T
                # The residual of THIS rank's (error-fed) gradient
                # against the shared approximation becomes next step's
                # error feedback.
                err = (m - m_hat).astype(state["err"][i].dtype)
                new_errs[i] = err.reshape(leaves[i].shape)
                new_qs[i] = q
                if op == Sum:
                    # Factors were averaged (the numerically stable
                    # exchange); Sum semantics scale the decompressed
                    # mean back up.
                    m_hat = m_hat * n_participants
                if postscale_factor != 1.0:
                    m_hat = m_hat * postscale_factor
                out[i] = m_hat.reshape(leaves[i].shape).astype(
                    leaves[i].dtype)
        for i in plain_idx:
            out[i] = plain_out[i]
        new_state = {"q": tuple(new_qs), "err": tuple(new_errs)}
        # Normalize the state's mesh-varying types: err is device-varying
        # (per-rank residual) while the psum'd q comes back axis-invariant
        # — a scan/cond carrying this state needs stable types across
        # iterations (same fix as _local_aggregation's _mark_varying).
        from horovod_tpu.ops import in_jit
        new_state = in_jit.mark_varying(new_state, axis_name)
        return jax.tree_util.tree_unflatten(treedef, out), new_state

    return optax.GradientTransformation(init_fn, update_fn)


def powersgd_wire_numbers(shapes, rank, min_compression_rate=2.0):
    """Diagnostic: (compressed_bytes, uncompressed_bytes) per step for a
    list of fp32 leaf shapes — what the factor exchange moves vs a plain
    allreduce. Matrix leaves move r*(n+m) elements; exempt leaves move
    their full size either way."""
    wire = 0
    full = 0
    for shape in shapes:
        n = shape[0] if shape else 1
        m = 1
        for s in shape[1:]:
            m *= s
        size = n * m
        full += size * 4
        if _use_powersgd(tuple(shape), rank, min_compression_rate):
            r = min(rank, n, m)
            wire += r * (n + m) * 4
        else:
            wire += size * 4
    return wire, full
