"""Memory-lean loss kernels for the LM training path.

The standard next-token loss materializes the full ``(B, L, V)`` fp32
logits tensor — at GPT-2 bench shapes (B=8, L=1024, V=50304) that is
~1.6 GB of HBM written and re-read per step, the single largest memory
term of LM training and a direct MFU tax. The reference has no model-level
code at all (SURVEY.md §5.7 — Horovod operates below the model level);
this is part of the TPU build's model capability, in the same spirit as
the flash-attention kernels: restructure the computation so the O(L·V)
intermediate never exists.

:func:`next_token_xent_chunked` scans the sequence in chunks: each chunk
runs the head projection + softmax cross-entropy on ``(B, chunk, V)``
and immediately reduces to scalars; ``jax.checkpoint`` on the scan body
recomputes the chunk's logits in the backward instead of stashing them.
Peak logits memory drops from O(L·V) to O(chunk·V) in BOTH passes at the
cost of one extra head matmul per chunk in the backward.
"""

import jax
import jax.numpy as jnp
import optax
from jax import lax


def next_token_xent_chunked(head_fn, hidden, labels, chunk=128):
    """Mean softmax cross-entropy of ``head_fn(hidden)`` against
    ``labels`` without materializing the full logits tensor.

    - ``head_fn``: maps hidden states ``(B, c, H) -> (B, c, V)`` logits —
      e.g. ``functools.partial(GPTHead(cfg).apply,
      {"params": params["head"]})`` (the zoo's heads are separate modules
      bound under ``params["head"]``, so this composes with
      ``model.apply(..., features_only=True)``).
    - ``hidden``: ``(B, L, H)`` pre-head states, ``L`` divisible by
      ``chunk``.
    - ``labels``: ``(B, L)`` int targets aligned with positions;
      ``< 0`` (e.g. -100, :func:`parallel.next_token_labels`' pad)
      excludes a position from the mean.

    Returns the fp32 scalar mean over valid positions — identical (up to
    reduction order) to computing full logits and averaging, verified by
    tests down to gradients.
    """
    B, L, H = hidden.shape
    if L % chunk:
        raise ValueError(f"sequence length {L} not divisible by "
                         f"chunk={chunk}")
    n = L // chunk
    hidden_c = jnp.moveaxis(hidden.reshape(B, n, chunk, H), 1, 0)
    labels_c = jnp.moveaxis(labels.reshape(B, n, chunk), 1, 0)

    @jax.checkpoint
    def body(carry, xs):
        h, y = xs
        logits = head_fn(h).astype(jnp.float32)     # (B, chunk, V) — only
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.maximum(y, 0))
        valid = (y >= 0).astype(jnp.float32)
        tot, cnt = carry
        return (tot + jnp.sum(ce * valid), cnt + jnp.sum(valid)), None

    (tot, cnt), _ = lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (hidden_c, labels_c))
    return tot / jnp.maximum(cnt, 1.0)
