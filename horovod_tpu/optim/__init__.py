from horovod_tpu.optim.losses import next_token_xent_chunked  # noqa: F401
from horovod_tpu.optim.optimizer import (  # noqa: F401
    DistributedOptimizer, allreduce_gradients_transform, fused_allreduce_tree,
    distributed_value_and_grad, broadcast_parameters, broadcast_object_tree,
)
from horovod_tpu.optim.powersgd import (  # noqa: F401
    PowerSGDCompressor, powersgd_gradients_transform, powersgd_wire_numbers,
)
