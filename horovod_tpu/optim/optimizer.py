"""DistributedOptimizer and gradient-reduction transforms.

Reference surface being matched (horovod/torch/optimizer.py:132-344
``DistributedOptimizer`` + horovod/tensorflow/__init__.py:822
``DistributedOptimizer`` / :957 ``_DistributedGradientTape`` and the
local-gradient-aggregation helpers horovod/tensorflow/gradient_aggregation.py):
wrap a local optimizer so gradients are averaged across workers before the
update, with optional fp16 wire compression and ``backward_passes_per_step``
local aggregation.

TPU-native design: the wrapper is an ``optax.GradientTransformation`` meant to
run *inside* the jitted, shard_mapped train step. There are no per-parameter
hooks or async handles — XLA sees every gradient at once, so we implement the
fusion buffer (reference: fusion_buffer_manager.h) ahead-of-time:
:func:`fused_allreduce_tree` groups all leaves by dtype, concatenates them
into flat buffers capped at ``HOROVOD_FUSION_THRESHOLD`` bytes, and reduces
each bucket with a single ICI ``psum`` — collectives per step scale with
total gradient bytes over the threshold (a handful for typical models), not
with parameter count, and XLA is free to overlap them with the backward
pass. ``backward_passes_per_step`` maps onto
``optax.MultiSteps`` (local accumulation; the allreduce runs only on the
boundary step, exactly the reference's aggregation semantics).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from horovod_tpu.common.topology import HVD_AXIS
from horovod_tpu.ops import in_jit
from horovod_tpu.ops.collective_ops import Adasum, Average, ReduceOp, Sum
from horovod_tpu.ops.compression import Compression


def fused_allreduce_tree(tree, op=Average, axis_name=HVD_AXIS,
                         process_set=None, compression=Compression.none,
                         prescale_factor=1.0, postscale_factor=1.0):
    """Allreduce every leaf of a pytree with per-dtype flat-buffer fusion.

    The in-jit analog of Horovod's tensor fusion: instead of one collective
    per parameter (reference enqueues per-tensor and fuses in the background
    cycle), leaves are packed into flat buckets of up to
    ``HOROVOD_FUSION_THRESHOLD`` bytes per wire dtype — so the collective
    count is ``ceil(group_bytes / threshold)`` per dtype group (one for
    models under the threshold; e.g. BERT-Large's 1.4 GB fp32 gradients at
    the default 64 MB threshold reduce in ~22 buckets).
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    from horovod_tpu.common import basics
    from horovod_tpu.common.config import Config
    from horovod_tpu.common.exceptions import NotInitializedError
    try:
        threshold = basics.config().fusion_threshold
    except NotInitializedError:
        threshold = Config().fusion_threshold
    op = ReduceOp(op)
    int8_route = (compression is Compression.int8 and process_set is None
                  and op in (Sum, Average))
    if compression is Compression.int8:
        # Quantization happens inside the bucket exchange below (or not at
        # all when the combination can't express it); compress() is the
        # EAGER paths' routing hook and must not arm a one-shot wire
        # request from inside a jit trace.
        compressed = [(jnp.asarray(l), None) for l in leaves]
    else:
        compressed = [compression.compress(jnp.asarray(l)) for l in leaves]
    groups = {}
    for i, (c, _) in enumerate(compressed):
        groups.setdefault(jnp.dtype(c.dtype), []).append(i)
    out = [None] * len(leaves)
    for dt, idxs in groups.items():
        if op == Average and not jnp.issubdtype(dt, jnp.floating):
            raise ValueError(
                "Average is not supported for integer tensors; use hvd.Sum "
                "(matches the eager allreduce API and reference "
                "torch/mpi_ops.py checks).")
        if op == Adasum or not jnp.issubdtype(dt, jnp.number) \
                or jnp.issubdtype(dt, jnp.integer):
            # Adasum normalizes per-tensor, and non-float leaves shouldn't be
            # folded into a float buffer: reduce these leaves individually.
            for i in idxs:
                out[i] = in_jit.allreduce(
                    compressed[i][0], op=op, axis_name=axis_name,
                    process_set=process_set, prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor)
            continue
        # Bucket the group at the fusion threshold (reference:
        # HOROVOD_FUSION_THRESHOLD, fusion_buffer_manager.h:40): one giant
        # flat buffer both doubles peak gradient memory and — with an
        # awkward element count (e.g. BERT-Large's 367,480,636 = 4 × a
        # large prime) — pushes XLA into pathological 2-D re-tilings of
        # the 1-D vector that OOM on padding.
        buckets, cur, cur_bytes = [], [], 0
        for i in idxs:
            nbytes = compressed[i][0].size * dt.itemsize
            if cur and cur_bytes + nbytes > threshold:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nbytes
        buckets.append(cur)
        for bucket in buckets:
            flats = [compressed[i][0].reshape(-1) for i in bucket]
            buf = jnp.concatenate(flats) if len(flats) > 1 else flats[0]
            total = buf.size
            # Tile-friendly length (the FUSION_BUFFER_ATOMIC_UNIT move,
            # common.h:156): without it XLA may factor an odd-length
            # vector into (huge, 2) and pad the lane dim 64x.
            pad = (-total) % 1024
            if pad:
                buf = jnp.pad(buf, (0, pad))
            if int8_route and jnp.issubdtype(dt, jnp.floating):
                # int8 can't ride a plain psum (overflow + per-rank
                # scales): route the bucket through the two-phase
                # quantized exchange (shared wrapper so the eager fusion
                # path can never diverge on scaling order).
                from horovod_tpu.parallel.strategies import \
                    scaled_allreduce_int8
                buf = scaled_allreduce_int8(
                    buf, axis_name=axis_name, average=(op == Average),
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor)
            else:
                buf = in_jit.allreduce(buf, op=op, axis_name=axis_name,
                                       process_set=process_set,
                                       prescale_factor=prescale_factor,
                                       postscale_factor=postscale_factor)
            off = 0
            for i in bucket:
                sz = compressed[i][0].size
                out[i] = jax.lax.slice_in_dim(buf, off, off + sz).reshape(
                    compressed[i][0].shape)
                off += sz
    out = [compression.decompress(o, ctx)
           for o, (_, ctx) in zip(out, compressed)]
    return jax.tree_util.tree_unflatten(treedef, out)


def allreduce_gradients_transform(op=Average, axis_name=HVD_AXIS,
                                  process_set=None,
                                  compression=Compression.none,
                                  prescale_factor=1.0, postscale_factor=1.0):
    """An optax transform that allreduces the incoming gradients."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        if axis_name is None:
            return updates, state
        return fused_allreduce_tree(
            updates, op=op, axis_name=axis_name, process_set=process_set,
            compression=compression, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor), state

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(optimizer, op=Average, axis_name=HVD_AXIS,
                         process_set=None, compression=Compression.none,
                         backward_passes_per_step=1,
                         average_aggregated_gradients=True,
                         prescale_factor=1.0, postscale_factor=1.0):
    """Wrap an optax optimizer with cross-replica gradient reduction.

    Use inside a shard_mapped/pjitted train step whose data axis is
    ``axis_name``; pass ``axis_name=None`` for single-replica runs (the
    reduction becomes a no-op, like running the reference without hvd ranks).

    reference: torch/optimizer.py:517 DistributedOptimizer(...) /
    tensorflow/__init__.py:822; backward_passes_per_step aggregation
    reference: gradient_aggregation.py.
    """
    if backward_passes_per_step < 1:
        raise ValueError(
            f"backward_passes_per_step must be >= 1, got "
            f"{backward_passes_per_step}")
    from horovod_tpu.optim.powersgd import (PowerSGDCompressor,
                                            powersgd_gradients_transform)
    if isinstance(compression, PowerSGDCompressor):
        # Stateful low-rank compression: its own transform carries the
        # warm-start factors + error feedback (powersgd.py).
        reduce_tx = powersgd_gradients_transform(
            rank=compression.rank, op=op, axis_name=axis_name,
            process_set=process_set,
            min_compression_rate=compression.min_compression_rate,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            ef_dtype=compression.ef_dtype)
    else:
        reduce_tx = allreduce_gradients_transform(
            op=op, axis_name=axis_name, process_set=process_set,
            compression=compression, prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)
    tx = optax.chain(reduce_tx, optimizer)
    if backward_passes_per_step > 1:
        tx = _local_aggregation(tx, backward_passes_per_step,
                                average_aggregated_gradients, axis_name)
    return tx


class _AggState(NamedTuple):
    step: jnp.ndarray
    acc: any
    inner: any


def _local_aggregation(inner, k, average, axis_name):
    """Accumulate gradients locally for ``k`` backward passes; run the inner
    transform (which contains the allreduce) only on the boundary step — so
    cross-replica communication happens once per ``k`` passes
    (reference: gradient_aggregation.py LocalGradientAggregationHelper).

    Hand-rolled rather than optax.MultiSteps because the skip/do branches must
    carry identical device-varying types inside shard_map (MultiSteps' cond
    branches trip the vma check); we harmonize with lax.pcast/pvary.
    """

    def _mark_varying(tree):
        if axis_name is None:
            return tree
        return in_jit.mark_varying(tree, axis_name)

    def init_fn(params):
        return _AggState(step=jnp.zeros((), jnp.int32),
                         acc=jax.tree_util.tree_map(jnp.zeros_like, params),
                         inner=inner.init(params))

    def update_fn(updates, state, params=None):
        acc = jax.tree_util.tree_map(lambda a, g: a + g, state.acc, updates)
        boundary = (state.step + 1) % k == 0

        def do(acc, inner_state, params):
            g = jax.tree_util.tree_map(
                lambda a: a / k, acc) if average else acc
            u, s = inner.update(g, inner_state, params)
            zero = jax.tree_util.tree_map(jnp.zeros_like, acc)
            return _mark_varying((u, s, zero))

        def skip(acc, inner_state, params):
            u = jax.tree_util.tree_map(jnp.zeros_like, updates)
            return _mark_varying((u, inner_state, acc))

        u, inner_state, acc = lax.cond(boundary, do, skip, acc, state.inner,
                                       params)
        return u, _AggState(step=state.step + 1, acc=acc, inner=inner_state)

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)


def distributed_value_and_grad(fun, op=Average, axis_name=HVD_AXIS,
                               process_set=None, compression=Compression.none,
                               **grad_kwargs):
    """``jax.value_and_grad`` + allreduce — the DistributedGradientTape analog
    (reference: tensorflow/__init__.py:957 _DistributedGradientTape)."""
    vg = jax.value_and_grad(fun, **grad_kwargs)

    def wrapped(*args, **kwargs):
        value, grads = vg(*args, **kwargs)
        if axis_name is not None:
            grads = fused_allreduce_tree(grads, op=op, axis_name=axis_name,
                                         process_set=process_set,
                                         compression=compression)
        return value, grads

    return wrapped


def broadcast_parameters(params, root_rank=0, process_set=None,
                         stacked=False):
    """Eager broadcast of a parameter pytree from ``root_rank`` so all ranks
    start identical (reference: torch/__init__.py broadcast_parameters /
    _keras/callbacks.py BroadcastGlobalVariablesCallback).

    With ``stacked=False`` (default) every leaf is a replicated array and all
    ranks receive the root's value. With ``stacked=True`` every leaf must be
    rank-major stacked (leading axis == set size) and broadcasts slice-wise.
    The mode is explicit because a replicated leaf whose first dim happens to
    equal the world size is indistinguishable from a stacked one.
    """
    from horovod_tpu.common import basics
    from horovod_tpu.common.process_sets import global_process_set
    from horovod_tpu.ops import collective_ops as C

    ps = process_set if process_set is not None else global_process_set
    n = ps.size() if ps.ranks is not None else basics.size()
    # Eager stacked contract: single-process supplies all n rows, a
    # multi-process member only the rows of its local chips.
    n_rows = C._expected_rows(ps.mesh, n)

    def bcast_leaf(leaf):
        leaf = jnp.asarray(leaf)
        if stacked:
            return C.broadcast(leaf, root_rank, process_set=process_set)
        tiled = jnp.broadcast_to(leaf[None], (n_rows,) + leaf.shape)
        out = C.broadcast(tiled, root_rank, process_set=process_set)
        return out[0]

    return jax.tree_util.tree_map(bcast_leaf, params)


def broadcast_object_tree(obj, root_rank=0, process_set=None):
    """Broadcast an arbitrary python object (optimizer hyperparams, epoch
    counters, ...) — reference: broadcast_object (torch/functions.py)."""
    from horovod_tpu.ops.collective_ops import broadcast_object
    return broadcast_object(obj, root_rank=root_rank, process_set=process_set)
