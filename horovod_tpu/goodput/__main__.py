"""``python -m horovod_tpu.goodput`` == ``... goodput.report``."""

from horovod_tpu.goodput.report import main

raise SystemExit(main())
