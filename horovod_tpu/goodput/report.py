"""``python -m horovod_tpu.goodput.report`` — render and regress runs.

Reads the journals :mod:`horovod_tpu.goodput.history` leaves behind and
answers, from the launch box with nothing else running: *was that job
actually training, and is this run worse than the ones before it?*

- default / ``--run ID``: render one run — wall, goodput ratio, the full
  badput decomposition, conservation check, and the victim rank when the
  cluster view carries one (max ``straggler_wait`` / watchdog naming).
- ``--diff OLD NEW``: compare two runs; the regression gate combines an
  absolute goodput-ratio drop with a cross-run robust-z (the same
  median/MAD score the step watchdog names stragglers with) of the new
  run against ALL journaled runs. Exit code 1 when a regression is
  flagged — wire it straight into CI.
"""

import argparse
import json
import os
import sys

from horovod_tpu.goodput.ledger import BADPUT_CATEGORIES, PRODUCTIVE
from horovod_tpu.goodput.history import read_runs
from horovod_tpu.profile.ledger import robust_z

# Cross-run robust-z beyond which a per-category badput share (or a
# goodput-ratio deficit) counts as a regression, and the absolute
# goodput-ratio drop that flags regardless of history depth (robust-z
# needs >= 4 runs to mean anything; two journaled runs still gate).
Z_THRESHOLD = 3.0
DROP_THRESHOLD = 0.05


def _goodput_of(summary):
    rec = summary.get("goodput") or {}
    return rec.get("summary") or {}


def _category_shares(snap):
    """Badput categories as fractions of wall (comparable across runs of
    different lengths)."""
    wall = float(snap.get("wall_s") or 0.0)
    cats = snap.get("categories") or {}
    if wall <= 0:
        return {}
    return {k: float(cats.get(k, 0.0)) / wall for k in BADPUT_CATEGORIES}


def find_victim(summary):
    """-> (rank, reason) or None: the rank the decomposition blames.
    The step watchdog's cross-rank straggler naming wins when present —
    under a synchronous collective EVERY rank books self-relative
    ``straggler_wait``, but the comparative verdict (robust-z on the
    dispatch-path attribution across ranks) names only the one stalling
    the others. Falls back to the max per-rank ``straggler_wait`` and
    then ``rendezvous_recovery`` from the journaled cluster view."""
    view = summary.get("cluster") or {}
    ranks = (view.get("goodput") or {}).get("ranks") or {}
    snap = _goodput_of(summary)
    named = snap.get("straggler_named")
    if named is not None:
        wait = float((ranks.get(str(named)) or {})
                     .get("straggler_wait_s") or 0.0)
        detail = f", straggler_wait {wait:.2f}s" if wait else ""
        return named, f"watchdog straggler naming{detail}"
    best = None
    for cat in ("straggler_wait_s", "rendezvous_recovery_s"):
        for rank, d in ranks.items():
            v = float((d or {}).get(cat) or 0.0)
            if v > 0.0 and (best is None or v > best[1]):
                best = (rank, v, cat[:-2])
        if best is not None:
            break
    if best is None:
        return None
    rank, seconds, why = best
    return rank, f"{why} {seconds:.2f}s"


def render_run(summary):
    snap = _goodput_of(summary)
    run = summary.get("run", "?")
    start = summary.get("start") or {}
    lines = []
    ended = "ended cleanly" if summary.get("ended") else \
        "NO run_end marker (killed run)"
    lines.append(f"run {run}  fingerprint={start.get('fingerprint', '?')}"
                 f"  world={start.get('world', '?')}  [{ended}]")
    if not snap:
        lines.append("  no goodput records in journal")
        return lines
    wall = float(snap.get("wall_s") or 0.0)
    ratio = float(snap.get("goodput_ratio") or 0.0)
    err = float(snap.get("conservation_error") or 0.0)
    lines.append(f"  wall {wall:.1f}s  goodput {ratio:.1%}  "
                 f"steps {snap.get('steps', 0)}  "
                 f"resets {snap.get('resets', 0)}  "
                 f"conservation_error {err:.4%}")
    cats = snap.get("categories") or {}
    for cat in (PRODUCTIVE,) + BADPUT_CATEGORIES:
        v = float(cats.get(cat, 0.0))
        if cat != PRODUCTIVE and v <= 0.0:
            continue
        pct = v / wall if wall > 0 else 0.0
        lines.append(f"    {cat:<20s} {v:10.2f}s  {pct:6.1%}")
    victim = find_victim(summary)
    if victim is not None:
        lines.append(f"  victim: rank {victim[0]} ({victim[1]})")
    if summary.get("bench"):
        lines.append(f"  bench records: {len(summary['bench'])}")
    return lines


def diff_runs(old, new, runs, z_threshold=Z_THRESHOLD,
              drop_threshold=DROP_THRESHOLD):
    """-> (lines, regressed). ``runs`` is the full history for the
    robust-z baseline (the two runs under comparison included)."""
    lines = []
    regressed = False
    old_snap, new_snap = _goodput_of(old), _goodput_of(new)
    if not old_snap or not new_snap:
        return ["diff: missing goodput records"], False
    o_ratio = float(old_snap.get("goodput_ratio") or 0.0)
    n_ratio = float(new_snap.get("goodput_ratio") or 0.0)
    hist_ratios = [float(_goodput_of(r).get("goodput_ratio") or 0.0)
                   for r in runs.values() if _goodput_of(r)]
    z, med = robust_z(n_ratio, hist_ratios)
    lines.append(f"goodput_ratio  {o_ratio:.4f} -> {n_ratio:.4f}  "
                 f"(delta {n_ratio - o_ratio:+.4f}, z {z:+.2f} "
                 f"vs history median {med:.4f}, n={len(hist_ratios)})")
    if n_ratio < o_ratio - drop_threshold or \
            (len(hist_ratios) >= 4 and z <= -z_threshold
             and n_ratio < med):
        lines[-1] += "  REGRESSION"
        regressed = True
    o_sh, n_sh = _category_shares(old_snap), _category_shares(new_snap)
    hist_sh = [_category_shares(_goodput_of(r)) for r in runs.values()
               if _goodput_of(r)]
    for cat in BADPUT_CATEGORIES:
        o_v, n_v = o_sh.get(cat, 0.0), n_sh.get(cat, 0.0)
        if o_v == 0.0 and n_v == 0.0:
            continue
        zs = [s.get(cat, 0.0) for s in hist_sh]
        z, med = robust_z(n_v, zs)
        line = (f"badput/{cat:<20s} {o_v:6.2%} -> {n_v:6.2%}  "
                f"(z {z:+.2f})")
        if n_v > o_v + drop_threshold or \
                (len(zs) >= 4 and z >= z_threshold and n_v > med):
            line += "  REGRESSION"
            regressed = True
        lines.append(line)
    return lines, regressed


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.goodput.report",
        description="Render goodput run history and flag regressions.")
    p.add_argument("--dir", default=os.environ.get(
        "HOROVOD_RUN_HISTORY_DIR", "run_history"),
        help="run-history directory (default: $HOROVOD_RUN_HISTORY_DIR)")
    p.add_argument("--run", default=None,
                   help="render this run id (default: latest)")
    p.add_argument("--diff", nargs=2, metavar=("OLD", "NEW"), default=None,
                   help="compare two run ids; exit 1 on regression")
    p.add_argument("--list", action="store_true", help="list runs")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    p.add_argument("--z-threshold", type=float, default=Z_THRESHOLD)
    p.add_argument("--drop-threshold", type=float, default=DROP_THRESHOLD)
    args = p.parse_args(argv)

    runs = read_runs(args.dir)
    if not runs:
        print(f"no run journals under {args.dir}", file=sys.stderr)
        return 2
    order = sorted(runs, key=lambda r: runs[r].get("t0") or 0)

    if args.list:
        for rid in order:
            s = runs[rid]
            snap = _goodput_of(s)
            ratio = snap.get("goodput_ratio")
            ratio = f"{float(ratio):.1%}" if ratio is not None else "?"
            mark = "" if s.get("ended") else "  [killed]"
            print(f"{rid}  goodput={ratio}  records={s['records']}{mark}")
        return 0

    if args.diff:
        old_id, new_id = args.diff
        if old_id not in runs or new_id not in runs:
            missing = [r for r in (old_id, new_id) if r not in runs]
            print(f"unknown run id(s): {', '.join(missing)}",
                  file=sys.stderr)
            return 2
        lines, regressed = diff_runs(
            runs[old_id], runs[new_id], runs,
            z_threshold=args.z_threshold,
            drop_threshold=args.drop_threshold)
        if args.json:
            print(json.dumps({"regressed": regressed, "lines": lines}))
        else:
            print(f"diff {old_id} -> {new_id}")
            for line in lines:
                print(f"  {line}")
        return 1 if regressed else 0

    rid = args.run or order[-1]
    if rid not in runs:
        print(f"unknown run id: {rid}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(runs[rid], default=str))
        return 0
    for line in render_run(runs[rid]):
        print(line)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
