"""hvdgoodput: job-level goodput/badput accounting + durable run history.

Every other observability plane in this tree (metrics, flight, step
profiler, telemetry, tracing/SLO) answers *within-run* questions and
evaporates with the process. This package answers the two questions that
survive the run:

1. **What fraction of this job's wall-clock was productive training, and
   where did the rest go?** — :mod:`horovod_tpu.goodput.ledger`, a
   per-rank state machine that decomposes total wall time into
   ``productive_compute`` plus seven named badput categories, with the
   repo's signature conservation guarantee: the categories sum to the
   measured wall within 1% (asserted, like the byte-accounting
   cross-checks in the dispatch tier).

2. **How does this run compare to every run before it?** —
   :mod:`horovod_tpu.goodput.history`, an append-only per-run JSONL
   journal flushed line-by-line (the ``HVD_BENCH_PROGRESS_FILE``
   discipline) so a SIGKILLed run still leaves evidence, and
   :mod:`horovod_tpu.goodput.report` (``python -m
   horovod_tpu.goodput.report``) to render one run and diff/regress
   across runs with the same robust-z the step profiler uses for
   straggler naming.

Knobs: ``HOROVOD_GOODPUT`` (default on), ``HOROVOD_GOODPUT_DIR``
(per-rank shutdown summaries), ``HOROVOD_RUN_HISTORY_DIR`` (the durable
journal; empty = off). Like every observability plane here, goodput must
never fail the job: all module-level entry points are armed-gated and
fail-soft.
"""

from horovod_tpu.goodput.ledger import (BADPUT_CATEGORIES, CATEGORIES,
                                        PRODUCTIVE, GoodputLedger,
                                        ServingGoodput, armed, configure,
                                        get_ledger, note_commit,
                                        note_recovery, note_reset,
                                        note_straggler, note_unwedged,
                                        note_wedge, on_step_boundary,
                                        reset, serving_snapshot, set_trial,
                                        shutdown, snapshot, wedge_from_rows)
from horovod_tpu.goodput.history import (RunJournal, config_fingerprint,
                                         get_journal, journal_append,
                                         journal_configure, read_journal,
                                         read_runs)

__all__ = [
    "BADPUT_CATEGORIES", "CATEGORIES", "PRODUCTIVE", "GoodputLedger",
    "ServingGoodput", "RunJournal", "armed", "configure",
    "config_fingerprint", "get_journal", "get_ledger", "journal_append",
    "journal_configure", "note_commit", "note_recovery", "note_reset",
    "note_straggler", "note_unwedged", "note_wedge", "on_step_boundary",
    "read_journal", "read_runs", "reset", "serving_snapshot", "set_trial",
    "shutdown", "snapshot", "wedge_from_rows",
]
