"""The per-rank goodput ledger: wall-clock conservation state machine.

Decomposes total job wall time into ``productive_compute`` plus seven
named badput categories, every second booked exactly once:

- ``init_compile``        start of accounting -> first step boundary
                          (bootstrap, tracing, XLA compilation).
- ``rendezvous_recovery`` elastic reset -> first post-restore step
                          boundary, plus the aborted open window that the
                          failure destroyed (its work is lost — that is
                          what makes it badput, not productive time).
- ``checkpoint_commit``   seconds spent inside elastic ``State.commit`` /
                          checkpoint saves (reported by the commit site,
                          consumed from the window it occurred in).
- ``straggler_wait``      per-step excess of the comm-side attribution
                          (``host_dispatch + collective``) over its own
                          rolling median — the slow-peer tax the step
                          watchdog names ranks for. Floored at
                          ``STRAGGLER_FLOOR_S`` so scheduler jitter on a
                          healthy run does not accumulate into badput.
- ``cross_wait_comm``     the step profiler's ``cross_wait`` attribution:
                          exposed (non-overlapped) cross-slice DCN wait.
- ``autopilot_trial``     step time spent while an autopilot trial/probe
                          had the knobs off their resting point — booked
                          instead of productive_compute for those steps.
- ``wedge_idle``          time in a window the telemetry health model
                          called ``stalled`` (step clock stopped) that
                          never produced a step.

**Conservation guarantee**: ``productive_compute + sum(badput)`` equals
the measured wall (``now - start``) within 1% at every snapshot — by
construction, since every transition books exactly the gap since the
previous mark, and the live tail is attributed virtually at read time.
``snapshot()`` computes the conservation error; ``assert_conservation()``
raises on violation (integration bugs: double-booked gaps, mixed clocks).

The class is a fake clock seam end to end — every mutator takes
``now=None`` (tests drive it with explicit times, production passes
nothing and gets ``time.monotonic()``) — the same pattern as
:class:`horovod_tpu.telemetry.slo.SloEngine`. Module-level wrappers gate
on ``armed`` and never raise (observability must never fail the job).
"""

import threading
import time

from horovod_tpu.common.config import _env_bool, _env_float

PRODUCTIVE = "productive_compute"
BADPUT_CATEGORIES = ("init_compile", "rendezvous_recovery",
                     "checkpoint_commit", "straggler_wait",
                     "cross_wait_comm", "autopilot_trial", "wedge_idle")
CATEGORIES = (PRODUCTIVE,) + BADPUT_CATEGORIES

# Jitter floor for the straggler-wait rule: per-step comm excess below
# this is scheduler noise, not a straggler (the chaos-soak injected
# delays are 30-120ms, an order of magnitude above).
STRAGGLER_FLOOR_S = 0.005

# Rolling comm-baseline history for the straggler excess rule.
_COMM_HISTORY = 64

# Phase -> category a gap is booked to when no step record explains it.
_PHASE_CAT = {"init": "init_compile", "recovery": "rendezvous_recovery",
              "wedge": "wedge_idle", "train": PRODUCTIVE}


def _median(xs):
    s = sorted(xs)
    n = len(s)
    if not n:
        return 0.0
    m = n // 2
    return s[m] if n % 2 else 0.5 * (s[m - 1] + s[m])


class GoodputLedger:
    """Category state machine over one rank's wall clock."""

    def __init__(self, straggler_floor_s=STRAGGLER_FLOOR_S):
        self._lock = threading.Lock()
        self._floor = float(straggler_floor_s)
        self._t0 = None
        self._mark = None
        self._phase = "init"
        self._acc = dict.fromkeys(CATEGORIES, 0.0)
        self._comm_hist = []
        self._commit_pending = 0.0
        self._trial = False
        self._saw_explicit = False
        self._steps = 0
        self._resets = 0
        self._recoveries = []       # (cause, observed_seconds) cross-check
        self._straggler_named = None

    # --- lifecycle ------------------------------------------------------

    def start(self, now=None):
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._t0 is None:
                self._t0 = self._mark = now
                self._phase = "init"

    def started(self):
        return self._t0 is not None

    # --- transitions ----------------------------------------------------

    def _book(self, cat, dt):
        if dt > 0.0:
            self._acc[cat] += dt

    def on_step_boundary(self, rec=None, step=True, now=None):
        """One step-profiler boundary. ``rec`` is the closed window record
        (None when the marker only opened the first window); ``step`` is
        the caller's step argument — ``None`` auto marks are suppressed
        once an explicit step has been seen, mirroring the profile
        ledger's own rule so the two state machines agree on boundaries.
        """
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._t0 is None:
                return
            if rec is None and step is None and self._saw_explicit:
                return
            if step is not None and step is not True:
                self._saw_explicit = True
            gap = max(now - self._mark, 0.0)
            if rec is None:
                # Marker opened a window: the gap is whatever phase we
                # were in (init_compile, rendezvous_recovery, ...).
                self._book(_PHASE_CAT[self._phase], gap)
            else:
                self._book_window_locked(gap, rec)
                self._steps += 1
            self._mark = now
            self._phase = "train"

    def _book_window_locked(self, gap, rec):
        """Decompose one closed step window of measured duration ``gap``
        using the profiler's attribution. Badput parts are clamped so the
        window books exactly ``gap`` — conservation by construction."""
        att = rec.get("attribution") or {}
        cross = max(float(att.get("cross_wait", 0.0)), 0.0)
        comm = max(float(att.get("host_dispatch", 0.0)), 0.0) \
            + max(float(att.get("collective", 0.0)), 0.0)
        straggler = 0.0
        if len(self._comm_hist) >= 8:
            excess = comm - _median(self._comm_hist)
            if excess > self._floor:
                straggler = excess
        self._comm_hist.append(comm)
        if len(self._comm_hist) > _COMM_HISTORY:
            self._comm_hist.pop(0)
        commit = min(self._commit_pending, gap)
        self._commit_pending -= commit
        badput = cross + straggler + commit
        if badput > gap > 0.0:
            scale = gap / badput
            cross, straggler, commit = (cross * scale, straggler * scale,
                                        commit * scale)
            badput = gap
        self._book("cross_wait_comm", cross)
        self._book("straggler_wait", straggler)
        self._book("checkpoint_commit", commit)
        self._book("autopilot_trial" if self._trial else PRODUCTIVE,
                   gap - badput)

    def on_reset(self, now=None):
        """Elastic reset: the open window is lost work. Book the gap to
        the current phase's category — except a live training window,
        whose destroyed partial step is recovery badput, not productive
        time — then enter the recovery phase."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._t0 is None:
                return
            gap = max(now - self._mark, 0.0)
            cat = _PHASE_CAT[self._phase]
            self._book("rendezvous_recovery" if cat == PRODUCTIVE else cat,
                       gap)
            self._mark = now
            self._phase = "recovery"
            self._resets += 1
            self._comm_hist = []

    def note_recovery(self, cause, seconds):
        """Observed ``elastic_recovery_seconds`` sample — kept as a
        cross-check against the gap-booked ``rendezvous_recovery`` (the
        gap is authoritative; this records what the elastic wrapper saw).
        """
        with self._lock:
            self._recoveries.append((str(cause), float(seconds)))

    def note_commit(self, seconds):
        """Seconds spent in a checkpoint commit; consumed out of the
        window(s) it occurred in at the next boundary."""
        with self._lock:
            if seconds > 0.0:
                self._commit_pending += float(seconds)

    def note_wedge(self, now=None):
        """Telemetry stall verdict (step clock stopped) for this rank:
        the time since the last boundary stops counting as (future)
        productive. A step that still completes overrides this — a
        closed window is authoritative."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._t0 is None or self._phase != "train":
                return
            self._phase = "wedge"

    def note_unwedged(self, now=None):
        """Health recovered without an elastic reset: book the wedge gap
        and resume training attribution."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._t0 is None or self._phase != "wedge":
                return
            self._book("wedge_idle", max(now - self._mark, 0.0))
            self._mark = now
            self._phase = "train"

    def set_trial(self, active):
        """Autopilot trial window: step time while a probe has the knobs
        off their resting point books to ``autopilot_trial``."""
        with self._lock:
            self._trial = bool(active)

    def note_straggler(self, rank):
        """A watchdog straggler naming (evidence for the report CLI)."""
        with self._lock:
            self._straggler_named = rank

    # --- reads ----------------------------------------------------------

    def snapshot(self, now=None):
        """Point-in-time decomposition. The live tail (time since the
        last mark) is attributed virtually to the current phase so the
        categories always sum to the measured wall."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._t0 is None:
                return {"enabled": False}
            wall = max(now - self._t0, 0.0)
            acc = dict(self._acc)
            tail_cat = _PHASE_CAT[self._phase]
            if self._phase == "train" and self._trial:
                tail_cat = "autopilot_trial"
            acc[tail_cat] += max(now - self._mark, 0.0)
            steps = self._steps
            resets = self._resets
            recoveries = list(self._recoveries)
            named = self._straggler_named
            phase = self._phase
        accounted = sum(acc.values())
        err = abs(wall - accounted) / wall if wall > 0 else 0.0
        out = {
            "enabled": True,
            "wall_s": round(wall, 6),
            "phase": phase,
            "steps": steps,
            "resets": resets,
            "goodput_ratio": round(acc[PRODUCTIVE] / wall, 6)
            if wall > 0 else 1.0,
            "categories": {k: round(v, 6) for k, v in acc.items()},
            "badput_s": round(accounted - acc[PRODUCTIVE], 6),
            "conservation_error": round(err, 8),
        }
        if recoveries:
            out["recoveries_observed"] = [
                {"cause": c, "seconds": round(s, 6)} for c, s in recoveries]
        if named is not None:
            out["straggler_named"] = named
        return out

    def assert_conservation(self, now=None, tol=0.01):
        snap = self.snapshot(now)
        if not snap.get("enabled"):
            return snap
        err = snap["conservation_error"]
        if err > tol:
            raise AssertionError(
                f"goodput conservation violated: categories sum to "
                f"{sum(snap['categories'].values()):.6f}s vs wall "
                f"{snap['wall_s']:.6f}s (error {err:.4%} > {tol:.2%})")
        return snap


class ServingGoodput:
    """The serving-plane variant: goodput = in-SLO token-seconds.

    Each decode step contributes ``dt * tokens`` token-seconds (step wall
    weighted by tokens committed that step); the contribution counts as
    goodput when the step was taken with every declared SLO burn rate
    <= 1.0 (no SLO declared -> everything is in-SLO). Pure accumulator,
    fake-clock by construction (the caller supplies ``dt``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._token_s = 0.0
        self._in_slo_token_s = 0.0
        self._tokens = 0
        self._steps = 0

    def record_decode_step(self, dt_s, tokens, in_slo):
        if dt_s < 0 or tokens <= 0:
            return
        delta = float(dt_s) * int(tokens)
        with self._lock:
            self._token_s += delta
            if in_slo:
                self._in_slo_token_s += delta
            self._tokens += int(tokens)
            self._steps += 1

    def snapshot(self):
        with self._lock:
            total, good = self._token_s, self._in_slo_token_s
            tokens, steps = self._tokens, self._steps
        return {
            "token_seconds": round(total, 6),
            "in_slo_token_seconds": round(good, 6),
            "tokens": tokens,
            "steps": steps,
            "goodput_ratio": round(good / total, 6) if total > 0 else 1.0,
        }


# --- module singletons + armed-gated fail-soft wrappers -----------------
#
# Same shape as metrics/instruments and telemetry/slo: one bool the hot
# path reads, short critical sections, lazy imports for cross-module
# mirrors, and nothing here may raise into the training loop.

armed = _env_bool("HOROVOD_GOODPUT", True)
_ledger = GoodputLedger()
_serving = ServingGoodput()
_export_last = {}
_export_t = 0.0
_journal_t = 0.0

# Periodic cadences (seconds): metrics-counter export and the durable
# journal heartbeat. The journal flush is what makes a SIGKILLed run
# still leave a goodput summary behind.
_EXPORT_EVERY_S = 1.0
_JOURNAL_EVERY_S = _env_float("HOROVOD_GOODPUT_JOURNAL_S", 10.0)


def get_ledger():
    return _ledger


def reset():
    """Fresh module singletons (tests / forked soak workers)."""
    global _ledger, _serving, _export_last, _export_t, _journal_t, \
        _shutdown_done
    _ledger = GoodputLedger()
    _serving = ServingGoodput()
    _export_last = {}
    _export_t = 0.0
    _journal_t = 0.0
    _shutdown_done = False


def configure(config):
    """Arm the plane from a Config (called by ``basics.init``). Starts
    the wall clock — everything before the first step boundary books to
    ``init_compile``. Start-once: an elastic in-place re-init calls
    ``basics.init`` again, and the accumulated decomposition must
    survive it (the recovery it is accounting for IS the evidence)."""
    global armed
    armed = bool(config.goodput)
    if not armed or _ledger.started():
        return
    _ledger.start()
    # Finalize at true process exit only: basics.shutdown also runs on
    # every elastic in-place reset, where the run (and its journal) must
    # keep going.
    import atexit
    atexit.register(shutdown)
    try:
        from horovod_tpu.flight import recorder as _flight
        if _flight.armed:
            _flight.record_event("goodput", what="armed")
    except Exception:  # noqa: BLE001
        pass


def on_step_boundary(rec, step=True):
    """Fed from the profile ledger's step listener."""
    if not armed:
        return
    try:
        now = time.monotonic()
        _ledger.on_step_boundary(rec, step=step, now=now)
        _export_metrics(now)
        _journal_heartbeat(now)
    except Exception:  # noqa: BLE001 — observability must never fail the job
        pass


def note_reset():
    if not armed:
        return
    try:
        _ledger.on_reset()
        from horovod_tpu.flight import recorder as _flight
        if _flight.armed:
            _flight.record_event("goodput", what="reset")
    except Exception:  # noqa: BLE001
        pass


def note_recovery(cause, seconds):
    if not armed:
        return
    try:
        _ledger.note_recovery(cause, seconds)
    except Exception:  # noqa: BLE001
        pass


def note_commit(seconds):
    if not armed:
        return
    try:
        _ledger.note_commit(seconds)
    except Exception:  # noqa: BLE001
        pass


def note_wedge():
    if not armed:
        return
    try:
        _ledger.note_wedge()
    except Exception:  # noqa: BLE001
        pass


def note_unwedged():
    if not armed:
        return
    try:
        _ledger.note_unwedged()
    except Exception:  # noqa: BLE001
        pass


def set_trial(active):
    if not armed:
        return
    try:
        _ledger.set_trial(active)
    except Exception:  # noqa: BLE001
        pass


def note_straggler(rank):
    if not armed:
        return
    try:
        _ledger.note_straggler(rank)
    except Exception:  # noqa: BLE001
        pass


def record_serving_step(dt_s, tokens, in_slo):
    if not armed:
        return
    try:
        _serving.record_decode_step(dt_s, tokens, in_slo)
    except Exception:  # noqa: BLE001
        pass


def snapshot():
    """Current decomposition, or ``{"enabled": False}`` when off."""
    if not armed:
        return {"enabled": False}
    try:
        return _ledger.snapshot()
    except Exception:  # noqa: BLE001
        return {"enabled": False}


def serving_snapshot():
    if not armed:
        return {}
    try:
        return _serving.snapshot()
    except Exception:  # noqa: BLE001
        return {}


def wedge_from_rows(rows, rank):
    """Apply the telemetry health plane's stall verdicts to this rank's
    ledger: ``rows`` is the classified per-rank list a job view carries
    (each row has ``rank`` and ``state``). Pure decision + local effect;
    called from the telemetry agent tick."""
    if not armed:
        return
    try:
        for row in rows or ():
            if row.get("rank") != rank:
                continue
            if row.get("state") == "stalled":
                note_wedge()
            elif row.get("state") == "healthy":
                note_unwedged()
            return
    except Exception:  # noqa: BLE001
        pass


def _export_metrics(now):
    """Throttled delta export into ``goodput_seconds_total{category}``
    (counters only increment, so export the per-category deltas)."""
    global _export_t, _export_last
    if now - _export_t < _EXPORT_EVERY_S:
        return
    _export_t = now
    snap = _ledger.snapshot()
    if not snap.get("enabled"):
        return
    from horovod_tpu.metrics import instruments as _metrics
    for cat, total in snap["categories"].items():
        delta = total - _export_last.get(cat, 0.0)
        if delta > 0.0:
            _metrics.record_goodput_seconds(cat, delta)
            _export_last[cat] = total


def _journal_heartbeat(now):
    """Throttled goodput summary into the durable run-history journal —
    the record a SIGKILLed run is left holding."""
    global _journal_t
    if now - _journal_t < _JOURNAL_EVERY_S:
        return
    _journal_t = now
    from horovod_tpu.goodput import history as _history
    _history.journal_append("goodput", summary=_ledger.snapshot())


_shutdown_done = False


def shutdown():
    """Final flush: last goodput summary (plus the serving variant when
    it saw traffic) into the journal, optional per-rank summary file,
    run_end marker. Idempotent — jax-0.4.x compat elastic workers end in
    ``os._exit`` (runner/task.py), where atexit never runs, so the clean
    exit path calls this explicitly before ``hvd.shutdown()`` and the
    atexit registration becomes a no-op fallback for everything else."""
    global _shutdown_done
    if not armed or _shutdown_done:
        return
    _shutdown_done = True
    try:
        snap = _ledger.snapshot()
        extra = {}
        srv = _serving.snapshot()
        if srv.get("steps"):
            extra["serving"] = srv
        from horovod_tpu.goodput import history as _history
        _history.journal_append("goodput", summary=snap, **extra)
        _history.journal_finalize(snap)
        _dump_rank_summary(snap, extra)
    except Exception:  # noqa: BLE001
        pass


def _dump_rank_summary(snap, extra):
    import json
    import os
    gdir = os.environ.get("HOROVOD_GOODPUT_DIR", "")
    if not gdir:
        return
    try:
        os.makedirs(gdir, exist_ok=True)
        rank = int(os.environ.get("HOROVOD_CROSS_RANK", "0") or 0)
        path = os.path.join(gdir, f"goodput_r{rank:02d}.json")
        with open(path, "w") as f:
            json.dump({"rank": rank, **snap, **extra}, f, indent=1,
                      sort_keys=True)
    except (OSError, ValueError):
        pass
