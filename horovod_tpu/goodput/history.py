"""Durable cross-run history: append-only per-run JSONL journals.

One file per run (``run_<id>.jsonl`` under ``HOROVOD_RUN_HISTORY_DIR``),
one JSON object per line, appended with an open/write/close per record —
the ``HVD_BENCH_PROGRESS_FILE`` discipline. Nothing is buffered in the
process, so a run killed mid-flight (SIGKILL a worker, then the
launcher) still leaves a parseable journal whose last goodput heartbeat
is at most ``HOROVOD_GOODPUT_JOURNAL_S`` old.

Record kinds:

- ``run_start``  run id, config fingerprint, world size, argv.
- ``goodput``    a goodput ledger summary (periodic heartbeat + final).
- ``bench``      a BENCH record ride-along from :mod:`bench`.
- ``cluster``    final cluster view (telemetry job view, when present).
- ``run_end``    clean-shutdown marker with the final goodput ratio — a
                 journal without one is a killed run, by definition.

Only the coordinator rank (cross rank 0) journals by default: the
journal is *job*-level evidence, and per-rank detail rides in through
the cluster view. Tests and the twin construct :class:`RunJournal`
directly.
"""

import hashlib
import json
import os
import threading
import time

_lock = threading.Lock()
_journal = None


def config_fingerprint(config):
    """Stable hash of the effective config — lets the report CLI group
    and diff runs that ran the same shape."""
    try:
        import dataclasses
        d = dataclasses.asdict(config)
    except (TypeError, ValueError):
        d = dict(getattr(config, "__dict__", {}) or {})
    blob = json.dumps(d, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class RunJournal:
    """Append-only JSONL journal for ONE run."""

    def __init__(self, root, run_id=None, fingerprint=""):
        self.root = str(root)
        self.run_id = run_id or time.strftime("%Y%m%d-%H%M%S") \
            + f"-{os.getpid()}"
        self.fingerprint = fingerprint
        self.path = os.path.join(self.root, f"run_{self.run_id}.jsonl")
        os.makedirs(self.root, exist_ok=True)

    def append(self, kind, **payload):
        """One flushed line; IO errors are the caller's concern only in
        tests — production goes through the fail-soft module wrapper."""
        line = json.dumps({"t": round(time.time(), 3), "run": self.run_id,
                           "kind": kind, **payload}, default=str)
        with open(self.path, "a") as f:
            f.write(line + "\n")
            f.flush()


def journal_configure(config, rank=0, world=1, run_id=None):
    """Arm the module journal (called by ``basics.init`` on rank 0 when
    ``run_history_dir`` is set)."""
    global _journal
    root = getattr(config, "run_history_dir", "") or ""
    if not root or rank != 0:
        _journal = None
        return None
    try:
        j = RunJournal(root, run_id=run_id or os.environ.get(
            "HOROVOD_RUN_ID") or None,
            fingerprint=config_fingerprint(config))
        j.append("run_start", fingerprint=j.fingerprint, world=world,
                 rank=rank, pid=os.getpid())
        with _lock:
            _journal = j
        return j
    except (OSError, ValueError):
        _journal = None
        return None


def get_journal():
    return _journal


def journal_append(kind, **payload):
    """Fail-soft append to the armed journal (no-op when unarmed)."""
    j = _journal
    if j is None:
        return
    try:
        j.append(kind, **payload)
    except Exception:  # noqa: BLE001 — history must never fail the job
        pass


def journal_finalize(goodput_summary):
    """Clean-shutdown marker: final cluster view + run_end."""
    j = _journal
    if j is None:
        return
    try:
        view = None
        try:
            from horovod_tpu.telemetry import aggregator
            agent = aggregator.get_agent()
            if agent is not None:
                view = agent.cluster_snapshot()
        except Exception:  # noqa: BLE001
            view = None
        if view:
            j.append("cluster", view=view)
        j.append("run_end",
                 goodput_ratio=goodput_summary.get("goodput_ratio"),
                 wall_s=goodput_summary.get("wall_s"))
    except Exception:  # noqa: BLE001
        pass


# --- readers (report CLI, tests) ----------------------------------------

def read_journal(path):
    """All parseable records of one journal file, in order. Tolerates a
    torn final line (the SIGKILL case this store exists for)."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        return []
    return out


def read_runs(root):
    """-> {run_id: summary} for every journal under ``root``. Each
    summary: start record, last goodput record, bench records, cluster
    view, whether the run ended cleanly."""
    runs = {}
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return runs
    for name in names:
        if not (name.startswith("run_") and name.endswith(".jsonl")):
            continue
        recs = read_journal(os.path.join(root, name))
        if not recs:
            continue
        run_id = recs[0].get("run") or name[4:-6]
        summary = {"run": run_id, "path": os.path.join(root, name),
                   "records": len(recs), "bench": [], "goodput": None,
                   "cluster": None, "start": None, "ended": False}
        for rec in recs:
            kind = rec.get("kind")
            if kind == "run_start":
                summary["start"] = rec
            elif kind == "goodput":
                summary["goodput"] = rec
            elif kind == "bench":
                summary["bench"].append(rec)
            elif kind == "cluster":
                summary["cluster"] = rec.get("view")
            elif kind == "run_end":
                summary["ended"] = True
        summary["t0"] = recs[0].get("t")
        summary["t1"] = recs[-1].get("t")
        runs[run_id] = summary
    return runs
