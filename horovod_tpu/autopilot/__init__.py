"""Autopilot: the online self-driving controller (ROADMAP item 4).

Closes the loop from the signal plane the repo already carries —
step-profiler attribution, fusion fill ratios, dispatch-plan hit rates,
per-tier wire bytes, telemetry health, watchdog findings — to the
runtime's knobs, and from health verdicts to the elastic driver:

- :mod:`horovod_tpu.autopilot.signals` — per-decision-epoch
  :class:`SignalFrame` deltas over every signal source, fail-soft.
- :mod:`horovod_tpu.autopilot.controller` — the coordinator-rank control
  loop: the :class:`~horovod_tpu.autotune.parameter_manager.
  ParameterManager` BO driven online (``suggest``/``observe``) over
  fusion threshold + cycle time + strategy + wire dtype, the cross-leg
  overlap point and the per-tier (DCN) wire as controller-owned levers,
  guarded by bounded moves, revert-on-regression (step-profiler
  robust-z) and converge-then-freeze. Followers adopt flips at flush
  boundaries (the PR-10 wire-dtype discipline).
- :mod:`horovod_tpu.autopilot.remediate` — watchdog/telemetry verdicts
  → blacklist + re-rendezvous through the elastic driver, with
  hysteresis, a removal rate limit, a do-not-shrink floor, and the
  existing blacklist cooldown governing re-admission.

Armed by ``HOROVOD_AUTOPILOT=1`` / ``hvdrun --autopilot``; every
decision and remediation is an ``autopilot_decision`` /
``autopilot_remediate`` flight event plus
``autopilot_decisions_total{lever,outcome}`` /
``autopilot_remediations_total{cause,outcome}`` metrics, so the whole
trail is post-mortem-able via ``python -m horovod_tpu.flight.analyze``.
See docs/performance.md (levers, guardrails, freeze semantics) and the
docs/troubleshooting.md runbook.
"""

from horovod_tpu.autopilot.controller import (  # noqa: F401
    AutopilotController, get_controller, start_from_config, stop,
)
from horovod_tpu.autopilot.remediate import (  # noqa: F401
    DriverArm, RemediationPolicy,
)
from horovod_tpu.autopilot.signals import (  # noqa: F401
    SignalFrame, cluster_view, frame, snapshot,
)
