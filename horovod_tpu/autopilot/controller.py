"""The autopilot controller: one closed loop from signals to knobs.

Every decision epoch (``HOROVOD_AUTOPILOT_INTERVAL`` seconds, or an
explicit :meth:`AutopilotController.tick` from tests/benches) the
controller:

1. diffs the signal plane into a :class:`~horovod_tpu.autopilot.signals.
   SignalFrame` (step-profiler attribution + ``cross_wait``, fusion
   fill/defer, dispatch-plan hit deltas, per-tier wire bytes, telemetry
   health, watchdog findings);
2. scores the epoch (reduced payload bytes per second — the same unit the
   reference ParameterManager optimizes) and feeds the
   :class:`~horovod_tpu.autotune.parameter_manager.ParameterManager` BO
   online through its ``observe``/``suggest`` increments;
3. applies the proposal through the fusion runtime's knobs — fusion
   threshold + cycle time, allreduce strategy (flat/torus/torus_qcross),
   and (when the user opted into one) the 16-bit/quantized flat wire —
   with the PR-10 flush-boundary discipline doing the multi-process half:
   the controller runs ONLY on the coordinator, its knob writes ride the
   next flush boundary to every follower, so eager and fused programs
   flip everywhere at one boundary;
4. steers the two levers the ParameterManager does not own: the
   cross-slice (DCN) wire of the hierarchical tier (adopt the quantized
   cross leg when a real hierarchy exists, keep it only if DCN bytes
   actually collapse and the step wall does not regress) and the
   cross-leg overlap point (compute-dominant epochs widen the await to
   the step boundary, comm-dominant ones collapse it to the next flush);
5. enforces the guardrails: **bounded move** per epoch (the BO proposal
   is clamped to one octave — ``max_move_log2=1`` — per epoch),
   **revert-on-regression** (an adopted cross-wire/overlap move whose
   next epoch regresses the step wall by the step profiler's robust-z is
   rolled back), and **converge-then-freeze** (after
   ``bayes_opt_max_samples`` scored epochs the best observed config is
   frozen, like the reference's offline tuner — the loop then only
   watches health);
6. feeds the remediation arm: dead/stalled telemetry verdicts and
   watchdog straggler namings go through the
   :class:`~horovod_tpu.autopilot.remediate.RemediationPolicy`
   (hysteresis / rate limit / floor), surviving actions are published to
   the elastic driver's KV for blacklist + re-rendezvous.

Every decision is forensics: a bounded in-memory record, an
``autopilot_decision`` flight-ring event and an
``autopilot_decisions_total{lever,outcome}`` metric — ``python -m
horovod_tpu.flight.analyze`` renders the trail post-mortem.
"""

import collections
import threading
import time

from horovod_tpu.common import logging as hvd_logging
from horovod_tpu.autopilot import remediate as _remediate
from horovod_tpu.autopilot import signals as _signals
from horovod_tpu.profile.ledger import robust_z as _robust_z

_MAX_DECISIONS = 256

# Revert-on-regression judges with the step profiler's OWN robust-z
# (profile.ledger.robust_z — one definition, threshold from
# config.profile_z_threshold); this many accepted epochs make a baseline.
_MIN_HISTORY = 3


class AutopilotController:
    """One per job, coordinator rank only (followers adopt knob flips at
    flush boundaries). Tests construct it directly and drive ``tick()``;
    production wires a daemon thread via :func:`start_from_config`."""

    def __init__(self, config, time_fn=time.monotonic):
        self._config = config
        self._time = time_fn
        self.interval = max(float(getattr(config, "autopilot_interval",
                                          10.0)), 0.1)
        self.epoch = 0
        self.frozen = False
        self._decisions = collections.deque(maxlen=_MAX_DECISIONS)
        self._tick_records = []    # records emitted by the CURRENT tick
        self._prev_snapshot = None
        self._walls = collections.deque(maxlen=32)   # accepted epoch walls
        self._z_threshold = float(getattr(config, "profile_z_threshold",
                                          4.0) or 4.0)
        self._pm = None
        self._dcn_peak_bps = None  # resolved lazily from the roofline
        # The previous DCN-tier wire when the controller armed int8 for
        # a torus_qcross sweep sample (None = nothing armed): restored
        # when the sweep moves off the strategy, so the arming can never
        # outlive the sample that needed it.
        self._qcross_armed = None
        # Cross-wire lever state: None = not tried yet; otherwise the
        # (previous cross wire, dcn bytes baseline) to revert to.
        self._cross_trial = None
        self._cross_adopted = False
        # The a2a (expert-dispatch) twins of the two states above: the
        # previous expert cross wire when the sweep armed int8 for a
        # hier_qcross sample, and the guarded one-epoch trial of the
        # quantized expert leg after freeze.
        self._a2a_qcross_armed = None
        self._a2a_cross_trial = None
        self._a2a_cross_adopted = False
        self._pending_acks = {}    # req_id -> action awaiting driver ack
        self._stop = threading.Event()
        self._thread = None
        min_world = int(getattr(config, "autopilot_min_world", 0) or 0)
        if min_world <= 0:
            min_world = 1
        self.policy = _remediate.RemediationPolicy(
            hysteresis=getattr(config, "autopilot_hysteresis", 3),
            max_removals=getattr(config, "autopilot_max_removals", 1),
            min_world=min_world, time_fn=time_fn)

    # --- plumbing -------------------------------------------------------

    def _runtime(self):
        """The fusion runtime (created on demand — the autopilot is an
        explicit opt-in, and its levers live there)."""
        from horovod_tpu.ops import fusion
        return fusion.get_runtime()

    def _slices(self):
        try:
            import jax
            from horovod_tpu.ops.collective_ops import _live_slices
            n = jax.device_count()
            slices, _ = _live_slices(n)
            return slices
        except Exception:  # noqa: BLE001
            return 1

    def _build_pm(self, runtime):
        """The proposal engine: the same ParameterManager the fusion
        runtime's offline autotuner uses, over the SAME categorical
        space (autotune.sweep_categoricals — one definition), but with
        epoch-granular samples, zero warmup (the baseline tick and the
        no-signal guard play that role — a warmup here would just burn
        scored epochs) and the bounded-move guardrail armed."""
        from horovod_tpu.autotune import (ParameterManager,
                                          sweep_categoricals)

        from horovod_tpu.ops import wire as _wire

        # The hierarchical-alltoall tier joins the sweep only when it is
        # armed (knob or registry pin): a job with no expert dispatch
        # must not spend scored epochs on a lever it cannot move.
        a2a_default = "hier_qcross" \
            if getattr(self._config, "hierarchical_alltoall", False) else ""
        a2a_cur = _wire.alltoall_strategy_for("global", a2a_default)
        cats = sweep_categoricals(
            runtime.strategy, self._config.wire_dtype,
            self._slices() > 1, a2a_strategy=a2a_cur or None,
            a2a_cross_dtype=getattr(self._config, "alltoall_cross_dtype",
                                    ""))
        pm = ParameterManager(
            warmup_samples=0,
            steps_per_sample=1,
            bayes_opt_max_samples=int(
                self._config.autotune_bayes_opt_max_samples),
            gaussian_process_noise=float(
                self._config.autotune_gaussian_process_noise),
            log_file=self._config.autotune_log_file or None,
            initial_threshold=runtime.threshold,
            initial_cycle_ms=runtime._cycle_s * 1000.0,
            categorical_knobs=cats,
            max_move_log2=1.0)
        self._load_prior(pm)
        return pm

    def _load_prior(self, pm):
        """Warm-start ``pm`` from a twin-pretrained prior artifact
        (``HOROVOD_AUTOPILOT_PRIOR`` — an ``export_observations`` JSON
        file written by ``horovod_tpu.sim.autopilot``): the categorical
        sweep is skipped and the numeric search starts at the twin's
        best point. Fail-soft by design — a missing, malformed, or
        space-mismatched prior logs and leaves the cold start intact
        (a bad artifact must never take the autopilot down with it)."""
        path = str(getattr(self._config, "autopilot_prior", "") or "")
        if not path:
            return
        try:
            import json
            with open(path) as f:
                data = json.load(f)
            consumed = pm.import_observations(data)
        except Exception as e:  # noqa: BLE001 — cold start still valid
            hvd_logging.warning(
                "autopilot prior %s not loaded (%s); starting cold",
                path, e)
            self._record("tuner", "prior_rejected", path=path,
                         error=str(e)[:200])
            return
        hvd_logging.info(
            "autopilot warm-started from twin prior %s: %d observations,"
            " categoricals=%s", path, consumed, pm.categoricals)
        self._record("tuner", "prior_loaded", path=path,
                     observations=consumed, categoricals=pm.categoricals)

    def _score(self, frame):
        """The epoch's objective: reduced payload bytes per second (the
        reference ParameterManager's unit), with the epoch's DCN bytes
        priced at the roofline's cross-slice peak and added to the
        denominator. On silicon the DCN wall is already inside
        ``elapsed_s`` and the term is a small monotone bias toward
        DCN-frugal configs; on the CPU tier — where a DCN "hop" costs
        the same memcpy as an ICI one and wall clock cannot separate
        them — it is what makes the hierarchy/wire levers converge to
        the same winners the hardware would pick
        (``HOROVOD_PEAK_DCN_GBS`` scales it)."""
        dcn_s = 0.0
        if frame.get("dcn_bytes"):
            if self._dcn_peak_bps is None:
                try:
                    from horovod_tpu.profile import roofline
                    self._dcn_peak_bps = max(
                        float(roofline.chip_peaks()["dcn_gbs"]), 1e-3) * 1e9
                except Exception:  # noqa: BLE001
                    self._dcn_peak_bps = 1e12
            dcn_s = frame["dcn_bytes"] / self._dcn_peak_bps
        return frame["reduced_bytes"] / (frame["elapsed_s"] + dcn_s)

    def _record(self, lever, outcome, frame=None, **extra):
        rec = {"epoch": self.epoch, "lever": lever, "outcome": outcome,
               "t": round(time.time(), 3)}
        rec.update(extra)
        if frame is not None:
            rec["signal"] = {k: frame.get(k) for k in
                            ("wall_mean_s", "steps", "reduced_bytes",
                             "dcn_bytes", "fill_ratio_mean")}
        self._decisions.append(rec)
        self._tick_records.append(rec)
        try:
            from horovod_tpu.metrics import instruments as _metrics
            _metrics.record_autopilot_decision(lever, outcome)
        except Exception:  # noqa: BLE001
            pass
        try:
            from horovod_tpu.flight import recorder as _flight
            if _flight.armed:
                # `is not None`, not truthiness: a legitimate 0.0 score
                # must not fall through to the wall mean (two units in
                # one field would skew any post-mortem reading scores).
                dur = extra.get("score")
                if dur is None and frame is not None:
                    dur = frame.get("wall_mean_s")
                _flight.record_event(
                    "autopilot_decision", name=lever, what=outcome,
                    seq=self.epoch, dur=dur)
        except Exception:  # noqa: BLE001
            pass
        return rec

    def decisions(self, last=None):
        out = list(self._decisions)
        return out if last is None else out[-last:]

    # --- the decision epoch --------------------------------------------

    def tick(self):
        """One decision epoch. Never raises (the loop must outlive any
        one bad signal read); returns the epoch's decision records."""
        # Collected as they are recorded, not sliced off the bounded
        # deque afterwards — once the deque is full, a length-based
        # slice would return [] forever.
        self._tick_records = []
        try:
            self._tick_inner()
        except Exception as e:  # noqa: BLE001
            hvd_logging.warning("autopilot tick failed: %s", e)
        return list(self._tick_records)

    def _tick_inner(self):
        cur = _signals.snapshot()
        view = _signals.cluster_view()
        if self._prev_snapshot is None:
            # First tick: baseline only — there is no epoch to score yet
            # (scoring a half-open window is exactly the NaN/garbage the
            # observe() clamp guards; skipping it is cleaner still).
            self._prev_snapshot = cur
            self._record("tuner", "baseline")
            return
        frame = _signals.frame(self._prev_snapshot, cur, view)
        self._prev_snapshot = cur
        self.epoch += 1

        self._remediate(frame, view)

        if not self.frozen:
            self._tune(frame)
        else:
            # Frozen: the loop narrows to guardrail duty — judge a still-
            # pending cross-wire trial, keep the overlap point steered,
            # and watch for drift (a sustained regression is surfaced and
            # post-mortem-able, never silently absorbed).
            runtime = self._runtime()
            self._judge_cross_trial(frame, runtime)
            self._judge_a2a_cross_trial(frame, runtime)
            self._steer_overlap(frame, runtime)
            if frame["wall_mean_s"] is not None:
                if len(self._walls) >= _MIN_HISTORY:
                    z, med = _robust_z(frame["wall_mean_s"],
                                       list(self._walls))
                    if z >= self._z_threshold:
                        self._record("tuner", "drift_detected", frame,
                                     z=round(z, 2),
                                     median_s=round(med, 6))
                    else:
                        self._walls.append(frame["wall_mean_s"])
                else:
                    self._walls.append(frame["wall_mean_s"])
        # Tell the goodput ledger whether a guarded trial window is open:
        # steps measured under a trial book to autopilot_trial (the trial
        # pays for itself in the decomposition), not productive_compute.
        try:
            from horovod_tpu.goodput import ledger as _goodput
            _goodput.set_trial(self._cross_trial is not None
                               or self._a2a_cross_trial is not None)
        except Exception:  # noqa: BLE001
            pass

    # --- tuning arm -----------------------------------------------------

    def _tune(self, frame):
        runtime = self._runtime()
        if self._pm is None:
            self._pm = self._build_pm(runtime)
            # The flush-path tuner and the autopilot must not fight over
            # the same knobs: the autopilot supersedes it.
            if runtime._parameter_manager is not None:
                hvd_logging.info(
                    "autopilot supersedes the flush-window autotuner")
                runtime._parameter_manager = None

        if not frame["steps"] and not frame["flushes"]:
            # Nothing dispatched this epoch: no score to attribute to the
            # current knobs (feeding 0 would bury them unfairly).
            self._record("tuner", "no_signal", frame)
            return

        score = self._score(frame)
        update = self._pm.observe(score)
        if frame["wall_mean_s"] is not None:
            self._walls.append(frame["wall_mean_s"])
        if update is None or not self._pm.tuning:
            self.frozen = True
            thr, cyc, cats = self._pm.suggest()
            self._apply(runtime, thr, cyc, cats)
            self._record("tuner", "frozen", frame, score=round(score, 1),
                         threshold=thr, cycle_ms=round(cyc, 3),
                         categoricals=dict(cats))
            self._maybe_try_cross(frame, runtime)
            self._maybe_try_a2a_cross(frame, runtime)
            return
        thr, cyc, cats = update
        changed = self._apply(runtime, thr, cyc, cats)
        self._record("tuner", "adopt" if changed else "hold", frame,
                     score=round(score, 1), threshold=thr,
                     cycle_ms=round(cyc, 3), categoricals=dict(cats))
        self._steer_overlap(frame, runtime)

    def _apply(self, runtime, threshold, cycle_ms, cats):
        """Apply a proposal to the runtime's knobs (coordinator-side; the
        next flush boundary carries program-shaping knobs to followers).
        Returns whether anything changed."""
        changed = False
        if threshold != runtime.threshold:
            runtime.threshold = int(threshold)
            changed = True
        new_cycle = max(float(cycle_ms), 1e-3) / 1000.0
        if abs(new_cycle - runtime._cycle_s) > 1e-9:
            runtime._cycle_s = new_cycle
            changed = True
        strategy = cats.get("strategy")
        if strategy and strategy != runtime.strategy:
            runtime.strategy = strategy
            changed = True
        from horovod_tpu.ops import wire as _wire
        if strategy == "torus_qcross":
            # torus_qcross MEANS a quantized cross leg: when the per-tier
            # policy chain resolves to full precision (the detuned /
            # unconfigured case), sweeping the strategy must sweep the
            # wire that defines it — otherwise qcross measures as plain
            # torus and the lever can never win. The ICI legs stay exact
            # either way; a bad epoch under it simply scores low and the
            # sweep moves on (the guardrail).
            cw = _wire.cross_wire_for("global", self._config)
            label = _wire.quantized_label("int8")
            if not _wire.is_quantized(cw) and label \
                    and self._qcross_armed is None:
                self._qcross_armed = cw or ""
                _wire.runtime_sync_wire_dtype(label, "global", tier="dcn")
                runtime.cross_wire = label
                changed = True
        elif strategy and self._qcross_armed is not None:
            # The sweep moved OFF torus_qcross: the wire the controller
            # armed FOR it must leave with it — a leftover int8 registry
            # entry would read as a user opt-in later (_maybe_try_cross
            # would skip its guarded trial) and price a lossy DCN leg
            # the runtime never moves.
            prev = self._qcross_armed
            self._qcross_armed = None
            _wire.runtime_sync_wire_dtype(prev, "global", tier="dcn")
            runtime.cross_wire = prev
            changed = True
        wire = cats.get("wire_dtype")
        if wire:
            import jax.numpy as jnp
            new_wire = jnp.dtype(wire).type
            if new_wire is not runtime.wire_dtype:
                runtime.wire_dtype = new_wire
                changed = True
        a2a = cats.get("a2a_strategy")
        if a2a:
            if _wire.alltoall_strategy_for("global") != a2a:
                _wire.runtime_sync_alltoall_strategy(a2a, "global")
                changed = True
            if a2a == "hier_qcross":
                # hier_qcross MEANS a quantized expert cross leg — same
                # rule as torus_qcross above: when the a2a cross chain
                # resolves to full precision the sweep must arm the wire
                # that defines the strategy, and restore it the moment
                # the sweep moves off (a leftover int8 pin would read as
                # a user opt-in and lossy-quantize activations the user
                # never asked to quantize).
                acw = _wire.alltoall_cross_wire_for("global", self._config)
                label = _wire.quantized_label("int8")
                if not _wire.is_quantized(acw) and label \
                        and self._a2a_qcross_armed is None:
                    self._a2a_qcross_armed = acw or ""
                    _wire.runtime_sync_alltoall_cross_dtype(label,
                                                            "global")
                    changed = True
            elif self._a2a_qcross_armed is not None:
                prev = self._a2a_qcross_armed
                self._a2a_qcross_armed = None
                _wire.runtime_sync_alltoall_cross_dtype(prev, "global")
                changed = True
        a2a_cw = cats.get("a2a_cross_dtype")
        if a2a_cw is not None and self._a2a_qcross_armed is None:
            cur = _wire.alltoall_cross_wire_for("global", self._config)
            if cur != _wire.resolve_wire_dtype(a2a_cw):
                _wire.runtime_sync_alltoall_cross_dtype(a2a_cw, "global")
                changed = True
        if changed:
            # Mirror the flush-snapshot adoption into the eager
            # registries now (sync dispatches between flushes must see
            # the same policy; runtime sync defers to explicit user
            # pins). Multi-process followers adopt the same values from
            # the next published boundary.
            from horovod_tpu.ops import wire as _wire
            if runtime.wire_dtype is not None:
                import jax.numpy as jnp
                _wire.runtime_sync_wire_dtype(
                    jnp.dtype(runtime.wire_dtype).name, "global")
            runtime._sync_eager_policy(runtime.strategy,
                                       runtime.cross_wire)
        return changed

    def _steer_overlap(self, frame, runtime):
        """The cross-leg overlap point lever, at epoch granularity: the
        per-flush steering already follows the last step's attribution;
        the controller pins the MODE when an epoch's attribution is
        one-sided, so a single outlier step cannot flap the await point
        mid-epoch. Records only actual changes."""
        att = frame.get("attribution_mean_s") or {}
        if not att or not runtime._overlap:
            return
        comm = att.get("collective", 0.0) + att.get("cross_wait", 0.0)
        mode = "next_flush" if comm > att.get("compute", 0.0) else "step"
        changed = mode != runtime._overlap_mode
        runtime._overlap_mode = mode
        # Pinning is what makes this a lever: the runtime's per-flush
        # steering defers while pinned, so the mode holds for the whole
        # epoch instead of being recomputed from the single last step at
        # the next flush.
        runtime._overlap_pinned = True
        if changed:
            self._record("overlap", mode, frame)

    # --- cross-wire lever ----------------------------------------------

    def _maybe_try_cross(self, frame, runtime):
        """After the tuner froze: if the winning strategy is the
        hierarchical tier and the cross leg still runs full precision,
        trial the quantized cross wire for one epoch. Kept only if DCN
        bytes actually collapse and the wall does not regress
        (:meth:`_judge_cross_trial`); reverted otherwise. One trial per
        freeze — this is a policy move with a guardrail, not a sweep."""
        from horovod_tpu.ops import wire as _wire
        if self._cross_adopted or self._cross_trial is not None:
            return
        if runtime.strategy not in ("torus", "torus_qcross") \
                or self._slices() <= 1:
            return
        current = _wire.cross_wire_for("global", self._config)
        if _wire.is_quantized(current):
            self._cross_adopted = True
            return                     # already quantized by config/user
        label = _wire.quantized_label("int8")
        if label is None:
            return
        prev = current or ""
        prev_strategy = runtime.strategy
        runtime.strategy = "torus_qcross"
        _wire.runtime_sync_wire_dtype(label, "global", tier="dcn")
        runtime.cross_wire = label
        runtime._sync_eager_policy(runtime.strategy, runtime.cross_wire)
        self._cross_trial = (prev, frame.get("dcn_bytes") or 0.0,
                             prev_strategy)
        self._record("cross_wire", "trial", frame, wire=label)

    def _judge_cross_trial(self, frame, runtime):
        """Revert-on-regression for the cross-wire trial, judged on the
        first measured epoch AFTER the trial armed. Trials only start at
        the freeze transition, so the judging call site is the frozen
        branch of the tick."""
        from horovod_tpu.ops import wire as _wire
        if self._cross_trial is None:
            return
        if not frame["flushes"] and not frame["steps"]:
            return                      # nothing measured yet; keep waiting
        prev_wire, prev_dcn, prev_strategy = self._cross_trial
        self._cross_trial = None
        wall = frame.get("wall_mean_s")
        regressed = False
        if wall is not None and len(self._walls) >= _MIN_HISTORY:
            z, _ = _robust_z(wall, list(self._walls))
            regressed = z >= self._z_threshold
        dcn_now = frame.get("dcn_bytes") or 0.0
        # A zero-DCN baseline is ABSENT evidence, not a collapse: without
        # a measured before/after the lossy cross wire is not kept.
        shrunk = prev_dcn > 0.0 and dcn_now < 0.75 * prev_dcn
        if regressed or not shrunk:
            # Revert BOTH halves to their saved pre-trial values —
            # inferring the strategy from the wire would leave
            # torus_qcross behind whenever the pre-trial cross wire was
            # a non-empty cast (e.g. bfloat16).
            _wire.runtime_sync_wire_dtype(prev_wire, "global", tier="dcn")
            runtime.cross_wire = prev_wire
            runtime.strategy = prev_strategy
            runtime._sync_eager_policy(runtime.strategy,
                                       runtime.cross_wire)
            self._record("cross_wire", "reverted", frame,
                         dcn_bytes=dcn_now, regressed=regressed)
            return
        self._cross_adopted = True
        self._record("cross_wire", "adopted", frame, dcn_bytes=dcn_now)

    def _maybe_try_a2a_cross(self, frame, runtime):
        """The expert-dispatch twin of :meth:`_maybe_try_cross`: after
        the tuner froze, if the hierarchical alltoall tier won (or is
        pinned) and its cross leg still runs full precision, trial the
        quantized expert cross wire for one epoch. Activations carry no
        error feedback, so the guardrail is strict: kept only if DCN
        bytes actually collapse and the wall does not regress."""
        from horovod_tpu.ops import wire as _wire
        if self._a2a_cross_adopted or self._a2a_cross_trial is not None:
            return
        default = "hier_qcross" \
            if getattr(self._config, "hierarchical_alltoall", False) else ""
        strategy = _wire.alltoall_strategy_for("global", default)
        if strategy not in ("hier", "hier_qcross") or self._slices() <= 1:
            return
        current = _wire.alltoall_cross_wire_for("global", self._config)
        if _wire.is_quantized(current):
            self._a2a_cross_adopted = True
            return                     # already quantized by config/user
        label = _wire.quantized_label("int8")
        if label is None:
            return
        prev = current or ""
        _wire.runtime_sync_alltoall_strategy("hier_qcross", "global")
        _wire.runtime_sync_alltoall_cross_dtype(label, "global")
        self._a2a_cross_trial = (prev, frame.get("dcn_bytes") or 0.0,
                                 strategy)
        self._record("a2a_cross_wire", "trial", frame, wire=label)

    def _judge_a2a_cross_trial(self, frame, runtime):
        """Revert-on-regression for the expert cross-wire trial — same
        judge as :meth:`_judge_cross_trial` (robust-z on the wall,
        DCN-bytes collapse below 0.75x the pre-trial baseline), reverting
        BOTH the wire and the strategy to their saved pre-trial
        values."""
        from horovod_tpu.ops import wire as _wire
        if self._a2a_cross_trial is None:
            return
        if not frame["flushes"] and not frame["steps"]:
            return                      # nothing measured yet; keep waiting
        prev_wire, prev_dcn, prev_strategy = self._a2a_cross_trial
        self._a2a_cross_trial = None
        wall = frame.get("wall_mean_s")
        regressed = False
        if wall is not None and len(self._walls) >= _MIN_HISTORY:
            z, _ = _robust_z(wall, list(self._walls))
            regressed = z >= self._z_threshold
        dcn_now = frame.get("dcn_bytes") or 0.0
        shrunk = prev_dcn > 0.0 and dcn_now < 0.75 * prev_dcn
        if regressed or not shrunk:
            _wire.runtime_sync_alltoall_cross_dtype(prev_wire, "global")
            _wire.runtime_sync_alltoall_strategy(prev_strategy, "global")
            self._record("a2a_cross_wire", "reverted", frame,
                         dcn_bytes=dcn_now, regressed=regressed)
            return
        self._a2a_cross_adopted = True
        self._record("a2a_cross_wire", "adopted", frame,
                     dcn_bytes=dcn_now)

    # --- remediation arm ------------------------------------------------

    def _verdicts(self, frame, view):
        """Merge telemetry dead/stalled states and watchdog straggler
        namings into this epoch's verdict dict."""
        verdicts = {}
        for rank, count in (frame.get("straggler_namings") or {}).items():
            verdicts[int(rank)] = {"cause": "straggler",
                                   "host": _remediate.host_of_rank(
                                       rank, view)}
        for rank, st in (frame.get("unhealthy") or {}).items():
            state = st.get("state")
            if state in ("dead", "stalled"):
                verdicts[int(rank)] = {
                    "cause": state,
                    "host": st.get("host")
                    or _remediate.host_of_rank(rank, view)}
            elif state == "straggling" and st.get("why") \
                    == "watchdog_named" and int(rank) not in verdicts:
                verdicts[int(rank)] = {"cause": "straggler",
                                       "host": st.get("host")}
        return verdicts

    def _world(self, view):
        if view and not view.get("local_only") and view.get("world"):
            return int(view["world"])
        try:
            import jax
            return jax.process_count()
        except Exception:  # noqa: BLE001
            return 1

    def _check_acks(self):
        """Consume driver-arm outcomes for outstanding requests: a
        rejection (the driver's floor/rate are authoritative and may
        veto what the coordinator's view allowed) refunds the policy's
        rate-budget slot and host cooldown so the arm isn't starved for
        a whole window by a request that executed nothing."""
        if not self._pending_acks:
            return
        client = _remediate._launcher_kv()
        if client is None:
            return
        for req_id, action in list(self._pending_acks.items()):
            try:
                raw = client.get("autopilot", f"ack/{req_id}")
            except Exception:  # noqa: BLE001 — retry next epoch
                continue
            if raw is None:
                continue
            outcome = raw.decode() if isinstance(raw, bytes) else str(raw)
            del self._pending_acks[req_id]
            if outcome.startswith("rejected"):
                self.policy.refund(action.get("host"))
            self._record("remediate", outcome, rank=action.get("rank"),
                         host=action.get("host"), cause=action["cause"],
                         request=req_id)

    @staticmethod
    def _host_sizes(view):
        """{host: ranks-on-it} from the telemetry view (the policy's
        per-host floor debit); empty when no view exists."""
        sizes = {}
        for st in (view.get("health") or {}).values() if view else ():
            h = st.get("host")
            if h:
                sizes[h] = sizes.get(h, 0) + 1
        return sizes

    def _remediate(self, frame, view):
        # Keep the policy's host protection pointed at OUR host: the
        # controller runs on the coordinator, and a verdict on a rank
        # colocated with it must never evict this host.
        import os
        my_host = os.environ.get("HOROVOD_HOST_KEY") \
            or _remediate.host_of_rank(0, view)
        if my_host:
            self.policy.protected_hosts = {my_host}
        self._check_acks()
        verdicts = self._verdicts(frame, view)
        if not verdicts:
            self.policy.observe({}, self._world(view))
            return
        actions = self.policy.observe(verdicts, self._world(view),
                                      host_sizes=self._host_sizes(view))
        for action in actions:
            req = _remediate.publish_request(action, epoch=self.epoch)
            if req:
                self._pending_acks[req] = action
            self._record("remediate",
                         "requested" if req else "unreachable", frame,
                         rank=action["rank"], host=action.get("host"),
                         cause=action["cause"], request=req)

    # --- thread ---------------------------------------------------------

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval):
                self.tick()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="hvd-autopilot")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        # Hand overlap steering back to the per-flush path: a pin must
        # not outlive the loop that maintains it.
        try:
            from horovod_tpu.common import basics
            rt = basics._get_state().fusion
            if rt is not None:
                rt._overlap_pinned = False
        except Exception:  # noqa: BLE001 — already torn down
            pass


# --- module singleton (basics.init / shutdown wiring) ----------------------

_controller = None


def get_controller():
    return _controller


def start_from_config(config):
    """Arm the autopilot when ``HOROVOD_AUTOPILOT`` asks for it. The
    control thread runs ONLY on the coordinator (process 0) — knob flips
    reach followers through the flush-boundary stream, and two deciders
    would publish conflicting boundaries. Returns the controller or
    None."""
    global _controller
    if not getattr(config, "autopilot", False):
        return None
    if _controller is not None:
        return _controller
    try:
        import jax
        if jax.process_count() > 1 and jax.process_index() != 0:
            return None
    except Exception:  # noqa: BLE001
        return None
    _controller = AutopilotController(config)
    _controller.start()
    hvd_logging.info(
        "autopilot armed: interval=%.1fs hysteresis=%d max_removals=%d "
        "min_world=%d", _controller.interval,
        _controller.policy.hysteresis, _controller.policy.max_removals,
        _controller.policy.min_world)
    return _controller


def stop():
    global _controller
    if _controller is not None:
        _controller.stop()
        _controller = None
