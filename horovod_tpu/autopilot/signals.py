"""The autopilot's signal plane: one coherent per-epoch frame.

Everything the controller steers on already exists somewhere in the
stack — step-profiler attribution, fusion fill ratios, dispatch-plan hit
rates, per-tier wire bytes, telemetry health, watchdog findings — but as
cumulative counters and bounded rings with per-subsystem schemas. This
module snapshots all of them at a decision-epoch boundary and diffs two
snapshots into a :class:`SignalFrame`: the DELTAS for exactly one epoch,
in one dict, with every read fail-soft (a signal source that is off or
mid-reset contributes nothing, never an exception — the controller must
keep deciding on whatever signal survives).

The frame is deliberately plain data (no live references into the
subsystems) so decisions are post-mortem-able: the controller attaches
the frame it decided on to each decision record.
"""

import time

from horovod_tpu.profile.ledger import CATEGORIES

# Counter families diffed value-wise per (sorted label items) series.
_COUNTER_FAMILIES = (
    "fusion_boundary_outcomes_total",
    "dispatch_plan_events_total",
    "wire_bytes_total",
    "collective_bytes_total",
    "step_profiler_events_total",
)
# Histogram families diffed as (count, sum) pairs.
_HISTOGRAM_FAMILIES = (
    "fusion_fill_ratio",
    "fusion_flush_bytes",
    "collective_latency_seconds",
)


def _series_map(snap, name):
    out = {}
    for s in snap.get(name, {}).get("series", ()):
        key = tuple(sorted(s["labels"].items()))
        if "value" in s:
            out[key] = float(s["value"])
        else:
            out[key] = (float(s.get("count", 0)), float(s.get("sum", 0.0)))
    return out


def snapshot():
    """Cumulative view of every signal source at one instant. Cheap: one
    registry snapshot + bounded ring reads; safe to call from the
    controller thread at any time."""
    snap = {"t": time.perf_counter(), "wall_t": time.time()}
    try:
        from horovod_tpu.metrics.instruments import REGISTRY
        reg = REGISTRY.snapshot()
        snap["counters"] = {n: _series_map(reg, n)
                            for n in _COUNTER_FAMILIES}
        snap["histograms"] = {n: _series_map(reg, n)
                              for n in _HISTOGRAM_FAMILIES}
    except Exception:  # noqa: BLE001 — registry off/mid-reset
        snap["counters"], snap["histograms"] = {}, {}
    try:
        from horovod_tpu.profile import ledger as _ledger
        recs = _ledger.step_report(last=None) or []
        snap["last_step_key"] = (recs[-1]["epoch"], recs[-1]["step"],
                                 recs[-1]["t"]) if recs else None
        snap["step_records"] = recs
    except Exception:  # noqa: BLE001
        snap["last_step_key"], snap["step_records"] = None, []
    try:
        from horovod_tpu.profile import watchdog as _watchdog
        snap["findings"] = list(_watchdog.findings())
    except Exception:  # noqa: BLE001
        snap["findings"] = []
    try:
        # Declared-SLO burn (absolute, not a delta: a burn rate is
        # already windowed) — ROADMAP item 1's resize-on-SLO input.
        from horovod_tpu.telemetry import slo as _slo
        snap["slo_burn"] = _slo.burn_rates()
    except Exception:  # noqa: BLE001
        snap["slo_burn"] = {}
    try:
        # Goodput decomposition (cumulative; frame() diffs the category
        # seconds) — lets the controller see efficiency, not just
        # bytes/sec: a tuning trial that moves bytes but grows
        # straggler_wait is a loss.
        from horovod_tpu.goodput import ledger as _goodput
        snap["goodput"] = _goodput.snapshot()
    except Exception:  # noqa: BLE001
        snap["goodput"] = {}
    return snap


def _delta_counters(prev, cur):
    out = {}
    for name, series in cur.items():
        p = prev.get(name, {})
        d = {}
        for key, v in series.items():
            dv = v - p.get(key, 0.0)
            if dv:
                d[key] = dv
        out[name] = d
    return out


def _delta_hist(prev, cur):
    out = {}
    for name, series in cur.items():
        p = prev.get(name, {})
        d = {}
        for key, (cnt, tot) in series.items():
            p_cnt, p_tot = p.get(key, (0.0, 0.0))
            if cnt - p_cnt:
                d[key] = (cnt - p_cnt, tot - p_tot)
        out[name] = d
    return out


class SignalFrame(dict):
    """One decision epoch's signal deltas (a dict subclass so records
    serialize straight into flight/bench evidence). Keys:

    - ``elapsed_s``           wall of the epoch (perf_counter delta)
    - ``steps``               step records closed this epoch
    - ``wall_mean_s``         mean step wall over those records
    - ``attribution_mean_s``  per-category means incl. ``cross_wait``
    - ``reduced_bytes``       collective payload bytes this epoch
    - ``flushes`` / ``flush_bytes`` / ``fill_ratio_mean``
    - ``boundary_deferred``   follower boundaries deferred
    - ``plan_hits`` / ``plan_misses``
    - ``wire_bytes``          {"dtype|tier": bytes} deltas
    - ``dcn_bytes`` / ``ici_bytes``
    - ``health_counts``       live telemetry state counts (absolute)
    - ``unhealthy``           {rank: {"state", "why"}} non-healthy ranks
    - ``straggler_namings``   {rank: count} new watchdog namings
    - ``slo_burn``            {objective: burn} declared-SLO burn rates
                              (absolute; {} when no SLO is declared)
    - ``goodput_ratio``       cumulative job goodput ratio (absolute;
                              None when accounting is off)
    - ``badput_delta_s``      {category: seconds} badput booked this
                              epoch (goodput-ledger category deltas)
    """


def frame(prev, cur, cluster_view=None):
    """Diff two :func:`snapshot` results into a :class:`SignalFrame`.
    ``cluster_view`` (a ``cluster_snapshot()`` dict) is absolute state,
    not a delta — it rides along for the remediation arm."""
    f = SignalFrame()
    f["elapsed_s"] = max(cur["t"] - prev["t"], 1e-9)
    counters = _delta_counters(prev.get("counters", {}),
                               cur.get("counters", {}))
    hists = _delta_hist(prev.get("histograms", {}),
                        cur.get("histograms", {}))

    # Step records closed during this epoch (ledger keeps a bounded ring;
    # the (epoch, step, t) key of the previous frame's last record marks
    # the cut).
    recs = cur.get("step_records", [])
    prev_key = prev.get("last_step_key")
    if prev_key is not None:
        recs = [r for r in recs
                if (r["epoch"], r["step"], r["t"]) > prev_key]
    f["steps"] = len(recs)
    if recs:
        walls = [r["wall_s"] for r in recs]
        f["wall_mean_s"] = round(sum(walls) / len(walls), 6)
        att = {}
        for cat in CATEGORIES + ("compute",):
            att[cat] = round(sum(r["attribution"].get(cat, 0.0)
                                 for r in recs) / len(recs), 6)
        f["attribution_mean_s"] = att
    else:
        f["wall_mean_s"] = None
        f["attribution_mean_s"] = {}

    f["reduced_bytes"] = sum(
        counters.get("collective_bytes_total", {}).values())
    fl = hists.get("fusion_flush_bytes", {})
    f["flushes"] = int(sum(c for c, _ in fl.values()))
    f["flush_bytes"] = sum(s for _, s in fl.values())
    fr = hists.get("fusion_fill_ratio", {})
    n_fr = sum(c for c, _ in fr.values())
    f["fill_ratio_mean"] = round(
        sum(s for _, s in fr.values()) / n_fr, 6) if n_fr else None
    f["boundary_deferred"] = sum(
        v for k, v in counters.get("fusion_boundary_outcomes_total",
                                   {}).items()
        if dict(k).get("outcome") == "deferred")
    plan = counters.get("dispatch_plan_events_total", {})
    f["plan_hits"] = sum(v for k, v in plan.items()
                         if dict(k).get("event") == "hit")
    f["plan_misses"] = sum(v for k, v in plan.items()
                           if dict(k).get("event") == "miss")
    wire = {}
    for key, v in counters.get("wire_bytes_total", {}).items():
        lab = dict(key)
        wire[f"{lab.get('dtype')}|{lab.get('tier')}"] = v
    f["wire_bytes"] = wire
    f["dcn_bytes"] = sum(v for k, v in wire.items()
                         if k.endswith("|dcn"))
    f["ici_bytes"] = sum(v for k, v in wire.items()
                         if k.endswith("|ici"))

    # New watchdog straggler namings this epoch: findings present in cur
    # but not in prev (keyed by (kind, rank, step) — the bounded deque may
    # have evicted old entries, which only ever UNDER-counts).
    seen = {(e.get("kind"), e.get("rank"), e.get("step"))
            for e in prev.get("findings", [])}
    namings = {}
    for e in cur.get("findings", []):
        if e.get("kind") != "straggler":
            continue
        if (e.get("kind"), e.get("rank"), e.get("step")) in seen:
            continue
        r = e.get("rank")
        if r is not None:
            namings[int(r)] = namings.get(int(r), 0) + 1
    f["straggler_namings"] = namings

    f["slo_burn"] = dict(cur.get("slo_burn", {}))

    # Goodput: ratio rides absolute (it is already cumulative and the
    # controller wants the level), badput as per-category deltas so a
    # trial's verdict can charge exactly the badput it caused.
    gp_cur, gp_prev = cur.get("goodput") or {}, prev.get("goodput") or {}
    f["goodput_ratio"] = gp_cur.get("goodput_ratio") \
        if gp_cur.get("enabled") else None
    deltas = {}
    if gp_cur.get("enabled"):
        c_cats = gp_cur.get("categories") or {}
        p_cats = gp_prev.get("categories") or {}
        for cat, v in c_cats.items():
            if cat == "productive_compute":
                continue
            dv = float(v) - float(p_cats.get(cat, 0.0))
            if dv > 0.0:
                deltas[cat] = round(dv, 6)
    f["badput_delta_s"] = deltas

    f["health_counts"] = {}
    f["unhealthy"] = {}
    if cluster_view:
        f["health_counts"] = dict(cluster_view.get("counts", {}))
        for r_str, st in (cluster_view.get("health") or {}).items():
            if st.get("state") not in (None, "healthy"):
                f["unhealthy"][int(r_str)] = {
                    "state": st.get("state"), "why": st.get("why"),
                    "host": st.get("host")}
    return f


def cluster_view():
    """The telemetry job view for the remediation arm (fail-soft: the
    local fallback or None when telemetry is entirely absent)."""
    try:
        from horovod_tpu.telemetry import aggregator as _agg
        return _agg.cluster_snapshot()
    except Exception:  # noqa: BLE001
        return None
