"""Automated remediation: health verdicts → blacklist + re-rendezvous.

The loop the ROADMAP's "instead of a human reading /cluster/health"
demands, in three pieces:

- :class:`RemediationPolicy` — a PURE decision core (fake-clock testable):
  per-epoch verdicts in (watchdog straggler namings + telemetry
  dead/stalled states), bounded actions out, guarded by **hysteresis**
  (a rank must be named ``hysteresis`` consecutive epochs — one noisy
  publish round must never cost a host), a **rate limiter** (at most
  ``max_removals`` per rolling ``window_s`` — a systemic slowdown that
  names a different rank each round must not strip the fleet), a
  **do-not-shrink floor** (``min_world``), and a per-host re-request
  cooldown (an actioned host is not re-requested within the window; its
  driver-side cooldown — the existing ``blacklist_cooldown_range``
  exponential backoff — governs actual re-admission).

- the **coordinator arm** (:func:`publish_request`, called by the
  controller): publishes each action to the launcher HTTP-KV under the
  ``autopilot`` scope (``req/<n>`` + a ``head`` counter), records the
  ``autopilot_remediate`` flight event and the
  ``autopilot_remediations_total{cause,outcome=requested}`` metric.

- the **driver arm** (:class:`DriverArm`, polled by the elastic driver's
  discovery loop): consumes requests, re-validates floor + rate against
  the driver's OWN view (the worker-side checks ran on a stale world —
  the driver's are authoritative), then cools the host down through the
  existing :class:`~horovod_tpu.runner.elastic.discovery.HostManager`
  failure path — discovery drops the host, the normal membership update
  re-rendezvouses the survivors, and the exponential cooldown re-admits
  the host later exactly like a crash would. Every consumed request is
  acked back to ``autopilot/ack/<id>`` so the decision's outcome is
  KV-auditable too.

Rank 0 is protected: the coordination service and the boundary stream
live there, and removing it converts a slow job into a dead one.
"""

import json
import os
import time

from horovod_tpu.common import logging as hvd_logging

# Rolling rate-limiter window (seconds). Deliberately not a knob: the
# knobs bound HOW MUCH may be removed (HOROVOD_AUTOPILOT_MAX_REMOVALS)
# and HOW SMALL the world may get (HOROVOD_AUTOPILOT_MIN_WORLD); the
# window just defines "per incident".
WINDOW_S = 600.0

# Causes, most severe first (a dead verdict overrides a straggler one).
CAUSES = ("dead", "stalled", "straggler")


class RemediationPolicy:
    """The pure decision core. ``observe`` is called once per decision
    epoch with that epoch's verdicts; state (streaks, action log) lives
    here so the controller stays stateless about remediation."""

    def __init__(self, hysteresis=3, max_removals=1, min_world=1,
                 window_s=WINDOW_S, protected=(0,), protected_hosts=(),
                 time_fn=time.monotonic):
        self.hysteresis = max(int(hysteresis), 1)
        self.max_removals = max(int(max_removals), 0)
        self.min_world = max(int(min_world), 1)
        self.window_s = float(window_s)
        self.protected = set(protected or ())
        # Removal is per HOST: protecting rank 0 alone would still evict
        # its host through a verdict on a COLOCATED rank (multi-slot
        # launches). The controller keeps this set pointed at its own
        # host each epoch.
        self.protected_hosts = set(protected_hosts or ())
        self._time = time_fn
        self._streaks = {}        # rank -> (consecutive epochs, last cause)
        self._actions = []        # (t, host, rank, cause) actioned log
        self._hosts_cooling = {}  # host -> t actioned (re-request cooldown)

    def _in_window(self, now):
        return [a for a in self._actions if now - a[0] < self.window_s]

    def observe(self, verdicts, world, now=None, host_sizes=None):
        """``verdicts``: {rank: {"cause": dead|stalled|straggler,
        "host": str|None}} for THIS epoch (absent rank = healthy this
        epoch, which resets its streak). ``world``: current live world
        size. ``host_sizes`` ({host: ranks-on-it}, from the telemetry
        view): removal is per HOST, so the floor debits the victim
        host's whole rank count, not 1. Returns the list of actions to
        execute now, each ``{"rank", "host", "cause", "streak"}`` —
        already debited from the rate limiter, so the caller executes
        all of them (and feeds driver rejections back via
        :meth:`refund`)."""
        now = self._time() if now is None else now
        # Streak bookkeeping: consecutive epochs named, any cause.
        for rank in list(self._streaks):
            if rank not in verdicts:
                del self._streaks[rank]
        for rank, v in verdicts.items():
            n, _ = self._streaks.get(rank, (0, None))
            self._streaks[rank] = (n + 1, v.get("cause"))

        actions = []
        recent = self._in_window(now)
        self._actions = recent
        budget = self.max_removals - len(recent)
        # Most-severe cause first, then longest streak, then lowest rank:
        # a deterministic order so two coordinators (tests, re-elections)
        # would pick the same victim.
        order = sorted(
            verdicts.items(),
            key=lambda kv: (CAUSES.index(kv[1].get("cause"))
                            if kv[1].get("cause") in CAUSES else len(CAUSES),
                            -self._streaks.get(kv[0], (0, None))[0],
                            kv[0]))
        pending = 0
        for rank, v in order:
            if budget <= 0:
                break
            if rank in self.protected:
                continue
            streak, _ = self._streaks.get(rank, (0, None))
            if streak < self.hysteresis:
                continue
            host = v.get("host")
            if host is None:
                # Unmappable target (telemetry view not fresh yet): emit
                # nothing — a host-less request would only burn the rate
                # budget at the driver. The streak KEEPS accumulating, so
                # the action fires the first epoch the host resolves.
                continue
            if host in self.protected_hosts:
                continue          # the coordinator's host lives here
            if host in self._hosts_cooling and \
                    now - self._hosts_cooling[host] < self.window_s:
                continue          # already actioned; driver cooldown owns it
            removes = (host_sizes or {}).get(host, 1)
            if world - pending - removes < self.min_world:
                # Floor veto for THIS victim only (`continue`, like the
                # DriverArm's per-request rejection): one oversized host
                # must not starve a smaller eligible one behind it.
                continue
            actions.append({"rank": rank, "host": host,
                            "cause": v.get("cause"), "streak": streak})
            self._actions.append((now, host, rank, v.get("cause")))
            self._hosts_cooling[host] = now
            self._streaks.pop(rank, None)
            pending += removes
            budget -= 1
        return actions

    def refund(self, host):
        """Driver-arm REJECTION feedback: the request executed nothing,
        so its rate-budget slot and host cooldown are returned — a veto
        (floor/rate divergence between the coordinator's view and the
        driver's authoritative one) must not starve the arm for a whole
        window. The cleared hysteresis streak is deliberately NOT
        restored: re-accumulating it is the damping that prevents a
        request/reject ping-pong."""
        for i in range(len(self._actions) - 1, -1, -1):
            if self._actions[i][1] == host:
                del self._actions[i]
                break
        self._hosts_cooling.pop(host, None)

    def streaks(self):
        return {r: n for r, (n, _) in self._streaks.items()}


# --- coordinator arm: KV publication --------------------------------------

def _launcher_kv():
    """The launcher HTTP-KV client — the elastic worker's ONE
    env-to-client helper, with a bounded timeout (remediation runs on
    the control thread; a wedged KV must cost seconds, not the default
    30)."""
    from horovod_tpu.elastic.worker import _kv_client
    return _kv_client(timeout=5)


def host_of_rank(rank, cluster_view=None):
    """rank→host mapping for a remediation target: the telemetry health
    row's host (beacons carry ``HOROVOD_HOST_KEY`` — the same key the
    driver's host table uses), else None: the driver arm refuses
    host-less requests, so a target the telemetry plane cannot place is
    never removed on a guess."""
    if cluster_view:
        row = (cluster_view.get("health") or {}).get(str(rank)) or {}
        if row.get("host"):
            return row["host"]
    return None


def publish_request(action, epoch=None):
    """Coordinator side: write one remediation request to the launcher
    KV (scope ``autopilot``) and record the forensics trail. Returns the
    request id, or None when no launcher KV is reachable (single-process
    / non-hvdrun runs: the decision is still recorded, nothing executes
    it)."""
    from horovod_tpu.flight import recorder as _flight
    from horovod_tpu.metrics import instruments as _metrics

    cause = action.get("cause") or "unknown"
    if _flight.armed:
        _flight.record_event(
            "autopilot_remediate", name=f"rank{action.get('rank')}",
            what=cause, seq=epoch,
            sig=None, nbytes=None, op=action.get("host"))
    client = _launcher_kv()
    if client is None or not os.environ.get("HOROVOD_ELASTIC"):
        # A static launch has the launcher KV but NO DriverArm polling
        # it (only run_elastic_driver installs one): publishing would
        # record `requested` for a request nothing can ever execute —
        # and the runbook would read the missing `applied` as a driver
        # veto. The decision is still on the flight ring above.
        _metrics.record_autopilot_remediation(cause, "no_driver")
        return None
    try:
        head = int(client.get("autopilot", "head") or 0)
        req_id = f"{os.getpid()}-{head}"
        payload = dict(action)
        payload.update({"id": req_id, "t": round(time.time(), 3),
                        "epoch": epoch})
        client.put("autopilot", f"req/{head}",
                   json.dumps(payload).encode())
        client.put("autopilot", "head", str(head + 1).encode())
    except Exception as e:  # noqa: BLE001 — remediation is best-effort
        hvd_logging.warning("autopilot remediation publish failed: %s", e)
        _metrics.record_autopilot_remediation(cause, "publish_failed")
        return None
    _metrics.record_autopilot_remediation(cause, "requested")
    hvd_logging.warning(
        "autopilot: requested removal of rank %s (host %s, cause %s)",
        action.get("rank"), action.get("host"), cause)
    return req_id


# --- driver arm ------------------------------------------------------------

class DriverArm:
    """Polled by the elastic driver's discovery loop (one KV head read
    per poll). Applies each new request through the HostManager's
    failure/cooldown path and acks the outcome."""

    def __init__(self, kv, host_manager, min_world=1, max_removals=1,
                 window_s=WINDOW_S, time_fn=time.monotonic):
        self._kv = kv
        self._hm = host_manager
        self.min_world = max(int(min_world), 1)
        self.max_removals = max(int(max_removals), 0)
        self.window_s = float(window_s)
        self._time = time_fn
        self._next = 0            # next req index to consume
        self._seen = set()        # request ids already processed
        self._applied = []        # (t, host) applied log (rate window)

    def _ack(self, req, outcome):
        try:
            self._kv.put("autopilot", f"ack/{req.get('id')}",
                         outcome.encode())
        except Exception:  # noqa: BLE001
            pass
        try:
            from horovod_tpu.metrics import instruments as _metrics
            _metrics.record_autopilot_remediation(
                req.get("cause") or "unknown", outcome)
        except Exception:  # noqa: BLE001
            pass
        from horovod_tpu.flight import recorder as _flight
        if _flight.armed:
            _flight.record_event("autopilot_remediate",
                                 name=f"rank{req.get('rank')}",
                                 what=outcome, op=req.get("host"))

    def poll(self, hosts):
        """Consume any new requests against the freshly-discovered
        ``hosts`` dict; returns the set of hosts removed THIS poll (the
        driver excludes them from this round's assignment immediately —
        the HostManager cooldown keeps them out of later rounds)."""
        removed = set()
        try:
            head = int(self._kv.get("autopilot", "head") or 0)
        except Exception:  # noqa: BLE001
            return removed
        now = self._time()
        self._applied = [a for a in self._applied
                         if now - a[0] < self.window_s]
        while self._next < head:
            idx = self._next
            self._next += 1
            try:
                raw = self._kv.get("autopilot", f"req/{idx}")
            except Exception:  # noqa: BLE001
                # Transient transport fault: do NOT consume the index —
                # a dropped request would get no blacklist, no ack and
                # no retry until the policy's whole cooldown window.
                self._next = idx
                break
            try:
                req = json.loads(raw) if raw else None
            except Exception:  # noqa: BLE001 — malformed: skip it
                req = None
            if not req or req.get("id") in self._seen:
                continue
            self._seen.add(req.get("id"))
            host = req.get("host")
            if not host or host not in hosts:
                self._ack(req, "rejected_unknown_host")
                continue
            if len(self._applied) >= self.max_removals:
                self._ack(req, "rejected_rate")
                continue
            # Floor in PROCESSES (slots), not hosts: min_world mirrors
            # --min-np, and a multi-slot deployment removing one host
            # loses that host's slot count, not 1.
            live = sum(s for h, s in hosts.items() if h not in removed)
            if live - hosts[host] < self.min_world:
                self._ack(req, "rejected_floor")
                continue
            # The existing blacklist/cooldown path: record_failure applies
            # the exponential cooldown (HOROVOD_BLACKLIST_COOLDOWN_RANGE),
            # discovery drops the host while it cools, and re-admits it
            # after — the same lifecycle a crashed host gets.
            self._hm.record_failure(host)
            self._applied.append((now, host))
            removed.add(host)
            hvd_logging.warning(
                "autopilot driver arm: removing host %s (rank %s, "
                "cause %s) — re-rendezvous follows", host,
                req.get("rank"), req.get("cause"))
            self._ack(req, "applied")
        return removed
