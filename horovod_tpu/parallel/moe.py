"""Expert parallelism: switch-style Mixture-of-Experts with AllToAll dispatch.

The reference exposes AllToAll with negotiated uneven splits as a raw
primitive (reference: horovod/common/operations.cc:1930 EnqueueTensorAlltoall,
collective_operations.h:199-268) — the exact communication pattern MoE
dispatch needs — but ships no MoE layer (SURVEY.md §2.6: EP absent as a
strategy). This module builds the strategy TPU-first:

- **Static shapes**: capacity-based dispatch (Switch Transformer style).
  Every expert receives exactly ``capacity`` token slots per source shard;
  overflow tokens are dropped (their residual path passes through). No
  dynamic shapes, so the whole layer jits into one XLA program and the
  dispatch einsums run on the MXU.
- **EP over a mesh axis**: experts are sharded across ``ep``; two
  ``lax.all_to_all``s over ICI move token slots to their expert's shard and
  back — the MoE realization of the reference's alltoall primitive.
- **Router**: top-1 (switch) or top-2 gating with the standard
  load-balancing auxiliary loss (fraction-of-tokens x mean-probability).

Call (and init) inside ``shard_map`` with the ``ep`` axis bound; outside an
axis context the layer degrades to ep=1 (all experts local), which is the
correctness oracle used in tests.
"""

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.tp import axis_size_or_1, shard_init

EP_AXIS = "ep"


def _hier_dispatch(slots, axis_name, num_slices, cross_label):
    """Expert-major ``(E, C, d)`` slots -> source-major ``(e_local, n*C,
    d)`` via the 2-level alltoall: the reference's split0/concat1 tiled
    exchange reduces to the canonical split0/concat0 form plus a local
    transpose, which then decomposes into slice-local (ICI) and
    cross-slice (DCN, optionally block-scaled) legs
    (``strategies.alltoall_tiered_groups``). Bit-equivalent to the flat
    ``lax.all_to_all`` route UNLESS the cross leg quantizes."""
    from horovod_tpu.parallel.strategies import alltoall_tiered_groups
    n = int(lax.axis_size(axis_name))
    E, C, d = slots.shape
    e_local = E // n
    z = alltoall_tiered_groups(slots, axis_name, num_slices,
                               cross_wire=cross_label)
    return z.reshape(n, e_local, C, d).transpose(1, 0, 2, 3) \
            .reshape(e_local, n * C, d)


def _hier_combine(y, axis_name, num_slices, cross_label):
    """Inverse of :func:`_hier_dispatch`: source-major ``(e_local, n*C,
    d)`` expert outputs back to the expert-major ``(E, C, d)`` layout,
    through the same 2-level exchange."""
    from horovod_tpu.parallel.strategies import alltoall_tiered_groups
    n = int(lax.axis_size(axis_name))
    e_local, nC, d = y.shape
    C = nC // n
    z = y.reshape(e_local, n, C, d).transpose(1, 0, 2, 3) \
         .reshape(n * e_local, C, d)
    return alltoall_tiered_groups(z, axis_name, num_slices,
                                  cross_wire=cross_label)


def _router(x, probs, k: int, capacity: int):
    """Compute dispatch/combine tensors for top-k capacity routing.

    Args:
      x: (T, d) local tokens.  probs: (T, E) router probabilities.
    Returns:
      dispatch (T, E, C) one-hot, combine (T, E, C) gated weights, aux loss.
    """
    T, E = probs.shape
    gate_vals, expert_idx = lax.top_k(probs, k)           # (T, k)
    # Renormalize the selected gates so they sum to 1 per token (top-2 case).
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, -1, keepdims=True), 1e-9)

    dispatch = jnp.zeros((T, E, capacity), probs.dtype)
    combine = jnp.zeros((T, E, capacity), probs.dtype)
    # Process the k choices in priority order; capacity positions are
    # assigned first-come-first-served in token order per expert.
    used = jnp.zeros((E,), jnp.int32)
    for j in range(k):
        e = expert_idx[:, j]                               # (T,)
        onehot = jax.nn.one_hot(e, E, dtype=jnp.int32)     # (T, E)
        # Position of each token within its expert's queue for this choice.
        pos_in_e = jnp.cumsum(onehot, axis=0) - onehot + used[None, :]
        pos = jnp.sum(pos_in_e * onehot, -1)               # (T,)
        keep = pos < capacity
        slot = jax.nn.one_hot(jnp.where(keep, pos, capacity), capacity,
                              dtype=probs.dtype)           # (T, C), 0 if drop
        d_j = jax.nn.one_hot(e, E, dtype=probs.dtype)[..., None] \
            * slot[:, None, :]                             # (T, E, C)
        dispatch = dispatch + d_j
        combine = combine + d_j * gate_vals[:, j, None, None]
        used = used + jnp.sum(onehot * keep[:, None].astype(jnp.int32), 0)

    # Load-balancing loss (Switch Transformer eq. 4): E * sum_e f_e * P_e,
    # computed on the top-1 assignment.
    top1 = jax.nn.one_hot(expert_idx[:, 0], E, dtype=probs.dtype)
    f = jnp.mean(top1, axis=0)
    P = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(f * P)
    return dispatch, combine, aux


class MoEMlp(nn.Module):
    """Expert-parallel MoE feed-forward layer (drop-in for a dense MLP).

    ``num_experts`` is global; each ep shard owns ``num_experts / ep``
    experts' weights. Returns ``(y, aux_loss)``.
    """
    num_experts: int
    hidden_size: int
    intermediate_size: int
    k: int = 1
    capacity_factor: float = 2.0
    dtype: Any = jnp.float32
    axis_name: Optional[str] = EP_AXIS
    # Hierarchical expert dispatch: None = auto (the
    # HOROVOD_HIERARCHICAL_ALLTOALL / a2a strategy registry chain via
    # strategies.a2a_hierarchy_for), True = force when a slice hierarchy
    # exists, False = always flat.
    hierarchical: Optional[bool] = None

    @nn.compact
    def __call__(self, x):
        n = axis_size_or_1(self.axis_name)
        E, d, f = self.num_experts, self.hidden_size, self.intermediate_size
        if E % n != 0:
            raise ValueError(f"num_experts {E} not divisible by ep={n}")
        e_local = E // n
        orig_shape = x.shape
        xt = x.reshape(-1, d)                              # (T, d)
        T = xt.shape[0]
        capacity = max(1, int(self.capacity_factor * self.k * T / E))

        # Router in fp32 for stable softmax.
        logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                          name="router")(xt.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)
        dispatch, combine, aux = _router(xt, probs, self.k, capacity)

        # (T, E, C) x (T, d) -> (E, C, d): expert-major token slots.
        slots = jnp.einsum("tec,td->ecd", dispatch.astype(self.dtype),
                           xt.astype(self.dtype))

        hier = None
        if n > 1:
            from horovod_tpu.parallel.strategies import (
                _record_jit_a2a_flat, a2a_hierarchy_for)
            hier = a2a_hierarchy_for(self.axis_name, self.hierarchical)

        if n > 1 and hier is not None:
            # 2-level route: slice-local a2a (ICI) + cross-slice leg on
            # the per-tier wire (DCN) — expert dispatch pays DCN only for
            # genuinely cross-slice token slots.
            slots = _hier_dispatch(slots, self.axis_name, hier[0], hier[1])
        elif n > 1:
            # Send each expert block to its owner shard; receive all source
            # shards' slots for OUR local experts: (E, C, d) -> (e_local,
            # n*C, d), source-major along the slot axis. Tiled all_to_all is
            # a pure inter-device transpose — no reshapes, clean transpose
            # rule for AD.
            _record_jit_a2a_flat(slots, n)
            slots = lax.all_to_all(slots, self.axis_name, split_axis=0,
                                   concat_axis=1, tiled=True)
        else:
            slots = slots.reshape(e_local, capacity, d)

        # Each ep shard draws its own experts; the router above stays
        # replicated (axis-invariant) under the same init rng.
        w_in = self.param("w_in",
                          shard_init(nn.initializers.lecun_normal(),
                                     self.axis_name),
                          (e_local, d, f), jnp.float32)
        w_out = self.param("w_out",
                           shard_init(nn.initializers.lecun_normal(),
                                      self.axis_name),
                           (e_local, f, d), jnp.float32)
        h = jnp.einsum("ecd,edf->ecf", slots,
                       jnp.asarray(w_in, self.dtype))
        h = nn.gelu(h)
        y = jnp.einsum("ecf,efd->ecd", h, jnp.asarray(w_out, self.dtype))

        if n > 1 and hier is not None:
            y = _hier_combine(y, self.axis_name, hier[0], hier[1])
        elif n > 1:
            # Inverse transpose: source-major slots go back to their source
            # shard, restoring the expert-major (E, C, d) layout.
            _record_jit_a2a_flat(y, n)
            y = lax.all_to_all(y, self.axis_name, split_axis=1,
                               concat_axis=0, tiled=True)

        out = jnp.einsum("tec,ecd->td", combine.astype(self.dtype), y)
        return out.reshape(orig_shape), aux
