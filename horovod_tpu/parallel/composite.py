"""Composite parallelism: dp x pp x tp (x sp, with ep riding dp) in ONE XLA program.

The reference scales one way — data parallelism over whole-replica gradients
(SURVEY.md §2.6). This module is the TPU-native generalization: a 3-D device
mesh ``(dp, pp, tp)`` where

- **dp** carries the batch; replicated-parameter gradients are reduced over
  it automatically by AD (see below),
- **pp** carries pipeline stages (parallel/pp.py ppermute schedule),
- **tp** carries Megatron-sharded attention/MLP weights (parallel/tp.py),
- **ep** rides the dp axis: MoE expert weights are sharded over dp and
  dispatched with all_to_all (parallel/moe.py), DeepSpeed-MoE style,
- **sp** (optional, :func:`build_mesh4d` + ``config.sp_axis="sp"``) shards
  the sequence dim: ring/Ulysses attention inside every block
  (parallel/sequence.py), global RoPE/position offsets, boundary-correct
  next-token labels, and an sp-global token mean in the loss.

Gradient semantics come from ``shard_map``'s varying-manual-axes (VMA) type
system rather than hand-written reductions: parameters enter typed by their
PartitionSpec (replicated leaves axis-invariant, sharded leaves varying), and
the transpose of the implicit invariant->varying promotions inserts exactly
the reductions Megatron/DeepSpeed hand-code — psum over dp for replicated
weights, psum over tp for LayerNorms feeding sharded matmuls, *no* reduction
for tp-sharded or expert weights. The collectives the reference implements as
NCCL calls (reference: horovod/common/ops/nccl_operations.cc) appear here as
AD-inserted XLA collectives scheduled on the ICI torus.
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel.moe import MoEMlp
from horovod_tpu.parallel.pp import pipeline
from horovod_tpu.parallel.tp import TPTransformerBlock

DP_AXIS, PPL_AXIS, TP_AXIS, SP_AXIS = "dp", "pp", "tp", "sp"


def build_mesh3d(dp: int, pp: int, tp: int, devices=None) -> Mesh:
    """A (dp, pp, tp) mesh. Axis order puts tp innermost so tensor-parallel
    psums ride the fastest ICI links, pipeline hops the next, and dp (which
    communicates least often per step) the outermost — the standard layout
    recommendation for TPU pods."""
    if devices is None:
        devices = jax.devices()
    n = dp * pp * tp
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    arr = np.array(devices[:n], dtype=object).reshape(dp, pp, tp)
    return Mesh(arr, (DP_AXIS, PPL_AXIS, TP_AXIS))


def build_mesh4d(dp: int, pp: int, sp: int, tp: int, devices=None) -> Mesh:
    """A (dp, pp, sp, tp) mesh for composite training WITH sequence
    parallelism: tp innermost (per-block psums), then sp (per-block ring /
    all-to-all hops), then pp (per-microbatch hops), dp outermost."""
    if devices is None:
        devices = jax.devices()
    n = dp * pp * sp * tp
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    arr = np.array(devices[:n], dtype=object).reshape(dp, pp, sp, tp)
    return Mesh(arr, (DP_AXIS, PPL_AXIS, SP_AXIS, TP_AXIS))


def _spec_axes(spec):
    axes = []
    for part in spec:
        if part is None:
            continue
        axes.extend(part if isinstance(part, tuple) else (part,))
    return axes


def _pvary_to(tree, specs):
    """Promote each leaf to varying over exactly the axes its spec mentions
    (so values produced inside shard_map type-check against out_specs: e.g.
    LayerNorm ones-init is constant — invariant — but lives in the
    pp-stacked tree, so it must be pvaried over pp)."""

    def f(x, spec):
        vma = getattr(jax.typeof(x), "vma", ())
        for a in _spec_axes(spec):
            if a not in vma:
                x = lax.pcast(x, a, to="varying")
        return x

    return jax.tree_util.tree_map(f, tree, specs)


def _stage_leaf_spec(path_str: str) -> P:
    """PartitionSpec for one pp-stacked transformer-block leaf (leading dim
    is the stacked-layer dim -> 'pp'; tp placement per Megatron layout)."""
    if path_str.endswith("shard/kernel"):
        if "qkv" in path_str or "/in/" in path_str or "gate_up" in path_str:
            return P(PPL_AXIS, None, TP_AXIS)      # column-parallel
        return P(PPL_AXIS, TP_AXIS, None)          # row-parallel
    if path_str.endswith("shard/bias"):
        return P(PPL_AXIS, TP_AXIS)                # column-parallel bias
    return P(PPL_AXIS)                             # LN / row bias: replicated


def _path_str(path) -> str:
    return "/".join(getattr(k, "key", str(k)) for k in path)


@dataclasses.dataclass
class _CompositeLM:
    """Shared machinery for pipelined, tensor-parallel causal-LM training.

    Architecture: embed -> optional shared MoE FFN (residual, experts over
    dp) -> pipeline of TP transformer blocks over pp -> head. Use
    :meth:`init` then :meth:`make_train_step`; the returned step maps
    ``(params, opt_state, ids) -> (params, opt_state, loss)`` with ``ids``
    sharded over dp and all shardings as in :meth:`param_specs`.
    Subclasses implement :meth:`_build_modules` to supply the family's
    embed/head/block (and optional MoE) modules.
    """
    config: Any
    mesh: Mesh
    optimizer: Any
    n_micro: int = 4
    aux_weight: float = 0.01
    # jax.checkpoint each pipelined layer under the gpipe schedule: its
    # AD transpose otherwise stashes every microbatch's every-layer
    # activations (the reason 1F1B exists); remat bounds that at one
    # recompute per layer. The 1F1B schedule recomputes by construction
    # and ignores this flag. None (default) inherits config.remat; an
    # explicit True/False overrides it either way.
    remat: Any = None

    def _build_modules(self):
        raise NotImplementedError

    def __post_init__(self):
        c = self.config
        for ax in (DP_AXIS, PPL_AXIS, TP_AXIS):
            if ax not in self.mesh.shape:
                raise ValueError(f"mesh must have axis {ax!r}")
        self.sp = getattr(c, "sp_axis", None)
        if self.sp is not None and (self.sp != SP_AXIS
                                    or SP_AXIS not in self.mesh.shape):
            # Half-applied sequence parallelism (embed offsetting positions
            # while attention stays local, or an unknown axis name) would
            # silently train wrong — require the 4-D mesh contract.
            raise NotImplementedError(
                f"{type(self).__name__} supports config.sp_axis only as "
                f"{SP_AXIS!r} over a build_mesh4d mesh (got "
                f"sp_axis={self.sp!r}, mesh axes {tuple(self.mesh.shape)})")
        if self.sp is not None and getattr(c, "num_experts", 0):
            # The shared MoE FFN routes/balances over LOCAL token shards
            # only and its aux loss is per-shard — correct sp-aware expert
            # dispatch needs a sequence-gathered router. Refuse loudly
            # rather than surface an opaque trace-time VMA error.
            raise NotImplementedError(
                "sp_axis does not compose with MoE blocks yet "
                "(num_experts > 0): the router and load-balance aux would "
                "see only local token shards")
        # One knob, not two: config.remat (the whole-model flag docs/api.md
        # advertises) arms the trainer too — the composite builds blocks
        # directly, so the model-level nn.remat wrapping never runs here.
        # None means "inherit"; an explicit False stays False.
        if self.remat is None:
            self.remat = bool(getattr(c, "remat", False))
        self.pp = self.mesh.shape[PPL_AXIS]
        if c.num_layers % self.pp != 0:
            raise ValueError(
                f"{c.num_layers} layers not divisible by pp={self.pp}")
        self.layers_per_stage = c.num_layers // self.pp
        self._build_modules()

    # ---- shardings ----

    def param_specs(self, params_shape):
        """Spec tree matching the params pytree (by key path)."""

        def spec(path, _leaf):
            s = _path_str(path)
            if s.startswith("stages/"):
                return _stage_leaf_spec(s)
            if s.startswith("moe/") and ("w_in" in s or "w_out" in s):
                return P(DP_AXIS)                  # experts sharded over dp
            return P()                             # replicated

        return jax.tree_util.tree_map_with_path(spec, params_shape)

    def _ids_spec(self):
        """Token batches: batch dim over dp, sequence dim over sp when
        sequence parallelism is on."""
        return P(DP_AXIS, SP_AXIS) if self.sp else P(DP_AXIS)

    # ---- init ----

    def _init_local(self, rng, ids):
        """Runs inside shard_map: build this rank's local parameter shards."""
        stage = lax.axis_index(PPL_AXIS)
        p_embed = self.embed.init(jax.random.fold_in(rng, 0), ids)["params"]
        x = self.embed.apply({"params": p_embed}, ids)
        params = {"embed": p_embed}
        if self.moe is not None:
            params["moe"] = self.moe.init(
                jax.random.fold_in(rng, 1), x)["params"]
        rng_blocks = jax.random.fold_in(rng, 2)
        per_layer = [
            self.block.init(
                jax.random.fold_in(rng_blocks,
                                   stage * self.layers_per_stage + i),
                x)["params"]
            for i in range(self.layers_per_stage)
        ]
        params["stages"] = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *per_layer)
        params["head"] = self.head.init(jax.random.fold_in(rng, 3),
                                        x)["params"]
        return params

    def init(self, rng, sample_ids):
        """Initialize sharded params + optimizer state on the mesh.

        ``sample_ids``: a (global_batch, seq_len) int32 array (contents
        irrelevant); returns ``(params, opt_state, specs)`` where ``specs``
        is ``(param_specs, opt_specs)``.
        """
        ids_spec = self._ids_spec()

        # Structure-only pass (specs are keyed by tree paths, not shapes);
        # check_vma off since the throwaway out_specs are all-replicated.
        shapes = jax.eval_shape(
            jax.shard_map(self._init_local, mesh=self.mesh,
                          in_specs=(P(), ids_spec), out_specs=P(),
                          check_vma=False),
            rng, sample_ids)
        param_specs = self.param_specs(shapes)

        params = jax.jit(jax.shard_map(
            lambda r, i: _pvary_to(self._init_local(r, i), param_specs),
            mesh=self.mesh, in_specs=(P(), ids_spec),
            out_specs=param_specs))(rng, sample_ids)

        opt_shape = jax.eval_shape(self.optimizer.init, params)
        opt_specs = optax.tree_map_params(
            self.optimizer, lambda _, s: s, opt_shape, param_specs,
            transform_non_params=lambda _: P())
        opt_state = jax.jit(jax.shard_map(
            lambda p: _pvary_to(self.optimizer.init(p), opt_specs),
            mesh=self.mesh, in_specs=(param_specs,), out_specs=opt_specs))(
                params)
        return params, opt_state, (param_specs, opt_specs)

    # ---- training ----

    def _layer_fn(self, p, h):
        return self.block.apply({"params": p}, h)

    def _head_loss(self, head_params, y, ids):
        """Head + next-token loss over one (micro)batch — the ONE loss
        definition both schedules use (mean over equal-sized microbatches
        == the full-batch mean).

        Labels come from :func:`next_token_labels`: under sequence
        parallelism each shard's last position's label is the NEXT shard's
        first token (one ppermute) and the final global position is masked;
        without sp it degrades to the ordinary shift (identical to the
        former ``logits[:, :-1]`` vs ``ids[:, 1:]`` mean). The token mean
        is GLOBAL over sp (psum of sums), so the loss is sp-invariant.
        """
        from horovod_tpu.parallel.sequence import next_token_labels
        from horovod_tpu.parallel.tp import axis_bound
        logits = self.head.apply({"params": head_params}, y)
        labels = next_token_labels(ids, self.sp)   # None -> plain shift
        valid = labels != -100
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), jnp.where(valid, labels, 0))
        num = (ce * valid).sum()
        den = valid.sum().astype(jnp.float32)
        if self.sp and axis_bound(SP_AXIS):
            # psum whenever bound — at sp=1 it's a numeric no-op that
            # still clears the sp-varying type the sharded ids imprinted.
            num = lax.psum(num, SP_AXIS)
            den = lax.psum(den, SP_AXIS)
        return num / den

    def _loss_local(self, params, ids):
        c = self.config
        x = self.embed.apply({"params": params["embed"]}, ids)
        aux = jnp.zeros((), jnp.float32)
        if self.moe is not None:
            h, aux = self.moe.apply({"params": params["moe"]}, x)
            x = x + h
        B, L = ids.shape
        if B % self.n_micro != 0:
            raise ValueError(
                f"local batch {B} not divisible by n_micro={self.n_micro}")
        mbs = x.reshape(self.n_micro, B // self.n_micro, L, c.hidden_size)

        # remat applies HERE only: gpipe's AD transpose stashes every
        # microbatch's every-layer activations. 1F1B recomputes from its
        # own input stash by construction — checkpointing its stage_fwd
        # would just re-run each forward a second time for no memory win.
        layer = (jax.checkpoint(self._layer_fn) if self.remat
                 else self._layer_fn)
        y = pipeline(layer, params["stages"], mbs, PPL_AXIS)
        y = y.reshape(B, L, c.hidden_size)
        loss = self._head_loss(params["head"], y, ids)
        loss = loss + self.aux_weight * aux
        # Mean over the data-parallel axis; AD's transpose of this pmean +
        # the invariant->varying promotions yields the dp gradient allreduce.
        return lax.pmean(loss, DP_AXIS)

    def _grads_1f1b(self, params, ids):
        """Loss + grads via the memory-bounded 1F1B schedule
        (:func:`horovod_tpu.parallel.pp.pipeline_1f1b`): the pipeline
        computes stage/head/input gradients itself (recompute-based
        backward, O(pp) activation stash); the embedding chains through the
        returned input gradients; one explicit dp pmean replaces the dp
        allreduce that AD's transpose of the gpipe path's pmean-loss would
        insert."""
        from horovod_tpu.ops.in_jit import mark_varying
        from horovod_tpu.parallel.pp import pipeline_1f1b
        c = self.config
        if self.moe is not None:
            raise NotImplementedError(
                "schedule='1f1b' does not support MoE blocks yet (the aux "
                "loss and expert grads are outside the pipelined backward)")
        B, L = ids.shape
        if B % self.n_micro != 0:
            raise ValueError(
                f"local batch {B} not divisible by n_micro={self.n_micro}")
        # Mark every parameter dp-varying BEFORE the manual vjps: a
        # dp-invariant parameter consumed by dp-varying data would have its
        # cotangent dp-psum'd inside each vjp (the transpose of the
        # invariant->varying promotion) — an all-reduce per pipeline tick
        # AND a double-count once the explicit dp pmean below runs. Varying
        # params keep cotangents rank-local; the single pmean then takes
        # the true dp mean.
        p_emb, p_stages, p_head = (
            jax.tree_util.tree_map(lambda p: mark_varying(p, DP_AXIS),
                                   params[k])
            for k in ("embed", "stages", "head"))
        x, embed_vjp = jax.vjp(
            lambda pe: self.embed.apply({"params": pe}, ids), p_emb)
        mbs = x.reshape(self.n_micro, B // self.n_micro, L, c.hidden_size)
        tgts = ids.reshape(self.n_micro, B // self.n_micro, L)

        loss, (d_stages, d_head, d_mb) = pipeline_1f1b(
            self._layer_fn, self._head_loss, p_stages, p_head, mbs, tgts,
            PPL_AXIS)
        (d_embed,) = embed_vjp(d_mb.reshape(B, L, c.hidden_size))
        grads = {"embed": d_embed, "stages": d_stages, "head": d_head}
        loss = lax.pmean(loss, DP_AXIS)
        grads = jax.tree_util.tree_map(
            lambda g: lax.pmean(g, DP_AXIS), grads)
        return loss, grads

    def make_train_step(self, specs, donate=True, schedule="gpipe"):
        """Compiled train step. ``schedule``: ``"gpipe"`` differentiates
        the forward pipeline by AD (residuals for every microbatch stay
        live); ``"1f1b"`` uses the interleaved recompute schedule —
        O(pp) activation memory, same gradients."""
        param_specs, opt_specs = specs
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown schedule {schedule!r}")

        def step(params, opt_state, ids):
            if schedule == "1f1b":
                loss, grads = self._grads_1f1b(params, ids)
            else:
                loss, grads = jax.value_and_grad(self._loss_local)(params,
                                                                   ids)
                # With check_vma off, AD inserts NO cross-rank grad sync
                # (psum's un-rewritten transpose seeds every rank with its
                # own local cotangent): each rank's grads are d(local
                # loss). Two explicit reductions make gpipe match 1f1b's
                # hand-built ones: (1) embed/moe grads exist only on the
                # stage-0 pp rank (the pipeline ingests microbatches
                # there), so a pp psum replicates them; (2) every
                # replicated-or-pp-sharded leaf needs the dp mean.
                for k in ("embed", "moe"):
                    if k in grads:
                        grads[k] = jax.tree_util.tree_map(
                            lambda g: lax.psum(g, PPL_AXIS), grads[k])
                grads = jax.tree_util.tree_map(
                    lambda g: lax.pmean(g, DP_AXIS), grads)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        # check_vma off: the updated params/opt state ARE replicated over
        # tp (grads come out of psum'd TP collectives), but the rep
        # checker cannot statically infer that through the blocks' psum/
        # all-gather chains — the same inference gap dp.py documents.
        sharded = jax.shard_map(
            step, mesh=self.mesh,
            in_specs=(param_specs, opt_specs, self._ids_spec()),
            out_specs=(param_specs, opt_specs, P()), check_vma=False)
        return jax.jit(sharded,
                       donate_argnums=(0, 1) if donate else ())


@dataclasses.dataclass
class CompositeGPT(_CompositeLM):
    """Pipelined, tensor-parallel, (optionally) MoE GPT (experts over dp)."""

    def _build_modules(self):
        # Imported here: models.gpt uses parallel.tp/moe, so a module-level
        # import would be circular through the package __init__.
        from horovod_tpu.models.gpt import GPTEmbed, GPTHead
        c = self.config
        self.embed = GPTEmbed(c)
        self.head = GPTHead(c)
        self.block = TPTransformerBlock(
            c.num_heads, c.hidden_size, c.intermediate_size, dtype=c.dtype,
            axis_name=TP_AXIS, causal=True,
            use_flash=getattr(c, "use_flash", False),
            sp_axis=c.sp_axis, sp_impl=getattr(c, "sp_impl", "ring"))
        self.moe = None
        if c.num_experts:
            # moe_hierarchical: None = auto (the
            # HOROVOD_HIERARCHICAL_ALLTOALL / a2a-registry chain) — the
            # composite dp axis routes expert dispatch through the
            # 2-level alltoall whenever a slice hierarchy exists.
            self.moe = MoEMlp(c.num_experts, c.hidden_size,
                              c.intermediate_size, k=c.moe_k,
                              capacity_factor=c.capacity_factor,
                              dtype=c.dtype, axis_name=DP_AXIS,
                              hierarchical=getattr(c, "moe_hierarchical",
                                                   None))


@dataclasses.dataclass
class CompositeLlama(_CompositeLM):
    """Pipelined, tensor-parallel LLaMA: the same dp x pp x tp machinery
    with the family's RMSNorm/RoPE/SwiGLU/GQA blocks (models/llama.py).
    RoPE needs no per-stage position bookkeeping — every block derives
    positions locally from its (replicated-over-pp) token window."""

    def _build_modules(self):
        from horovod_tpu.models.llama import (LlamaBlock, LlamaEmbed,
                                              LlamaHead)
        # The LLaMA blocks read tp_axis from their config (unlike the GPT
        # path, which takes axis_name directly), so the modules get a
        # PRIVATE copy pinned to the composite mesh's tp axis — the
        # caller-visible self.config is never mutated. A conflicting
        # explicit axis is an error, not a silent rewrite.
        if self.config.tp_axis not in (None, TP_AXIS):
            raise ValueError(
                f"config.tp_axis={self.config.tp_axis!r} conflicts with "
                f"the composite mesh's tensor-parallel axis {TP_AXIS!r}; "
                "leave it as None (or set it to the mesh axis)")
        c = dataclasses.replace(self.config, tp_axis=TP_AXIS)
        self.embed = LlamaEmbed(c)
        self.head = LlamaHead(c)
        self.block = LlamaBlock(c)
        self.moe = None
