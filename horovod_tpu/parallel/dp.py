"""Data-parallel training steps over the mesh.

This is the TPU-native realization of "wrap your optimizer, train as usual"
(reference: docs + horovod/torch/optimizer.py DistributedOptimizer usage): a
builder that takes a user loss function and a (Distributed-)optax optimizer
and returns ONE compiled SPMD step, with the whole Horovod pipeline — local
backward, fused gradient allreduce, optimizer update — inside a single XLA
program that the compiler overlaps and schedules on the ICI torus.

Two idioms are supported:

- ``make_train_step`` (explicit SPMD): shard_map over the mesh; parameters are
  replicated; gradients stay device-local until the DistributedOptimizer's
  fused psum — the literal Horovod dataflow, with the fusion buffer replaced
  by :func:`horovod_tpu.optim.fused_allreduce_tree`.
- Plain GSPMD: because parameters enter replicated and the batch enters
  sharded, simply jitting the same loss under ``jax.jit`` with NamedShardings
  lets XLA's partitioner insert the gradient all-reduce itself. That mode
  needs no code from us beyond shardings — it is what the compile-time
  "response cache" means on TPU — so this module only provides the explicit
  variant, which exercises this framework's collectives.
"""

from typing import Any, Callable

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.common.topology import HVD_AXIS
from horovod_tpu.ops import in_jit


class TrainState(struct.PyTreeNode):
    """Minimal train state (params + optimizer state + step counter)."""
    step: Any
    params: Any
    opt_state: Any
    extra: Any = None  # e.g. batch_stats

    @classmethod
    def create(cls, params, optimizer, extra=None):
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=optimizer.init(params), extra=extra)


def make_train_step(loss_fn: Callable, optimizer, mesh, axis_name=HVD_AXIS,
                    batch_spec=None, has_aux=False, donate=True):
    """Build the compiled DP train step.

    ``loss_fn(params, batch)`` computes the LOCAL loss on this chip's batch
    shard. With ``has_aux`` the signature is ``loss_fn(params, batch, extra)
    -> (loss, new_extra)`` where ``extra`` is ``state.extra`` (e.g. BatchNorm
    ``batch_stats``); the returned extra is pmean'd across the axis so stored
    state stays replicated. The returned function maps ``(state, batch) ->
    (state, loss)`` with the batch sharded over ``axis_name`` and everything
    else replicated.

    The optimizer should be a :func:`horovod_tpu.optim.DistributedOptimizer`
    built with the same ``axis_name`` — its fused allreduce is the only
    cross-chip communication in the step.
    """
    if batch_spec is None:
        batch_spec = P(axis_name)

    def local_step(state, batch):
        # Parameters arrive replicated (axis-invariant). Lift them to
        # device-varying so autodiff keeps gradients local — the reduction
        # belongs to the DistributedOptimizer, not to AD's transpose rule.
        params = in_jit.mark_varying(state.params, axis_name)
        opt_state = in_jit.mark_varying(state.opt_state, axis_name)
        extra = in_jit.mark_varying(state.extra, axis_name)

        if has_aux:
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch, extra)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            aux = None
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = lax.pmean(loss, axis_name)
        if has_aux:
            # Per-shard aux (e.g. local batch-norm statistics) diverges across
            # devices; average it so the stored state is truly replicated —
            # the cross-replica running-stats sync SyncBatchNorm does inline.
            aux = jax.tree_util.tree_map(
                lambda a: lax.pmean(a, axis_name)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, aux)
        new_state = state.replace(step=state.step + 1, params=params,
                                  opt_state=opt_state,
                                  extra=aux if has_aux else state.extra)
        return new_state, loss

    # check_vma=False: the updated params/opt_state are device-varying *types*
    # but replicated *values* (every chip applies the same psum'd gradient),
    # which the static VMA analysis cannot prove. test_parallel asserts the
    # bitwise cross-device equality this relies on.
    sharded = jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), batch_spec),
        out_specs=(P(), P()), check_vma=False)
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_eval_step(eval_fn: Callable, mesh, axis_name=HVD_AXIS,
                   batch_spec=None):
    """Compiled eval step: per-shard metrics are pmean'd — the MetricAverage
    semantics (reference: _keras/callbacks.py:62 MetricAverageCallback)."""
    if batch_spec is None:
        batch_spec = P(axis_name)

    def local_eval(params, batch):
        metrics = eval_fn(in_jit.mark_varying(params, axis_name), batch)
        return jax.tree_util.tree_map(
            lambda m: lax.pmean(m, axis_name), metrics)

    sharded = jax.shard_map(local_eval, mesh=mesh,
                            in_specs=(P(), batch_spec), out_specs=P(),
                            check_vma=False)
    return jax.jit(sharded)


def make_zero_train_step(loss_fn: Callable, tx, mesh, axis_name=HVD_AXIS,
                         batch_spec=None, has_aux=False, donate=True,
                         average=True):
    """DP train step with ZeRO-1 optimizer-state sharding over the DP axis.

    Beyond reference parity (the reference replicates optimizer state on
    every worker, like every Horovod job): gradients are REDUCE-SCATTERED
    instead of all-reduced, each chip updates only its 1/n shard of the
    (flattened) parameters with its 1/n shard of the optimizer state, and
    the updated shards are all-gathered back — the same bytes on the wire
    as an allreduce (RS + AG is how ring allreduce decomposes), but adamw
    moment memory drops from 2×params to 2×params/n per chip.

    ``tx`` is a plain optax transform (NOT DistributedOptimizer — the
    reduction is fused into the scatter here). Transforms must be
    elementwise over the flat parameter vector (sgd/momentum/adam/adamw/
    rmsprop are; global-norm clipping is not, since a shard-local norm is
    not the global norm).

    Use ``ZeroTrainState.create(params, tx, mesh)`` for the matching state;
    ``state.opt_state`` holds flat shard-shaped leaves.
    """
    if batch_spec is None:
        batch_spec = P(axis_name)
    n = int(np.prod([mesh.shape[a] for a in
                     (axis_name if isinstance(axis_name, tuple)
                      else (axis_name,))]))

    def local_step(state, batch):
        params = in_jit.mark_varying(state.params, axis_name)
        opt_state = in_jit.mark_varying(state.opt_state, axis_name)
        extra = in_jit.mark_varying(state.extra, axis_name)

        if has_aux:
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch, extra)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            aux = None

        flat_g, _ = jax.flatten_util.ravel_pytree(grads)
        flat_p, unravel = jax.flatten_util.ravel_pytree(params)
        pad = (-flat_g.size) % n
        flat_g = jnp.pad(flat_g, (0, pad))
        # Fused reduce+shard: this chip receives the reduced shard
        # [idx*L : (idx+1)*L] of the gradient.
        g_shard = lax.psum_scatter(flat_g, axis_name, scatter_dimension=0,
                                   tiled=True)
        if average:
            g_shard = g_shard / n
        shard_len = flat_g.size // n
        idx = lax.axis_index(axis_name)
        p_shard = lax.dynamic_slice(jnp.pad(flat_p, (0, pad)),
                                    (idx * shard_len,), (shard_len,))
        updates, opt_state = tx.update(g_shard, opt_state, p_shard)
        p_shard = optax.apply_updates(p_shard, updates)
        flat_new = lax.all_gather(p_shard, axis_name, tiled=True)
        params = unravel(flat_new[:flat_p.size])

        loss = lax.pmean(loss, axis_name)
        if has_aux:
            aux = jax.tree_util.tree_map(
                lambda a: lax.pmean(a, axis_name)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, aux)
        return state.replace(step=state.step + 1, params=params,
                             opt_state=opt_state,
                             extra=aux if has_aux else state.extra), loss

    # opt_state shards stay device-varying across steps: their specs carry
    # the axis so each chip keeps only its 1/n moments. Vector leaves
    # (moments) shard; scalar leaves (step counts) replicate.
    opt_struct = jax.eval_shape(tx.init,
                                jax.ShapeDtypeStruct((n,), jnp.float32))
    opt_specs = jax.tree_util.tree_map(
        lambda x: P(axis_name) if getattr(x, "ndim", 0) >= 1 else P(),
        opt_struct)
    state_specs = ZeroTrainState(step=P(), params=P(), opt_state=opt_specs,
                                 extra=P())
    sharded = jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(state_specs, batch_spec),
        out_specs=(state_specs, P()), check_vma=False)
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


class ZeroTrainState(TrainState):
    """TrainState whose opt_state moment leaves are flat 1/n shards."""

    @classmethod
    def create(cls, params, tx, mesh, axis_name=HVD_AXIS, extra=None):
        n = int(np.prod([mesh.shape[a] for a in
                         (axis_name if isinstance(axis_name, tuple)
                          else (axis_name,))]))
        flat, _ = jax.flatten_util.ravel_pytree(params)
        shard_len = (flat.size + (-flat.size) % n) // n
        # GLOBAL moment arrays of n * shard_len: the sharded specs of
        # make_zero_train_step lay 1/n on each chip, so per-chip memory is
        # moments/n — the ZeRO-1 saving.
        opt_state = tx.init(jnp.zeros((n * shard_len,), flat.dtype))
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=opt_state, extra=extra)
