"""Data-parallel training steps over the mesh.

This is the TPU-native realization of "wrap your optimizer, train as usual"
(reference: docs + horovod/torch/optimizer.py DistributedOptimizer usage): a
builder that takes a user loss function and a (Distributed-)optax optimizer
and returns ONE compiled SPMD step, with the whole Horovod pipeline — local
backward, fused gradient allreduce, optimizer update — inside a single XLA
program that the compiler overlaps and schedules on the ICI torus.

Two idioms are supported:

- ``make_train_step`` (explicit SPMD): shard_map over the mesh; parameters are
  replicated; gradients stay device-local until the DistributedOptimizer's
  fused psum — the literal Horovod dataflow, with the fusion buffer replaced
  by :func:`horovod_tpu.optim.fused_allreduce_tree`.
- Plain GSPMD: because parameters enter replicated and the batch enters
  sharded, simply jitting the same loss under ``jax.jit`` with NamedShardings
  lets XLA's partitioner insert the gradient all-reduce itself. That mode
  needs no code from us beyond shardings — it is what the compile-time
  "response cache" means on TPU — so this module only provides the explicit
  variant, which exercises this framework's collectives.
"""

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.common.topology import HVD_AXIS
from horovod_tpu.ops import in_jit


class TrainState(struct.PyTreeNode):
    """Minimal train state (params + optimizer state + step counter)."""
    step: Any
    params: Any
    opt_state: Any
    extra: Any = None  # e.g. batch_stats

    @classmethod
    def create(cls, params, optimizer, extra=None):
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=optimizer.init(params), extra=extra)


def make_train_step(loss_fn: Callable, optimizer, mesh, axis_name=HVD_AXIS,
                    batch_spec=None, has_aux=False, donate=True):
    """Build the compiled DP train step.

    ``loss_fn(params, batch)`` computes the LOCAL loss on this chip's batch
    shard. With ``has_aux`` the signature is ``loss_fn(params, batch, extra)
    -> (loss, new_extra)`` where ``extra`` is ``state.extra`` (e.g. BatchNorm
    ``batch_stats``); the returned extra is pmean'd across the axis so stored
    state stays replicated. The returned function maps ``(state, batch) ->
    (state, loss)`` with the batch sharded over ``axis_name`` and everything
    else replicated.

    The optimizer should be a :func:`horovod_tpu.optim.DistributedOptimizer`
    built with the same ``axis_name`` — its fused allreduce is the only
    cross-chip communication in the step.
    """
    if batch_spec is None:
        batch_spec = P(axis_name)

    def local_step(state, batch):
        # Parameters arrive replicated (axis-invariant). Lift them to
        # device-varying so autodiff keeps gradients local — the reduction
        # belongs to the DistributedOptimizer, not to AD's transpose rule.
        params = in_jit.mark_varying(state.params, axis_name)
        opt_state = in_jit.mark_varying(state.opt_state, axis_name)
        extra = in_jit.mark_varying(state.extra, axis_name)

        if has_aux:
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch, extra)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            aux = None
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = lax.pmean(loss, axis_name)
        if has_aux:
            # Per-shard aux (e.g. local batch-norm statistics) diverges across
            # devices; average it so the stored state is truly replicated —
            # the cross-replica running-stats sync SyncBatchNorm does inline.
            aux = jax.tree_util.tree_map(
                lambda a: lax.pmean(a, axis_name)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, aux)
        new_state = state.replace(step=state.step + 1, params=params,
                                  opt_state=opt_state,
                                  extra=aux if has_aux else state.extra)
        return new_state, loss

    # check_vma=False: the updated params/opt_state are device-varying *types*
    # but replicated *values* (every chip applies the same psum'd gradient),
    # which the static VMA analysis cannot prove. test_parallel asserts the
    # bitwise cross-device equality this relies on.
    sharded = jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), batch_spec),
        out_specs=(P(), P()), check_vma=False)
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_eval_step(eval_fn: Callable, mesh, axis_name=HVD_AXIS,
                   batch_spec=None):
    """Compiled eval step: per-shard metrics are pmean'd — the MetricAverage
    semantics (reference: _keras/callbacks.py:62 MetricAverageCallback)."""
    if batch_spec is None:
        batch_spec = P(axis_name)

    def local_eval(params, batch):
        metrics = eval_fn(in_jit.mark_varying(params, axis_name), batch)
        return jax.tree_util.tree_map(
            lambda m: lax.pmean(m, axis_name), metrics)

    sharded = jax.shard_map(local_eval, mesh=mesh,
                            in_specs=(P(), batch_spec), out_specs=P(),
                            check_vma=False)
    return jax.jit(sharded)
