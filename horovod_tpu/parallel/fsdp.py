"""FSDP / ZeRO-3: fully-sharded parameters via GSPMD.

The ladder of optimizer/parameter sharding this framework offers:

- DP (``make_train_step``): params + optimizer state replicated; gradients
  fused-allreduced (the reference's only mode).
- ZeRO-1 (``make_zero_train_step``): optimizer MOMENTS sharded 1/n; params
  replicated; reduce-scatter + all-gather per step (dp.py).
- FSDP / ZeRO-3 (this module): PARAMS, gradients, and optimizer state all
  sharded 1/n per chip. Beyond reference parity — Horovod has no parameter
  sharding at all (SURVEY.md §2.6).

TPU-first design: no hand-written gather/scatter schedule. Parameters are
laid out with per-leaf ``NamedSharding``s (largest divisible dim split over
the mesh axis) and the train step is a plain ``jax.jit`` — XLA's GSPMD
partitioner inserts the all-gathers before each layer's compute and
reduce-scatters the gradients, then overlaps them with compute on the ICI
torus. That schedule is exactly what hand-rolled FSDP implementations
approximate; on TPU the compiler already owns it (SURVEY.md §5.8 stance:
let XLA fuse — don't hand-schedule what the compiler already does).

Memory per chip: params + grads + moments all drop by n× (vs n× for
moments only under ZeRO-1); the cost is an all-gather of each layer's
weights per step, which GSPMD overlaps with the previous layer's compute.
"""

import functools
from typing import Callable

import jax
import numpy as np
import optax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from horovod_tpu.common.topology import HVD_AXIS


def fsdp_spec(shape, n, min_size=16384, axis_name=HVD_AXIS):
    """PartitionSpec sharding the largest n-divisible dim of ``shape``.

    Leaves smaller than ``min_size`` elements stay replicated: sharding a
    LayerNorm bias saves nothing and costs a gather.
    """
    if int(np.prod(shape)) < min_size:
        return P()
    dims = [(d, i) for i, d in enumerate(shape) if d % n == 0]
    if not dims:
        return P()
    _, best = max(dims, key=lambda t: (t[0], -t[1]))  # ties -> first dim
    spec = [None] * len(shape)
    spec[best] = axis_name
    return P(*spec)


def fsdp_shardings(tree, mesh, axis_name=HVD_AXIS, min_size=16384):
    """Per-leaf NamedShardings for a parameter pytree."""
    n = mesh.shape[axis_name]

    def leaf(x):
        shape = getattr(x, "shape", ())
        return NamedSharding(mesh, fsdp_spec(shape, n, min_size, axis_name))

    return jax.tree.map(leaf, tree)


def _place(x, sharding):
    """Place host data with ``sharding``; under a multi-process mesh the
    sharding spans non-addressable devices, where device_put can't be used
    — build the global array from the host-replicated value instead."""
    if jax.process_count() > 1:
        arr = np.asarray(x)
        return jax.make_array_from_callback(arr.shape, sharding,
                                            lambda idx: arr[idx])
    return jax.device_put(x, sharding)


def shard_params(params, mesh, axis_name=HVD_AXIS, min_size=16384):
    """Lay a parameter pytree out FSDP-sharded on the mesh (params must be
    host-identical across processes, e.g. seeded or broadcast)."""
    sh = fsdp_shardings(params, mesh, axis_name, min_size)
    return jax.tree.map(_place, params, sh)


def make_fsdp_train_step(loss_fn: Callable, tx, mesh, axis_name=HVD_AXIS,
                         donate=True, min_size=16384):
    """Build an FSDP training step.

    ``loss_fn(params, batch)`` is written on GLOBAL arrays (plain jnp — no
    shard_map, no axis names): under jit the batch arrives sharded on its
    leading dim, params arrive FSDP-sharded, and GSPMD inserts the
    all-gather / reduce-scatter schedule. Returns
    ``(init_fn, step_fn)``:

    - ``init_fn(params) -> (params, opt_state)`` — places params sharded
      and initializes the optimizer state with matching (propagated)
      shardings.
    - ``step_fn(params, opt_state, batch) -> (params, opt_state, loss)``
      — one fused step; params/opt_state stay sharded across calls.
    """
    n = mesh.shape[axis_name]

    def init_fn(params):
        params = shard_params(params, mesh, axis_name, min_size)
        # Moment-like leaves share their param's shape, hence its sharding;
        # counts/scalars come out replicated (below min_size).
        opt_state = jax.jit(
            tx.init,
            out_shardings=fsdp_shardings(
                jax.eval_shape(tx.init, params), mesh, axis_name,
                min_size))(params)
        return params, opt_state

    @functools.partial(jax.jit, donate_argnums=(0, 1) if donate else ())
    def step_fn(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return init_fn, step_fn


def shard_batch(batch, mesh, axis_name=HVD_AXIS):
    """Place a host batch with its leading dim split over the mesh axis."""

    def leaf(x):
        spec = [axis_name] + [None] * (np.ndim(x) - 1)
        return _place(x, NamedSharding(mesh, P(*spec)))

    return jax.tree.map(leaf, batch)
