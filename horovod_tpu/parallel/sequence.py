"""Sequence/context parallelism: ring attention and Ulysses.

The reference has no attention code (SURVEY.md §5.7: Horovod operates below the
model level) but exposes exactly the primitives sequence parallelism composes
from — AllToAll with splits (Ulysses' head scatter, reference:
collective_operations.h:199-268) and point-to-point rings. This module builds
both schemes as first-class capabilities of the TPU framework:

- **Ulysses** (all-to-all SP): tokens sharded over the ``sp`` axis are
  exchanged for heads via one AllToAll, every chip computes full-sequence
  attention for its head subset, and a second AllToAll restores the token
  sharding. Communication: 2 all-to-alls of the activations, ICI-friendly.
- **Ring attention**: K/V blocks rotate around the ring via
  ``lax.ppermute`` while each chip accumulates flash-style online-softmax
  partial results for its resident Q block. Communication overlaps compute;
  memory stays O(L/n) per chip — the long-context workhorse.

Both are numerically exact (fp32 accumulators, online softmax) and verified
against full attention in tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

SP_AXIS = "hvd"  # default: sequence parallelism over the global mesh axis


def _attention_weights(q, k, scale, mask=None):
    # q: (B, Lq, H, D), k: (B, Lk, H, D) -> scores (B, H, Lq, Lk) in fp32
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    return s


def local_attention(q, k, v, causal=False):
    """Plain softmax attention on local (unsharded) tensors; the correctness
    oracle for the parallel schemes."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    mask = None
    if causal:
        Lq, Lk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((Lq, Lk), bool), k=Lk - Lq)[None, None]
    s = _attention_weights(q, k, scale, mask)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)) \
        .astype(q.dtype)


def _axis_bound(axis_name):
    """True when ``axis_name`` is bound in the current trace (i.e. we're
    inside shard_map over it). Lets the attention schemes run un-sharded —
    e.g. during flax ``Module.init`` outside the mesh context — by degrading
    to local attention."""
    try:
        lax.axis_size(axis_name)
        return True
    except NameError:
        return False


def ulysses_attention(q, k, v, axis_name=SP_AXIS, causal=False):
    """DeepSpeed-Ulysses-style sequence parallelism.

    Inputs are sequence-sharded: local shapes (B, L/n, H, D) with H divisible
    by n. Two AllToAlls re-shard tokens<->heads around a full-sequence local
    attention. Outside the axis context (e.g. parameter init) this computes
    plain local attention.
    """
    if not _axis_bound(axis_name):
        return local_attention(q, k, v, causal=causal)
    n = lax.axis_size(axis_name)
    if q.shape[2] % n != 0:
        raise ValueError(f"num heads {q.shape[2]} not divisible by sp={n}")

    def scatter_heads(t):
        # (B, L/n, H, D) -> (B, L, H/n, D)
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def gather_heads(t):
        # (B, L, H/n, D) -> (B, L/n, H, D)
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    oh = local_attention(qh, kh, vh, causal=causal)
    return gather_heads(oh)


def ring_attention(q, k, v, axis_name=SP_AXIS, causal=False):
    """Ring attention with online softmax (Liu et al.; blockwise parallel
    transformers): exact attention over the full sequence with O(L/n) memory
    and K/V rotating over ICI.

    Local shapes (B, L/n, H, D); every chip owns the Q block for its sequence
    shard and receives each K/V block exactly once. Outside the axis context
    (e.g. parameter init) this computes plain local attention.
    """
    if not _axis_bound(axis_name):
        return local_attention(q, k, v, causal=causal)
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, Lq, H, D = q.shape
    scale = 1.0 / np.sqrt(D)
    qf = q.astype(jnp.float32)

    # global positions of my Q rows (for causal masking)
    q_pos = idx * Lq + jnp.arange(Lq)  # (Lq,)

    perm = [(i, (i - 1) % n) for i in range(n)]  # block s lives at rank+s

    def step(s, carry):
        o, m, l, ks, vs = carry
        src = (idx + s) % n
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf,
                            ks.astype(jnp.float32)) * scale
        if causal:
            k_pos = src * Lq + jnp.arange(Lq)
            mask = q_pos[:, None] >= k_pos[None, :]        # (Lq, Lk)
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        blk_max = jnp.max(scores, axis=-1)                  # (B, H, Lq)
        m_new = jnp.maximum(m, blk_max)
        # guard fully-masked rows (m_new = -inf): keep them at zero weight
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        p = jnp.exp(scores - safe_m[..., None])
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] \
            + jnp.einsum("bhqk,bkhd->bhqd", p, vs.astype(jnp.float32))
        ks = lax.ppermute(ks, axis_name, perm)
        vs = lax.ppermute(vs, axis_name, perm)
        return o_new, m_new, l_new, ks, vs

    from horovod_tpu.ops.in_jit import mark_varying
    o = jnp.zeros((B, H, Lq, D), jnp.float32)
    m = jnp.full((B, H, Lq), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, Lq), jnp.float32)
    # constants start axis-invariant; the loop carry must be device-varying
    o, m, l = mark_varying((o, m, l), axis_name)
    o, m, l, _, _ = lax.fori_loop(0, n, step, (o, m, l, k, v),
                                  unroll=True)
    out = o / jnp.maximum(l, 1e-30)[..., None]              # (B, H, Lq, D)
    return jnp.moveaxis(out, 1, 2).astype(q.dtype)          # (B, Lq, H, D)
