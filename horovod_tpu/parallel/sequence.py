"""Sequence/context parallelism: ring attention and Ulysses.

The reference has no attention code (SURVEY.md §5.7: Horovod operates below the
model level) but exposes exactly the primitives sequence parallelism composes
from — AllToAll with splits (Ulysses' head scatter, reference:
collective_operations.h:199-268) and point-to-point rings. This module builds
both schemes as first-class capabilities of the TPU framework:

- **Ulysses** (all-to-all SP): tokens sharded over the ``sp`` axis are
  exchanged for heads via one AllToAll, every chip computes full-sequence
  attention for its head subset, and a second AllToAll restores the token
  sharding. Communication: 2 all-to-alls of the activations, ICI-friendly.
- **Ring attention**: K/V blocks rotate around the ring via
  ``lax.ppermute`` while each chip accumulates flash-style online-softmax
  partial results for its resident Q block. Communication overlaps compute;
  memory stays O(L/n) per chip — the long-context workhorse.

Both are numerically exact (fp32 accumulators, online softmax) and verified
against full attention in tests.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

SP_AXIS = "hvd"  # default: sequence parallelism over the global mesh axis


def _attention_weights(q, k, scale, mask=None):
    # q: (B, Lq, H, D), k: (B, Lk, H, D) -> scores (B, H, Lq, Lk) in fp32
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    return s


def local_attention(q, k, v, causal=False):
    """Plain softmax attention on local (unsharded) tensors; the correctness
    oracle for the parallel schemes. ``k``/``v`` may carry fewer (grouped)
    heads than ``q`` — they are broadcast here, locally."""
    k, v = broadcast_kv_heads(q, k, v)
    scale = 1.0 / np.sqrt(q.shape[-1])
    mask = None
    if causal:
        Lq, Lk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((Lq, Lk), bool), k=Lk - Lq)[None, None]
    s = _attention_weights(q, k, scale, mask)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)) \
        .astype(q.dtype)


def broadcast_kv_heads(q, k, v):
    """Repeat grouped K/V heads up to the query head count (no-op for MHA).
    The sp schemes call this as LATE as possible — after the collective
    exchange — so ring/Ulysses traffic keeps GQA's 1/g bandwidth saving."""
    if q.shape[2] % k.shape[2]:
        raise ValueError(f"kv heads {k.shape[2]} must divide query heads "
                         f"{q.shape[2]}")
    g = q.shape[2] // k.shape[2]
    if g == 1:
        return k, v
    return jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2)


def _axis_bound(axis_name):
    """True when ``axis_name`` is bound in the current trace (i.e. we're
    inside shard_map over it). Lets the attention schemes run un-sharded —
    e.g. during flax ``Module.init`` outside the mesh context — by degrading
    to local attention. (Shared predicate: parallel/tp.py axis_bound.)"""
    from horovod_tpu.parallel.tp import axis_bound
    return axis_bound(axis_name)


def ulysses_attention(q, k, v, axis_name=SP_AXIS, causal=False,
                      use_flash=False):
    """DeepSpeed-Ulysses-style sequence parallelism.

    Inputs are sequence-sharded: local shapes (B, L/n, H, D) with H divisible
    by n. Two AllToAlls re-shard tokens<->heads around a full-sequence local
    attention. Outside the axis context (e.g. parameter init) this computes
    plain local attention.

    ``use_flash=True`` runs the per-head-shard full-sequence attention
    through the Pallas flash kernels (flash_attention handles its own
    non-TPU fallback), cutting the O(L²) score materialization.

    ``k``/``v`` may carry fewer (grouped) heads than ``q``: when the kv
    head count divides the sp degree they ride the all-to-alls NARROW
    (1/g the exchange bytes) and are broadcast only on the local,
    post-exchange side; otherwise they are broadcast before the exchange.
    """
    if use_flash:
        from horovod_tpu.ops.pallas import flash_attention as attn
    else:
        attn = local_attention
    if not _axis_bound(axis_name):
        return attn(q, k, v, causal=causal)
    n = lax.axis_size(axis_name)
    if q.shape[2] % n != 0:
        raise ValueError(f"num heads {q.shape[2]} not divisible by sp={n}")
    if k.shape[2] % n != 0:
        # grouped heads don't split over sp — broadcast first (correct,
        # but loses the narrow exchange; ring SP keeps it at any g)
        k, v = broadcast_kv_heads(q, k, v)

    def scatter_heads(t):
        # (B, L/n, H, D) -> (B, L, H/n, D)
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def gather_heads(t):
        # (B, L, H/n, D) -> (B, L/n, H, D)
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    # flash streams grouped K/V natively; the jnp oracle broadcasts —
    # either way the broadcast (if any) happens AFTER the all-to-all.
    oh = attn(qh, kh, vh, causal=causal)
    return gather_heads(oh)


def next_token_labels(ids, axis_name=SP_AXIS, pad_id=-100):
    """Per-shard next-token labels under sequence sharding.

    With tokens sharded over ``axis_name`` each shard's LAST position's
    label is the FIRST token of the next shard — a shift inside the local
    slice silently trains the boundary position on the wrong target. This
    fetches the boundary token with one ``ppermute``; the final global
    position gets ``pad_id`` (mask it out of the loss, e.g. optax's
    ``where=labels != pad_id``). Outside the axis context this is the
    ordinary global shift.

    ``ids``: (B, L_local) int tokens. Returns same-shape labels.
    ``axis_name=None`` (tokens not sequence-sharded) always takes the
    plain-shift path — even when some OTHER mesh axis named like the
    default happens to be bound.
    """
    pad = jnp.full_like(ids[:, :1], pad_id)
    if axis_name is None or not _axis_bound(axis_name):
        return jnp.concatenate([ids[:, 1:], pad], axis=1)
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    # rank i receives rank i+1's first token (reverse ring direction).
    first_next = lax.ppermute(ids[:, :1], axis_name,
                              [((i + 1) % n, i) for i in range(n)])
    boundary = jnp.where(idx == n - 1, pad, first_next)
    return jnp.concatenate([ids[:, 1:], boundary], axis=1)


def _block_attn_fwd(q3, ks, vs, causal, scale, blocks, heads=None,
                    kv_heads=None):
    """(o_b, lse_b) for one ring hop on (BH, L, D) blocks: the Pallas flash
    kernel on TPU, the shared jnp block oracle elsewhere (the interpreter
    can't run the kernel under a VMA-checked shard_map). With
    ``kv_heads < heads`` the ks/vs blocks stay NARROW (B*KV rows): the
    kernel streams them via its GQA index maps; the oracle broadcasts
    locally — either way the ring traffic carried only the narrow blocks."""
    from horovod_tpu.ops.pallas.flash_attention import (_fa_forward,
                                                        _interpret,
                                                        _jnp_block_fwd,
                                                        gqa_repeat3)
    gqa = heads is not None and kv_heads is not None and heads != kv_heads
    if blocks is not None and not _interpret():
        return _fa_forward(q3, ks, vs, causal, scale, *blocks,
                           heads=heads if gqa else None,
                           kv_heads=kv_heads if gqa else None)
    if gqa:
        b = q3.shape[0] // heads
        g = heads // kv_heads
        ks = gqa_repeat3(ks, b, kv_heads, g)
        vs = gqa_repeat3(vs, b, kv_heads, g)
    return _jnp_block_fwd(q3, ks, vs, causal, scale)


def _block_attn_bwd(q3, ks, vs, out3, lse, do3, causal, scale, blocks,
                    heads=None, kv_heads=None):
    """Per-hop (dq, dk, dv) against the GLOBAL softmax: p = exp(s - lse)
    with the ring-wide logsumexp, so summing hop contributions reproduces
    the exact full-attention gradient. Under GQA the returned dk/dv are
    group-summed back onto the NARROW kv rows, so the gradient
    accumulators rotate narrow too."""
    from horovod_tpu.ops.pallas.flash_attention import (_fa_backward,
                                                        _interpret,
                                                        _jnp_block_bwd,
                                                        gqa_fold3,
                                                        gqa_repeat3)
    gqa = heads is not None and kv_heads is not None and heads != kv_heads
    if gqa:
        # The backward kernel is MHA-shaped (like _flash_bwd): broadcast
        # the narrow hop blocks LOCALLY, group-sum dk/dv back. The ring
        # still only ever carried the narrow blocks.
        b = q3.shape[0] // heads
        g = heads // kv_heads
        ks = gqa_repeat3(ks, b, kv_heads, g)
        vs = gqa_repeat3(vs, b, kv_heads, g)
    if blocks is not None and not _interpret():
        dq, dk, dv = _fa_backward(q3, ks, vs, out3, lse, do3, causal,
                                  scale, *blocks)
    else:
        dq, dk, dv = _jnp_block_bwd(q3, ks, vs, out3, lse, do3, causal,
                                    scale)
    if gqa:
        dk = gqa_fold3(dk, b, kv_heads, g)
        dv = gqa_fold3(dv, b, kv_heads, g)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash(q3, k3, v3, causal, axis_name, scale, blocks, heads=None,
                kv_heads=None):
    out, _ = _ring_flash_fwd(q3, k3, v3, causal, axis_name, scale, blocks,
                             heads, kv_heads)
    return out


def _ring_flash_fwd(q3, k3, v3, causal, axis_name, scale, blocks,
                    heads=None, kv_heads=None):
    """Ring forward: rotate K/V blocks, run the flash block kernel per hop,
    combine hop outputs by their logsumexp weights (exact). Under GQA
    (``kv_heads < heads``) the rotated k3/v3 carry only B*kv_heads rows —
    1/g the ppermute bytes."""
    from horovod_tpu.ops.in_jit import mark_varying_like
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    bh, L, d = q3.shape
    perm = [(i, (i - 1) % n) for i in range(n)]

    m = jnp.full((bh, L), -1e30, jnp.float32)
    norm = jnp.zeros((bh, L), jnp.float32)
    acc = jnp.zeros((bh, L, d), jnp.float32)
    # carry varying over sp AND any axes the data is sharded over (dp/pp
    # on a composite mesh)
    m, norm, acc = mark_varying_like((m, norm, acc), q3, axis_name)
    ks, vs = k3, v3
    for s in range(n):
        src = (idx + s) % n
        if causal and s > 0:
            # Blocks from ranks ahead of this one are entirely above the
            # causal diagonal: skip their kernels outright (the per-device
            # scalar predicate branches locally; no collective inside).
            o_b, lse_b = lax.cond(
                src < idx,
                lambda ks=ks, vs=vs: _block_attn_fwd(
                    q3, ks, vs, False, scale, blocks, heads, kv_heads),
                lambda: (q3 * 0,
                         q3[..., 0].astype(jnp.float32) * 0 - 1e30))
            visible = (src < idx).astype(jnp.float32)       # whole block
        else:
            o_b, lse_b = _block_attn_fwd(q3, ks, vs, causal and s == 0,
                                         scale, blocks, heads, kv_heads)
            visible = jnp.float32(1.0)
        m_new = jnp.maximum(m, jnp.where(visible > 0, lse_b, -1e30))
        # m_new stays -1e30 only while NO block is visible yet; exp(0)=1
        # corrections are harmless there because norm/acc are still zero.
        corr = jnp.exp(m - m_new)
        w = visible * jnp.exp(jnp.minimum(lse_b - m_new, 0.0))
        norm = norm * corr + w
        acc = acc * corr[..., None] + w[..., None] * o_b.astype(jnp.float32)
        m = m_new
        if s != n - 1:
            ks = lax.ppermute(ks, axis_name, perm)
            vs = lax.ppermute(vs, axis_name, perm)
    norm_safe = jnp.maximum(norm, 1e-30)
    out = (acc / norm_safe[..., None]).astype(q3.dtype)
    lse_tot = m + jnp.log(norm_safe)
    return out, (q3, k3, v3, out, lse_tot)


def _ring_flash_bwd(causal, axis_name, scale, blocks, heads, kv_heads, res,
                    do3):
    """Ring backward: rotate K/V (and their gradient accumulators) around
    the ring again; each hop's dk/dv lands home after n-1 rotations. Under
    GQA the rotated blocks AND accumulators stay narrow (B*kv_heads rows)."""
    q3, k3, v3, out3, lse_tot = res
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = [(i, (i - 1) % n) for i in range(n)]
    from horovod_tpu.ops.in_jit import mark_varying_like

    dq = jnp.zeros(q3.shape, jnp.float32)
    dk_rot = jnp.zeros(k3.shape, jnp.float32)
    dv_rot = jnp.zeros(v3.shape, jnp.float32)
    dq, dk_rot, dv_rot = mark_varying_like((dq, dk_rot, dv_rot), q3,
                                           axis_name)
    # Fully-masked rows (possible only without a visible diagonal) carry
    # lse ~ -1e30; clamp so exp(s - lse) cannot overflow — their hop
    # contributions are already zeroed by the visibility gate.
    lse_safe = jnp.where(lse_tot > -1e29, lse_tot, 0.0)
    ks, vs = k3, v3
    for s in range(n):
        src = (idx + s) % n
        if causal and s > 0:
            dq_b, dk_b, dv_b = lax.cond(
                src < idx,
                lambda ks=ks, vs=vs: _block_attn_bwd(
                    q3, ks, vs, out3, lse_safe, do3, False, scale, blocks,
                    heads, kv_heads),
                lambda ks=ks, vs=vs: (q3 * 0, ks * 0, vs * 0))
            visible = (src < idx).astype(jnp.float32)
        else:
            dq_b, dk_b, dv_b = _block_attn_bwd(
                q3, ks, vs, out3, lse_safe, do3, causal and s == 0, scale,
                blocks, heads, kv_heads)
            visible = jnp.float32(1.0)
        dq = dq + visible * dq_b.astype(jnp.float32)
        dk_rot = dk_rot + visible * dk_b.astype(jnp.float32)
        dv_rot = dv_rot + visible * dv_b.astype(jnp.float32)
        if s != n - 1:
            ks = lax.ppermute(ks, axis_name, perm)
            vs = lax.ppermute(vs, axis_name, perm)
            dk_rot = lax.ppermute(dk_rot, axis_name, perm)
            dv_rot = lax.ppermute(dv_rot, axis_name, perm)
    # After n-1 hops the accumulators sit one rotation short of home.
    dk_home = lax.ppermute(dk_rot, axis_name, perm)
    dv_home = lax.ppermute(dv_rot, axis_name, perm)
    return (dq.astype(q3.dtype), dk_home.astype(k3.dtype),
            dv_home.astype(v3.dtype))


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(q, k, v, axis_name=SP_AXIS, causal=False,
                   use_flash=False):
    """Ring attention with online softmax (Liu et al.; blockwise parallel
    transformers): exact attention over the full sequence with O(L/n) memory
    and K/V rotating over ICI.

    Local shapes (B, L/n, H, D); every chip owns the Q block for its sequence
    shard and receives each K/V block exactly once. Outside the axis context
    (e.g. parameter init) this computes plain local attention.

    ``use_flash=True`` runs each hop's block attention through the Pallas
    flash kernels (forward AND backward) and combines hops by their
    logsumexp weights — same exact math, MXU-tiled and O(block) VMEM. On
    non-TPU backends the hops use an equivalent jnp block kernel, so the
    path is testable on the virtual CPU mesh.

    ``k``/``v`` may carry fewer (grouped) heads than ``q``: the narrow
    tensors rotate the ring directly (1/g the ppermute bytes AND 1/g the
    resident K/V memory) and are expanded only at the hop kernels — the
    flash path streams them without materializing the broadcast at all.
    """
    if q.shape[2] % k.shape[2]:
        raise ValueError(f"kv heads {k.shape[2]} must divide query heads "
                         f"{q.shape[2]}")
    if not _axis_bound(axis_name):
        if use_flash:
            from horovod_tpu.ops.pallas import flash_attention as _flash_fn
            return _flash_fn(q, k, v, causal=causal)
        return local_attention(q, k, v, causal=causal)
    B, Lq, H, D = q.shape
    KV = k.shape[2]
    if use_flash:
        import importlib
        fa = importlib.import_module(
            "horovod_tpu.ops.pallas.flash_attention")
        bq, bk = fa._pick_block(Lq), fa._pick_block(k.shape[1])
        blocks = (bq, bk) if (bq and bk and fa.pltpu is not None) else None
        scale = 1.0 / np.sqrt(D)

        def to3(t):
            h = t.shape[2]
            return jnp.moveaxis(t, 2, 1).reshape(t.shape[0] * h,
                                                 t.shape[1], D)

        o3 = _ring_flash(to3(q), to3(k), to3(v), causal, axis_name, scale,
                         blocks, H if KV != H else None,
                         KV if KV != H else None)
        return jnp.moveaxis(o3.reshape(B, H, Lq, D), 1, 2)
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    g = H // KV
    scale = 1.0 / np.sqrt(D)
    qf = q.astype(jnp.float32)

    # global positions of my Q rows (for causal masking)
    q_pos = idx * Lq + jnp.arange(Lq)  # (Lq,)

    perm = [(i, (i - 1) % n) for i in range(n)]  # block s lives at rank+s

    def step(s, carry):
        o, m, l, ks, vs = carry
        src = (idx + s) % n
        # narrow (grouped) K/V rotate the ring; broadcast only here,
        # locally, for the einsum
        ksf, vsf = (jnp.repeat(t, g, axis=2) if g > 1 else t
                    for t in (ks, vs))
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf,
                            ksf.astype(jnp.float32)) * scale
        if causal:
            k_pos = src * Lq + jnp.arange(Lq)
            mask = q_pos[:, None] >= k_pos[None, :]        # (Lq, Lk)
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        blk_max = jnp.max(scores, axis=-1)                  # (B, H, Lq)
        m_new = jnp.maximum(m, blk_max)
        # guard fully-masked rows (m_new = -inf): keep them at zero weight
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        p = jnp.exp(scores - safe_m[..., None])
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None] \
            + jnp.einsum("bhqk,bkhd->bhqd", p, vsf.astype(jnp.float32))
        ks = lax.ppermute(ks, axis_name, perm)
        vs = lax.ppermute(vs, axis_name, perm)
        return o_new, m_new, l_new, ks, vs

    from horovod_tpu.ops.in_jit import mark_varying_like
    o = jnp.zeros((B, H, Lq, D), jnp.float32)
    m = jnp.full((B, H, Lq), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, Lq), jnp.float32)
    # constants start axis-invariant; the loop carry must be device-varying
    # over sp and any other axes the data is sharded over
    o, m, l = mark_varying_like((o, m, l), q, axis_name)
    o, m, l, _, _ = lax.fori_loop(0, n, step, (o, m, l, k, v),
                                  unroll=True)
    out = o / jnp.maximum(l, 1e-30)[..., None]              # (B, H, Lq, D)
    return jnp.moveaxis(out, 1, 2).astype(q.dtype)          # (B, Lq, H, D)
