"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

New capability relative to the reference (which is data-parallel only,
SURVEY.md §2.6); built TPU-first: the stage-to-stage handoff is a
``lax.ppermute`` hop to the ICI neighbour, the schedule is a ``lax.scan``
with static trip count (so the whole pipeline is ONE compiled XLA program,
reverse-mode differentiable — ppermute's transpose is the reverse ppermute),
and per-stage compute is a ``lax.scan`` over that stage's stacked layer
parameters.

SPMD formulation: every rank runs the same program; rank p of the ``pp``
axis holds the parameters of stage p (leaves stacked ``(layers_per_stage,
...)``, the global array being ``(pp * layers_per_stage, ...)`` sharded on
the leading dim). Microbatches are replicated over the pp axis; stage 0
selects its scheduled microbatch by index, the last stage's outputs are
broadcast back with one masked psum.
"""

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

PP_AXIS = "pp"

# Cross-device communication primitives: their presence in a sub-program
# means it cannot run under a cond whose predicate varies over the mesh
# (subset participation deadlocks the collective rendezvous). Substring
# match: JAX names variants like psum_invariant / all_gather_invariant.
_COLLECTIVE_STEMS = ("psum", "pmin", "pmax", "ppermute", "pgather",
                     "all_gather", "all_to_all", "reduce_scatter")


def _jaxpr_has_collectives(jaxpr) -> bool:
    """Recursively scan a jaxpr (and sub-jaxprs in scan/cond/pjit params)
    for collective primitives."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if any(stem in name for stem in _COLLECTIVE_STEMS):
            return True
        for v in eqn.params.values():
            for item in (v if isinstance(v, (list, tuple)) else (v,)):
                sub = getattr(item, "jaxpr", item)
                if hasattr(sub, "eqns") and _jaxpr_has_collectives(sub):
                    return True
    return False


import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _bcast_from_last(x, axis_name):
    """Replicate the LAST pp rank's value to every rank: one masked psum.

    custom_vjp because the psum's AD transpose over-delivers here: each
    rank's (identical, replicated) loss cotangent re-enters through the
    transpose, so the last stage receives the cotangent summed n_stages
    times — gradients scale by the pp world size (observed as exactly-8x
    grads on the 8-stage CPU tier). The backward hands the cotangent to
    the last stage exactly once; other ranks' buffers never reach the
    loss in forward (masked to zero), so their cotangent is zero."""
    n = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    return lax.psum(jnp.where(stage == n - 1, x, jnp.zeros_like(x)),
                    axis_name)


def _bcast_from_last_fwd(x, axis_name):
    return _bcast_from_last(x, axis_name), None


def _bcast_from_last_bwd(axis_name, _res, ct):
    from horovod_tpu.ops.in_jit import mark_varying

    n = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    return (mark_varying(
        jnp.where(stage == n - 1, ct, jnp.zeros_like(ct)), axis_name),)


_bcast_from_last.defvjp(_bcast_from_last_fwd, _bcast_from_last_bwd)


def stage_apply(layer_fn: Callable, stage_params, x):
    """Apply this stage's stacked layers sequentially: ``layer_fn(p_i, x)``
    scanned over the leading (layer) dim of ``stage_params``."""

    def body(h, p):
        return layer_fn(p, h), None

    out, _ = lax.scan(body, x, stage_params)
    return out


def pipeline(layer_fn: Callable, stage_params, microbatches,
             axis_name: str = PP_AXIS):
    """Run ``microbatches`` through the pipeline; returns stacked outputs.

    Args:
      layer_fn: ``(layer_params, x) -> y`` for ONE layer (same pytree
        structure per layer). Shapes of x and y must match (a transformer
        block), since the inter-stage buffer is shape-invariant.
      stage_params: this rank's stage parameters, leaves stacked
        ``(layers_per_stage, ...)``.
      microbatches: ``(n_micro, mb, ...)`` — identical (replicated) on every
        pp rank.
      axis_name: the pipeline mesh axis.

    Returns:
      ``(n_micro, mb, ...)`` outputs of the last stage, replicated on every
      pp rank (one masked psum).

    Schedule: tick t computes microbatch ``t - stage`` at ``stage`` (valid
    when ``0 <= t - stage < n_micro``), then shifts activations one hop
    forward; ``n_micro + n_stages - 1`` ticks drain the pipeline. Bubble
    fraction is ``(S-1)/(T+S-1)`` — pick ``n_micro >= 4*S`` for real runs.
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    from horovod_tpu.ops.in_jit import mark_varying

    state = mark_varying(jnp.zeros_like(microbatches[0]), axis_name)
    outputs = mark_varying(jnp.zeros_like(microbatches), axis_name)

    def tick(carry, t):
        state, outputs = carry
        mb_idx = t - stage
        # Stage 0 ingests its scheduled microbatch; later stages consume the
        # activation received on the previous hop.
        x_in = jnp.where(stage == 0,
                         microbatches[jnp.clip(mb_idx, 0, n_micro - 1)],
                         state)
        y = stage_apply(layer_fn, stage_params, x_in)
        # The last stage retires microbatch mb_idx at this tick.
        retire = (stage == n_stages - 1) & (mb_idx >= 0) & (mb_idx < n_micro)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(retire, y, outputs[jnp.clip(mb_idx, 0,
                                                           n_micro - 1)]),
            jnp.clip(mb_idx, 0, n_micro - 1), 0)
        state = lax.ppermute(y, axis_name, fwd)
        return (state, outputs), None

    (_, outputs), _ = lax.scan(tick, (state, outputs),
                               jnp.arange(n_micro + n_stages - 1))
    # Broadcast the last stage's outputs to every rank (grad-correct: a
    # plain masked psum's transpose would deliver n_stages copies of the
    # replicated loss cotangent — see _bcast_from_last).
    return _bcast_from_last(outputs, axis_name)


def pipeline_1f1b(layer_fn: Callable, head_loss_fn: Callable, stage_params,
                  head_params, microbatches, targets,
                  axis_name: str = PP_AXIS):
    """One-forward-one-backward pipeline TRAINING step in a single scan.

    :func:`pipeline` is forward-only and differentiated by AD: its transpose
    runs all backwards after all forwards, so residuals for every microbatch
    (and every layer) stay live — activation memory O(n_micro). This
    schedule interleaves each microbatch's backward into the same tick
    lattice (the 1F1B idea, Megatron-style) and recomputes the stage forward
    inside the backward tick, so only the stage INPUTS of in-flight
    microbatches are stashed: activation memory O(n_stages), independent of
    n_micro.

    Schedule (stage s of S, microbatch m of M): forward at tick ``s + m``
    (exactly :func:`pipeline`'s schedule), backward at tick
    ``2(S-1) - s + m`` — each stage's backward of m lands one tick after
    stage s+1's, so gradient hops ride the reverse ring with no extra
    barriers; the last stage turns a microbatch around (head loss + vjp) in
    the tick its forward completes. ``M + 2S - 2`` ticks total; at most
    ``2(S-1-s)+1 <= 2S-1`` microbatches in flight per stage.

    Args:
      layer_fn: ``(layer_params, x) -> y``, one shape-invariant layer.
      head_loss_fn: ``(head_params, y, target) -> scalar`` — the last
        stage's head + loss for ONE microbatch. Traced on every rank
        (masked off the non-last stages).
      stage_params: this rank's stage parameters (stacked leading layer dim).
      head_params: replicated head/loss parameters.
      microbatches: ``(n_micro, mb, ...)`` inputs, replicated over pp.
      targets: ``(n_micro, ...)`` per-microbatch targets, replicated.
      axis_name: the pipeline mesh axis.

    Returns:
      ``(loss, (d_stage_params, d_head_params, d_microbatches))``: the mean
      microbatch loss (replicated), this rank's stage-parameter gradients,
      the head gradients and input gradients (both replicated — chain
      ``d_microbatches`` through your embedding's vjp), all scaled for the
      MEAN loss over microbatches.
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    fwd_ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    rev_ring = [(i, (i - 1) % n_stages) for i in range(n_stages)]
    ssize = min(n_micro, 2 * n_stages - 1)      # stash slots (in-flight max)

    from horovod_tpu.ops.in_jit import mark_varying

    # Head params arrive replicated (axis-UNVARYING). The vjp transpose of
    # an unvarying->varying broadcast is a psum, so differentiating the head
    # directly would silently sum every stage's (mostly garbage) head
    # cotangent each tick. Marking them varying keeps each rank's head
    # gradient local; the masked psum at the end then selects the last
    # stage's real accumulation.
    head_params = jax.tree_util.tree_map(
        lambda p: mark_varying(p, axis_name), head_params)

    def stage_fwd(p, x):
        return stage_apply(layer_fn, p, x)

    # Scan carries must enter with the exact varying-axes type their
    # outputs will have. Activation/gradient-flow carries match the DATA's
    # axes (dp/sp-sharded batches) plus pp. Parameter-gradient carries
    # match each PARAM leaf's own axes — a vjp cotangent is varying exactly
    # over its primal's axes (axes the data varies over but the param does
    # not get psummed inside the transpose), so marking them with the data
    # axes would over-promote (e.g. sp) and break the out_specs. The loss
    # carry takes head_loss_fn's actual output type (it may reduce axes
    # internally, e.g. an sp-global token mean).
    data_axes = (set(getattr(jax.typeof(microbatches), "vma", ()))
                 | set(getattr(jax.typeof(targets), "vma", ()))
                 | {axis_name})

    def mv(x, axes):
        for ax in axes:
            x = mark_varying(x, ax)
        return x

    def grad_carry(params):
        return jax.tree_util.tree_map(
            lambda p: mv(jnp.zeros_like(p),
                         getattr(jax.typeof(p), "vma", ())), params)

    loss_aval = jax.eval_shape(head_loss_fn, head_params, microbatches[0],
                               targets[0])
    loss_axes = set(getattr(loss_aval, "vma", ())) | {axis_name}

    # Can the head loss + vjp be GATED to the last stage? Only when it
    # contains no collectives: a psum/ppermute inside a cond whose
    # predicate varies over pp would be entered by a subset of the
    # devices XLA's channel rendezvous expects and deadlock the step
    # (observed on the CPU thunk runtime; the TPU runtime has the same
    # subset-participation hazard). A collective-free head (the common
    # case — e.g. a local token-mean cross-entropy) skips the full-vocab
    # matmul + vjp on the S-1 non-last stages every tick.
    try:
        head_gateable = not _jaxpr_has_collectives(jax.make_jaxpr(
            head_loss_fn)(head_params, microbatches[0], targets[0]).jaxpr)
    except Exception:
        head_gateable = False            # conservative: trace quirks -> run

    zeros_mb = mv(jnp.zeros_like(microbatches[0]), data_axes)
    carry0 = dict(
        fwd_state=zeros_mb,                       # activation hop buffer
        bwd_state=zeros_mb,                       # gradient hop buffer
        stash=mv(jnp.zeros((ssize,) + microbatches.shape[1:],
                           microbatches.dtype), data_axes),
        d_mb=mv(jnp.zeros_like(microbatches), data_axes),
        d_params=grad_carry(stage_params),
        d_head=grad_carry(head_params),
        loss_sum=mv(jnp.zeros((), jnp.float32), loss_axes),
    )

    def tick(c, t):
        m_f = t - stage                               # fwd microbatch index
        m_b = t - (2 * (n_stages - 1) - stage)        # bwd microbatch index
        valid_f = (m_f >= 0) & (m_f < n_micro)
        valid_b = (m_b >= 0) & (m_b < n_micro)
        mi_f = jnp.clip(m_f, 0, n_micro - 1)
        mi_b = jnp.clip(m_b, 0, n_micro - 1)

        # The forward and backward slots are two data-independent collective
        # chains (fwd: tp psums -> activation ppermute; bwd: tp psums ->
        # gradient ppermute). optimization_barrier ties each slot to the
        # previous one (prior-tick bwd hop -> fwd slot -> fwd hop -> bwd
        # slot -> bwd hop) so every device issues collectives in one order.
        # The XLA CPU backend ADDITIONALLY needs
        # --xla_cpu_enable_concurrency_optimized_scheduler=false — its
        # optimized thunk scheduler can still reorder collective entry and
        # deadlock the rendezvous (docs/troubleshooting.md); TPU compiles a
        # total collective order, where the barriers cost nothing.
        bwd_in = c["bwd_state"]

        # --- forward slot ---
        x_in = jnp.where(stage == 0, microbatches[mi_f], c["fwd_state"])
        x_in, bwd_in = lax.optimization_barrier((x_in, bwd_in))
        y = stage_fwd(stage_params, x_in)
        stash = lax.dynamic_update_index_in_dim(
            c["stash"],
            jnp.where(valid_f, x_in, c["stash"][mi_f % ssize]),
            mi_f % ssize, 0)
        fwd_next = lax.ppermute(y, axis_name, fwd_ring)    # activation hop

        # --- last stage turns the microbatch around this tick ---
        # Only the last stage's result is ever consumed, and at a 32k-128k
        # vocab the head matmul + its vjp dominate a tick — when the head
        # is collective-free (head_gateable), gate it behind a cond so the
        # other S-1 stages skip the work entirely.
        def head_branch():
            loss_t, head_pull = jax.vjp(head_loss_fn, head_params, y,
                                        targets[mi_b])
            # The cotangent's varying-axes type must match loss_t's
            # exactly — on a composite mesh the loss is varying over more
            # than the pp axis (e.g. dp-sharded batches).
            ct = jnp.asarray(1.0 / n_micro, loss_t.dtype)
            for ax in getattr(jax.typeof(loss_t), "vma", ()):
                ct = mark_varying(ct, ax)
            dhead_t, dy_head, _ = head_pull(ct)
            return loss_t, dhead_t, dy_head

        def skip_branch():
            # Zeros with branch-matching varying-axes types: the loss as
            # eval_shape'd, head cotangents varying like their primals,
            # dy like the activation.
            zl = mv(jnp.zeros(loss_aval.shape, loss_aval.dtype),
                    getattr(loss_aval, "vma", ()))
            return zl, grad_carry(head_params), mv(jnp.zeros_like(y),
                                                   data_axes)

        if head_gateable:
            loss_t, dhead_t, dy_head = lax.cond(
                stage == n_stages - 1, head_branch, skip_branch)
        else:
            # head_loss_fn contains collectives (e.g. an sp-global token
            # mean): every stage must enter them in lockstep, so the head
            # runs unmasked everywhere and the on_head masks below select
            # the last stage's real result.
            loss_t, dhead_t, dy_head = head_branch()

        # --- backward slot (recompute the stage forward from the stash) ---
        dy = jnp.where(stage == n_stages - 1, dy_head, bwd_in)
        x_b = stash[mi_b % ssize]
        x_b, dy, fwd_next = lax.optimization_barrier((x_b, dy, fwd_next))
        _, stage_pull = jax.vjp(stage_fwd, stage_params, x_b)
        dparams_t, dx = stage_pull(dy)

        on_head = valid_b & (stage == n_stages - 1)
        c_next = dict(
            fwd_state=fwd_next,
            bwd_state=lax.ppermute(dx, axis_name, rev_ring),
            stash=stash,
            d_mb=lax.dynamic_update_index_in_dim(
                c["d_mb"],
                jnp.where(valid_b & (stage == 0), dx, c["d_mb"][mi_b]),
                mi_b, 0),
            d_params=jax.tree_util.tree_map(
                lambda acc, g: acc + jnp.where(valid_b, g,
                                               jnp.zeros_like(g)),
                c["d_params"], dparams_t),
            d_head=jax.tree_util.tree_map(
                lambda acc, g: acc + jnp.where(on_head, g,
                                               jnp.zeros_like(g)),
                c["d_head"], dhead_t),
            loss_sum=c["loss_sum"]
            + jnp.where(on_head, loss_t.astype(jnp.float32), 0.0) / n_micro,
        )
        return c_next, None

    c, _ = lax.scan(tick, carry0, jnp.arange(n_micro + 2 * n_stages - 2))
    last = stage == n_stages - 1
    loss = lax.psum(jnp.where(last, c["loss_sum"], 0.0), axis_name)
    d_head = jax.tree_util.tree_map(
        lambda g: lax.psum(jnp.where(last, g, jnp.zeros_like(g)), axis_name),
        c["d_head"])
    d_mb = lax.psum(jnp.where(stage == 0, c["d_mb"], 0.0), axis_name)
    return loss, (c["d_params"], d_head, d_mb)


def split_microbatches(batch, n_micro: int):
    """``(B, ...) -> (n_micro, B / n_micro, ...)``."""

    def split(x):
        if x.shape[0] % n_micro != 0:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by n_micro={n_micro}")
        return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])

    return jax.tree_util.tree_map(split, batch)


def stack_stage_params(per_layer_params, n_stages: int, axis_name=PP_AXIS):
    """Host-side helper: stack a list of per-layer param pytrees into the
    global ``(n_layers, ...)`` arrays to shard over the pp axis (spec
    ``P('pp')`` on the leading dim)."""
    n_layers = len(per_layer_params)
    if n_layers % n_stages != 0:
        raise ValueError(
            f"{n_layers} layers not divisible by {n_stages} stages")
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_layer_params)
