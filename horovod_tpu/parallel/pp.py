"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

New capability relative to the reference (which is data-parallel only,
SURVEY.md §2.6); built TPU-first: the stage-to-stage handoff is a
``lax.ppermute`` hop to the ICI neighbour, the schedule is a ``lax.scan``
with static trip count (so the whole pipeline is ONE compiled XLA program,
reverse-mode differentiable — ppermute's transpose is the reverse ppermute),
and per-stage compute is a ``lax.scan`` over that stage's stacked layer
parameters.

SPMD formulation: every rank runs the same program; rank p of the ``pp``
axis holds the parameters of stage p (leaves stacked ``(layers_per_stage,
...)``, the global array being ``(pp * layers_per_stage, ...)`` sharded on
the leading dim). Microbatches are replicated over the pp axis; stage 0
selects its scheduled microbatch by index, the last stage's outputs are
broadcast back with one masked psum.
"""

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

PP_AXIS = "pp"


def stage_apply(layer_fn: Callable, stage_params, x):
    """Apply this stage's stacked layers sequentially: ``layer_fn(p_i, x)``
    scanned over the leading (layer) dim of ``stage_params``."""

    def body(h, p):
        return layer_fn(p, h), None

    out, _ = lax.scan(body, x, stage_params)
    return out


def pipeline(layer_fn: Callable, stage_params, microbatches,
             axis_name: str = PP_AXIS):
    """Run ``microbatches`` through the pipeline; returns stacked outputs.

    Args:
      layer_fn: ``(layer_params, x) -> y`` for ONE layer (same pytree
        structure per layer). Shapes of x and y must match (a transformer
        block), since the inter-stage buffer is shape-invariant.
      stage_params: this rank's stage parameters, leaves stacked
        ``(layers_per_stage, ...)``.
      microbatches: ``(n_micro, mb, ...)`` — identical (replicated) on every
        pp rank.
      axis_name: the pipeline mesh axis.

    Returns:
      ``(n_micro, mb, ...)`` outputs of the last stage, replicated on every
      pp rank (one masked psum).

    Schedule: tick t computes microbatch ``t - stage`` at ``stage`` (valid
    when ``0 <= t - stage < n_micro``), then shifts activations one hop
    forward; ``n_micro + n_stages - 1`` ticks drain the pipeline. Bubble
    fraction is ``(S-1)/(T+S-1)`` — pick ``n_micro >= 4*S`` for real runs.
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    from horovod_tpu.ops.in_jit import mark_varying

    state = mark_varying(jnp.zeros_like(microbatches[0]), axis_name)
    outputs = mark_varying(jnp.zeros_like(microbatches), axis_name)

    def tick(carry, t):
        state, outputs = carry
        mb_idx = t - stage
        # Stage 0 ingests its scheduled microbatch; later stages consume the
        # activation received on the previous hop.
        x_in = jnp.where(stage == 0,
                         microbatches[jnp.clip(mb_idx, 0, n_micro - 1)],
                         state)
        y = stage_apply(layer_fn, stage_params, x_in)
        # The last stage retires microbatch mb_idx at this tick.
        retire = (stage == n_stages - 1) & (mb_idx >= 0) & (mb_idx < n_micro)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(retire, y, outputs[jnp.clip(mb_idx, 0,
                                                           n_micro - 1)]),
            jnp.clip(mb_idx, 0, n_micro - 1), 0)
        state = lax.ppermute(y, axis_name, fwd)
        return (state, outputs), None

    (_, outputs), _ = lax.scan(tick, (state, outputs),
                               jnp.arange(n_micro + n_stages - 1))
    # Broadcast the last stage's outputs to every rank.
    return lax.psum(jnp.where(stage == n_stages - 1, outputs, 0.0), axis_name)


def split_microbatches(batch, n_micro: int):
    """``(B, ...) -> (n_micro, B / n_micro, ...)``."""

    def split(x):
        if x.shape[0] % n_micro != 0:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by n_micro={n_micro}")
        return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])

    return jax.tree_util.tree_map(split, batch)


def stack_stage_params(per_layer_params, n_stages: int, axis_name=PP_AXIS):
    """Host-side helper: stack a list of per-layer param pytrees into the
    global ``(n_layers, ...)`` arrays to shard over the pp axis (spec
    ``P('pp')`` on the leading dim)."""
    n_layers = len(per_layer_params)
    if n_layers % n_stages != 0:
        raise ValueError(
            f"{n_layers} layers not divisible by {n_stages} stages")
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_layer_params)
