"""Multi-level allreduce strategies for 2-D (cross × local) meshes.

Reference algorithms being mapped:

- ``NCCLHierarchicalAllreduce`` (reference: horovod/common/ops/
  nccl_operations.cc ~200-580, knob HOROVOD_HIERARCHICAL_ALLREDUCE
  common.h:130): node-local ReduceScatter → cross-node allreduce of the
  scattered shards → node-local Allgather.
- ``NCCLTorusAllreduce`` (fork-specific; reference: nccl_operations.cc:606-843,
  knob HOROVOD_TORUS_ALLREDUCE common.h:132): the same 2-level scheme with the
  cross-node leg running per-local-rank on separate communicators — i.e. each
  local shard's cross-node reduction proceeds in parallel.

TPU-native mapping: ``local`` = chips within a slice (ICI), ``cross`` = slices
(DCN). ``psum_scatter(local) → psum(cross) → all_gather(local)`` expresses
exactly the torus schedule, and XLA runs each cross-slice shard reduction in
parallel — the property the fork's custom NCCL code buys — while moving only
1/local_size of the bytes over the slow cross link.
"""

import jax.numpy as jnp
from jax import lax

from horovod_tpu.common.topology import CROSS_AXIS, LOCAL_AXIS


def allreduce_torus(x, cross_axis=CROSS_AXIS, local_axis=LOCAL_AXIS,
                    average=False, flatten=True, cross_compression=None):
    """2-level allreduce: ICI reduce-scatter, DCN shard allreduce, ICI
    all-gather. Bit-equivalent to a flat allreduce (UNLESS
    ``cross_compression`` is set); bandwidth-optimal when the cross link is
    the bottleneck.

    ``x`` is this chip's local value. Requires ``x.size`` divisible by the
    local axis size when ``flatten`` (pads otherwise).

    ``cross_compression="int8"`` (lossy) quantizes ONLY the cross (DCN) leg
    via :func:`allreduce_int8` — the ICI reduce-scatter/all-gather stay
    full precision while the slow inter-slice hop moves ~2 bytes/element
    (the EQuARX deployment shape: quantize where bandwidth hurts). Shards
    too small to amortize the int8 exchange's cross_n×1024 block padding
    fall back to the exact psum (compressing them would move MORE bytes).
    """
    local_n = lax.axis_size(local_axis)
    orig_shape = x.shape
    flat = x.reshape(-1)
    pad = (-flat.size) % local_n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shard = lax.psum_scatter(flat, local_axis, scatter_dimension=0, tiled=True)
    cross_n = lax.axis_size(cross_axis)
    if cross_compression == "int8" and shard.size >= cross_n * 1024:
        shard = allreduce_int8(shard, axis_name=cross_axis)
    elif cross_compression == "int8":
        # Below one 1024-block per cross rank the padded int8 exchange
        # would move MORE bytes than the exact fp32 psum — stay exact.
        shard = lax.psum(shard, cross_axis)
    elif cross_compression is not None:
        raise ValueError(
            f"unknown cross_compression {cross_compression!r}; "
            "use None or 'int8'")
    else:
        shard = lax.psum(shard, cross_axis)
    full = lax.all_gather(shard, local_axis, axis=0, tiled=True)
    if pad:
        full = full[:-pad]
    out = full.reshape(orig_shape)
    if average:
        n = local_n * lax.axis_size(cross_axis)
        out = out / jnp.asarray(n, out.dtype)
    return out


def allgather_hierarchical(x, cross_axis=CROSS_AXIS, local_axis=LOCAL_AXIS):
    """2-level allgather: gather within each host's chips first, then one
    cross-host gather of whole host-blocks (reference:
    MPIHierarchicalAllgather, mpi_operations.cc — node-local gather then
    cross-node exchange of node blocks; knob
    HOROVOD_HIERARCHICAL_ALLGATHER common.h:131).

    ``x`` is this chip's local value; returns ``(n_total, *x.shape)`` in
    global rank-major order (rank = cross * local_size + local, matching
    :func:`horovod_tpu.common.topology.build_topology`'s layout) — the
    same value a flat all_gather produces, but the cross link moves one
    contiguous block per HOST instead of interleaving per-chip messages
    (the cross axis of mesh2d is the host boundary, like the reference's
    node boundary)."""
    loc = lax.all_gather(x, local_axis, axis=0, tiled=False)
    full = lax.all_gather(loc, cross_axis, axis=0, tiled=False)
    return full.reshape((-1,) + x.shape)


def allreduce_hierarchical(x, cross_axis=CROSS_AXIS, local_axis=LOCAL_AXIS,
                           average=False):
    """Hierarchical 2-phase allreduce: full local reduce then cross reduce.
    Moves the whole buffer on the cross link (unlike torus) but needs no
    divisibility; matches NCCLHierarchicalAllreduce's structure."""
    out = lax.psum(lax.psum(x, local_axis), cross_axis)
    if average:
        n = lax.axis_size(local_axis) * lax.axis_size(cross_axis)
        out = out / jnp.asarray(n, out.dtype)
    return out


# THE symmetric int8 quantizer lives in the wire tier now (one definition
# for the wire exchange AND the quantized KV cache); re-exported here for
# the existing import sites.
from horovod_tpu.ops.wire import symmetric_int8_quantize  # noqa: F401,E402


def _record_jit_wire(x, axis_name, wire):
    """Trace-time wire accounting for the in-jit entry points: the shapes
    are static during tracing, so this records once per compiled program
    (documented in wire_compression_events_total's help text), never on
    the device hot path."""
    try:
        from horovod_tpu.metrics import instruments as hvd_metrics
        from horovod_tpu.ops import wire as _wire
        n = int(lax.axis_size(axis_name))
        hvd_metrics.record_wire(
            "jit", wire, _wire.exchange_wire_bytes(int(x.size), n),
            compressed=True)
    except Exception:  # noqa: BLE001 — accounting must never break a trace
        pass


def scaled_allreduce_int8(x, axis_name="hvd", average=False,
                          prescale_factor=1.0, postscale_factor=1.0):
    """:func:`allreduce_int8` with the reference's pre/postscale applied
    around the exchange — the ONE wrapper both the jit fused path
    (optim/optimizer.py) and the eager fusion runtime (ops/fusion.py)
    call, so the scaling order can never diverge between them."""
    from horovod_tpu.ops import wire as _wire
    _record_jit_wire(x, axis_name, "int8")
    out, _ = _wire.block_scaled_allreduce(
        x, axis_name=axis_name, wire="int8", average=average,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor)
    return out


def allreduce_int8(x, axis_name="hvd", average=False):
    """Quantized allreduce: int8 on the wire, fp32 accumulation.

    The EQuARX-style two-phase exchange (arXiv:2506.17615) — int8 both
    legs, one fp32 scale per 1024-element block, reduce in fp32 — now
    implemented once in :func:`horovod_tpu.ops.wire.block_scaled_allreduce`
    (which also offers the fp8 variant and the error-feedback form whose
    residual the caller threads through its own state). This entry point
    is the stable in-jit API; it keeps the exchange exact-shape/dtype
    preserving and records trace-time wire accounting.
    """
    from horovod_tpu.ops import wire as _wire
    _record_jit_wire(x, axis_name, "int8")
    out, _ = _wire.block_scaled_allreduce(
        x, axis_name=axis_name, wire="int8", average=average)
    return out


def allreduce_quantized(x, axis_name="hvd", wire_dtype="int8", average=False,
                        prescale_factor=1.0, postscale_factor=1.0,
                        residual=None):
    """Generalized in-jit quantized allreduce: ``wire_dtype`` selects the
    block format — ``int8``, or ``fp8`` where this jax build has the
    dtype (an fp8-less build falls back to the int8 blocks: this function
    promises a QUANTIZED wire, and the accounting records the format
    actually used). With ``residual`` (an fp32 buffer of ``x``'s flat
    size threaded through the caller's optimizer state) returns ``(out,
    new_residual)`` — the in-jit error-feedback form; the caller MUST
    zero the residual on elastic reset (hvdlint HVP109 flags
    configurations that look like they won't). Without it returns just
    ``out``."""
    from horovod_tpu.ops import wire as _wire
    label = _wire.quantized_label(wire_dtype) or "int8"
    _record_jit_wire(x, axis_name, label)
    out, new_res = _wire.block_scaled_allreduce(
        x, residual=residual, axis_name=axis_name, wire=label,
        average=average, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor)
    return out if residual is None else (out, new_res)
