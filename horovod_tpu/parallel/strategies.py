"""Multi-level allreduce strategies for 2-D (cross × local) meshes.

Reference algorithms being mapped:

- ``NCCLHierarchicalAllreduce`` (reference: horovod/common/ops/
  nccl_operations.cc ~200-580, knob HOROVOD_HIERARCHICAL_ALLREDUCE
  common.h:130): node-local ReduceScatter → cross-node allreduce of the
  scattered shards → node-local Allgather.
- ``NCCLTorusAllreduce`` (fork-specific; reference: nccl_operations.cc:606-843,
  knob HOROVOD_TORUS_ALLREDUCE common.h:132): the same 2-level scheme with the
  cross-node leg running per-local-rank on separate communicators — i.e. each
  local shard's cross-node reduction proceeds in parallel.

TPU-native mapping: ``local`` = chips within a slice (ICI), ``cross`` = slices
(DCN). ``psum_scatter(local) → psum(cross) → all_gather(local)`` expresses
exactly the torus schedule, and XLA runs each cross-slice shard reduction in
parallel — the property the fork's custom NCCL code buys — while moving only
1/local_size of the bytes over the slow cross link.
"""

import jax.numpy as jnp
from jax import lax

from horovod_tpu.common.topology import CROSS_AXIS, LOCAL_AXIS


def allreduce_torus(x, cross_axis=CROSS_AXIS, local_axis=LOCAL_AXIS,
                    average=False, flatten=True):
    """2-level allreduce: ICI reduce-scatter, DCN shard allreduce, ICI
    all-gather. Bit-equivalent to a flat allreduce; bandwidth-optimal when the
    cross link is the bottleneck.

    ``x`` is this chip's local value. Requires ``x.size`` divisible by the
    local axis size when ``flatten`` (pads otherwise).
    """
    local_n = lax.axis_size(local_axis)
    orig_shape = x.shape
    flat = x.reshape(-1)
    pad = (-flat.size) % local_n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shard = lax.psum_scatter(flat, local_axis, scatter_dimension=0, tiled=True)
    shard = lax.psum(shard, cross_axis)
    full = lax.all_gather(shard, local_axis, axis=0, tiled=True)
    if pad:
        full = full[:-pad]
    out = full.reshape(orig_shape)
    if average:
        n = local_n * lax.axis_size(cross_axis)
        out = out / jnp.asarray(n, out.dtype)
    return out


def allreduce_hierarchical(x, cross_axis=CROSS_AXIS, local_axis=LOCAL_AXIS,
                           average=False):
    """Hierarchical 2-phase allreduce: full local reduce then cross reduce.
    Moves the whole buffer on the cross link (unlike torus) but needs no
    divisibility; matches NCCLHierarchicalAllreduce's structure."""
    out = lax.psum(lax.psum(x, local_axis), cross_axis)
    if average:
        n = lax.axis_size(local_axis) * lax.axis_size(cross_axis)
        out = out / jnp.asarray(n, out.dtype)
    return out
